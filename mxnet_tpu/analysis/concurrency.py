"""ConcurrencyLinter — static lock/protocol lint for the threaded planes.

The reference's worst production bugs were concurrency bugs, not math
bugs (ps-lite's whole design is surviving flaky peers); our serve and PS
planes now hold dozens of locks, condition variables, and daemon threads
with a request/reply wire between them. This pass is the ``analysis/``
family member that watches that code the way GraphLinter watches graphs:
an AST pass over the repo (the ``repo_lint.py`` driving machinery) that
understands ``with self._lock:`` nesting, condition-variable discipline,
thread lifecycle, and the wire-protocol opcode registries.

Rules (see docs/ANALYSIS.md "Concurrency lint" for the catalog):

- ``lock-order-cycle`` (error) — the per-module lock-acquisition graph
  (nesting + same-class interprocedural propagation) contains a cycle:
  some interleaving deadlocks. The runtime twin is ``mxnet_tpu.tsan``.
- ``blocking-call-under-lock`` (warning) — socket ``recv``/``sendall``/
  ``accept``/``connect``, ``subprocess`` waits, ``time.sleep``,
  ``os.fsync``, jax ``block_until_ready``, wire framing helpers
  (``_send_msg``/``_recv_msg``), or a ``Condition``/``Event`` wait while
  holding a (different) lock — one slow peer wedges every thread queued
  on that lock. Propagates one class deep: calling a same-class method
  that blocks counts as blocking.
- ``cv-wait-no-recheck`` (warning) — ``Condition.wait`` outside a
  ``while``-predicate loop: wakeups are spurious and racy by contract.
- ``join-timeout-unchecked`` (warning) — ``t.join(timeout=...)`` whose
  outcome is never checked (``join`` returns ``None``; only
  ``is_alive()`` reveals a leak) in a function that never consults
  ``is_alive``.
- ``thread-fire-and-forget`` (warning) — the chained
  ``threading.Thread(...).start()`` form: the handle is discarded, so
  the thread can never be joined, supervised, or even named in a stack
  dump.
- ``unbounded-wait`` (warning) — argument-less ``Condition.wait()`` /
  ``Event.wait()`` / ``Thread.join()``: no timeout means a lost wakeup
  is a permanent hang instead of a bounded stall.

Protocol rules (driven by the declarative ``mxnet_tpu.wire`` registries,
shared by the serve and PS planes):

- ``opcode-missing-handler`` (error) — a registered request opcode with
  no dispatch branch in its plane's handler.
- ``opcode-unknown-handler`` (error) — a dispatch branch for a constant
  the registry doesn't know (stale/renumbered op).
- ``opcode-duplicate-handler`` (error) — two branches test the same op.
- ``mutating-op-no-dedup`` (error) — a mutating op whose spec declares
  no exactly-once discipline (``seq``/``token``/``idempotent``/``legacy``).
- ``dedup-machinery-missing`` (error) — the spec declares seq-dedup /
  commit-token / WAL coverage but the handler branch (plus the same-class
  methods it calls) never touches that machinery.
- ``trace-propagation-missing`` (error) — the plane's framed receive
  loop never extracts wire trace context (PR 7's contract).

Waive a deliberate finding with ``# lint: disable=<rule-id>`` on the
offending line (justify nearby); waived findings are still *reported* at
info severity with ``details={"waived": True}`` but never fail the lint.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import Finding, Report, Severity
from .repo_lint import _suppressed

__all__ = ["RULES", "lint_source", "lint_paths", "lint_protocol",
           "check_registry", "unwaived", "main"]

RULES = {
    "lock-order-cycle":
        "lock-acquisition graph has a cycle (deadlockable interleaving)",
    "blocking-call-under-lock":
        "blocking operation (socket/sleep/fsync/device-sync/wait) while "
        "holding a lock",
    "cv-wait-no-recheck":
        "Condition.wait not inside a while-predicate re-check loop",
    "join-timeout-unchecked":
        "join(timeout=...) outcome never checked via is_alive()",
    "thread-fire-and-forget":
        "threading.Thread(...).start() with the handle discarded",
    "unbounded-wait":
        "wait()/join() with no timeout: a lost wakeup hangs forever",
    "opcode-missing-handler":
        "registered opcode has no handler branch",
    "opcode-unknown-handler":
        "handler branch for an unregistered opcode constant",
    "opcode-duplicate-handler":
        "two handler branches test the same opcode",
    "mutating-op-no-dedup":
        "mutating wire op declares no exactly-once discipline",
    "dedup-machinery-missing":
        "declared dedup/WAL machinery absent from the handler branch",
    "trace-propagation-missing":
        "wire receive loop never extracts trace context",
}

# constructor-name -> primitive kind
_LOCK_CTORS = {
    "Lock": "lock", "lock": "lock", "SanLock": "lock",
    "allocate_lock": "lock", "_raw_lock": "lock",
    "RLock": "rlock", "rlock": "rlock", "SanRLock": "rlock",
    "Condition": "condition", "condition": "condition",
    "SanCondition": "condition",
    "Event": "event", "event": "event",
}
_LOCKISH = ("lock", "rlock", "condition")
# attribute names that look like a lock when we cannot resolve the object
# (e.g. ``with self._pool._lock:`` reaching into another class)
_LOCKY_ATTRS = {"_lock", "lock", "_cv", "cv", "_mu", "_cond", "_mutex",
                "_global_lock", "_seq_lock", "_reload_lock"}

# direct blocking operations by attribute name …
_BLOCKING_ATTRS = {"recv", "recv_into", "recvfrom", "sendall", "accept",
                   "connect", "communicate", "fsync", "block_until_ready",
                   "sleep", "select"}
# … and by bare/module function name (the wire framing helpers block on
# the socket; create_connection dials)
_BLOCKING_FUNCS = {"sleep", "fsync", "select", "create_connection",
                   "_send_msg", "_recv_msg", "_recv_exact"}


def _ctor_kind(node) -> Optional[str]:
    """Primitive kind if ``node`` is a Lock/RLock/Condition/Event/tsan
    factory call, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Name):
        name = fn.id
    elif isinstance(fn, ast.Attribute):
        name = fn.attr
    return _LOCK_CTORS.get(name) if name else None


def _is_thread_ctor(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    return (isinstance(fn, ast.Name) and fn.id == "Thread") or \
        (isinstance(fn, ast.Attribute) and fn.attr == "Thread")


class _Scope:
    """Lock/thread identity tables for one class (or the module level)."""

    def __init__(self, name: str):
        self.name = name                       # class name or module base
        self.lock_attrs: Dict[str, str] = {}   # attr -> kind
        self.lockdict_attrs: Set[str] = set()  # attrs holding {key: lock}
        self.thread_attrs: Set[str] = set()


class _FuncInfo:
    """Per-function facts feeding the class-level fixpoint."""

    def __init__(self, qualname: str):
        self.qualname = qualname
        self.acquires: Set[str] = set()            # lock idents acquired
        self.blocks: List[Tuple[str, int]] = []    # (description, line)
        # (callee simple name, held idents at call, line, end_line)
        self.calls: List[Tuple[str, Tuple[str, ...], int, int]] = []
        self.has_is_alive = False


class _FuncWalker:
    """Walks one function body tracking the held-lock stack."""

    def __init__(self, module: "_ModuleLinter", scope: _Scope,
                 func: ast.AST, qualname: str):
        self.m = module
        self.scope = scope
        self.func = func
        self.info = _FuncInfo(qualname)
        self.held: List[Tuple[str, str]] = []   # (ident, kind)
        # per-held-cv: how many While loops opened since it was acquired
        self.loops_since: List[int] = []
        self.locals: Dict[str, Tuple[str, str]] = {}  # var -> (ident, kind)
        self.thread_locals: Set[str] = set()
        self.threadlist_locals: Set[str] = set()

    # -- identity resolution -------------------------------------------
    def _resolve(self, node) -> Optional[Tuple[str, str]]:
        """``(ident, kind)`` for an expression that may denote a lock."""
        if isinstance(node, ast.Name):
            if node.id in self.locals:
                return self.locals[node.id]
            mod = self.m.module_scope
            if node.id in mod.lock_attrs:
                return (f"{mod.name}.{node.id}", mod.lock_attrs[node.id])
            return None
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self":
                if node.attr in self.scope.lock_attrs:
                    return (f"{self.scope.name}.{node.attr}",
                            self.scope.lock_attrs[node.attr])
                return None
            # opaque chain (self._pool._lock, el.cv, ...): only treat as a
            # lock when the final attribute *looks* like one
            if node.attr in _LOCKY_ATTRS:
                try:
                    text = ast.unparse(node)
                except Exception:  # noqa: BLE001 — best-effort label
                    text = node.attr
                kind = "condition" if "cv" in node.attr or "cond" in node.attr \
                    else "lock"
                return (f"{self.scope.name}::{text}", kind)
            return None
        if isinstance(node, ast.Subscript):
            base = node.value
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" \
                    and base.attr in self.scope.lockdict_attrs:
                return (f"{self.scope.name}.{base.attr}[]", "lock")
            return None
        if isinstance(node, ast.Call):
            # self._locks.get(key, default) -> the dict's shared identity
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "get":
                inner = self._resolve_dictish(fn.value)
                if inner is not None:
                    return inner
            return None
        if isinstance(node, ast.IfExp):
            return self._resolve(node.body) or self._resolve(node.orelse)
        return None

    def _resolve_dictish(self, node) -> Optional[Tuple[str, str]]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and node.attr in self.scope.lockdict_attrs:
            return (f"{self.scope.name}.{node.attr}[]", "lock")
        return None

    def _is_threadish(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.thread_locals
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr in self.scope.thread_attrs
        return False

    # -- findings -------------------------------------------------------
    def _finding(self, rule: str, severity: str, msg: str, line: int,
                 fix: str, end_line: Optional[int] = None,
                 **details) -> None:
        self.m.emit(rule, severity, msg, line, fix, end_line=end_line,
                    **details)

    def _edge(self, dst: str, line: int) -> None:
        for src, _kind in self.held:
            if src != dst:
                self.m.add_edge(src, dst, line)

    def _block_op(self, desc: str, line: int, exempt_cv: Optional[str] = None,
                  end_line: Optional[int] = None) -> None:
        """A blocking operation happened here: record it for callers and
        flag it if any lock is held (``exempt_cv``: the CV being waited
        on — waiting releases *that* lock, not the others)."""
        self.info.blocks.append((desc, line))
        held = [h for h, _k in self.held if h != exempt_cv]
        if held:
            self._finding(
                "blocking-call-under-lock", Severity.WARNING,
                f"{desc} while holding {held[-1]!r}: every thread queued "
                "on that lock stalls behind this call", line,
                "move the blocking call outside the critical section, or "
                "waive with '# lint: disable=blocking-call-under-lock' "
                "and a justification",
                end_line=end_line, held=list(held))

    # -- traversal ------------------------------------------------------
    def run(self) -> _FuncInfo:
        for stmt in self.func.body:
            self._visit(stmt, loop_depth=0)
        return self.info

    def _visit(self, node, loop_depth: int) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested defs are analyzed as their own functions
        if isinstance(node, ast.With):
            self._visit_with(node, loop_depth)
            return
        if isinstance(node, (ast.While, ast.For)):
            # a While re-evaluates a predicate; count it for the CV rule
            bump = 1 if isinstance(node, ast.While) else 0
            for i in range(len(self.loops_since)):
                self.loops_since[i] += bump
            for child in ast.iter_child_nodes(node):
                self._visit(child, loop_depth + 1)
            if bump:
                for i in range(len(self.loops_since)):
                    self.loops_since[i] -= bump
            return
        if isinstance(node, ast.Assign):
            self._visit_assign(node)
        if isinstance(node, ast.Call):
            self._visit_call(node, loop_depth)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            self._visit(child, loop_depth)

    def _visit_with(self, node: ast.With, loop_depth: int) -> None:
        pushed = 0
        for item in node.items:
            got = self._resolve(item.context_expr)
            if got is not None and got[1] in _LOCKISH:
                ident, kind = got
                self._edge(ident, node.lineno)
                self.info.acquires.add(ident)
                self.held.append((ident, kind))
                self.loops_since.append(0)
                pushed += 1
            elif isinstance(item.context_expr, ast.Call):
                # `with self._conn(m) as cli:` — still a call under the
                # current held set
                self._visit_call(item.context_expr, loop_depth)
        try:
            for stmt in node.body:
                self._visit(stmt, loop_depth)
        finally:
            for _ in range(pushed):
                self.held.pop()
                self.loops_since.pop()

    def _visit_assign(self, node: ast.Assign) -> None:
        kind = _ctor_kind(node.value)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if kind is not None:
                    self.locals[tgt.id] = (
                        f"{self.info.qualname}.{tgt.id}", kind)
                elif _is_thread_ctor(node.value):
                    self.thread_locals.add(tgt.id)
                elif isinstance(node.value, ast.ListComp) \
                        and _is_thread_ctor(node.value.elt):
                    self.threadlist_locals.add(tgt.id)
                else:
                    resolved = self._resolve(node.value)
                    if resolved is not None:
                        self.locals[tgt.id] = resolved

    def _visit_call(self, node: ast.Call, loop_depth: int) -> None:
        fn = node.func
        line = node.lineno
        has_timeout = bool(node.args) or any(
            kw.arg == "timeout" for kw in node.keywords)

        if isinstance(fn, ast.Name):
            if fn.id in _BLOCKING_FUNCS:
                self._block_op(f"{fn.id}()", line)
            if fn.id == "is_alive":
                self.info.has_is_alive = True
            # bare call to a module-level function in this file
            if fn.id in self.m.module_funcs:
                self.info.calls.append(
                    (fn.id, tuple(h for h, _k in self.held), line,
                     getattr(node, "end_lineno", line) or line))
            return

        if not isinstance(fn, ast.Attribute):
            return
        attr = fn.attr
        if attr == "is_alive":
            self.info.has_is_alive = True
            return
        if attr == "wait":
            self._visit_wait(node, fn, has_timeout, loop_depth)
            return
        if attr == "join":
            self._visit_join(node, fn, has_timeout)
            return
        if attr == "start" and _is_thread_ctor(fn.value):
            self._finding(
                "thread-fire-and-forget", Severity.WARNING,
                "Thread(...).start() discards the handle: the thread "
                "can never be joined, supervised, or attributed in a "
                "stack dump", line,
                "keep the handle (join it on shutdown), or waive with "
                "'# lint: disable=thread-fire-and-forget' stating who "
                "supervises it",
                end_line=getattr(node, "end_lineno", None))
            return
        if attr in _BLOCKING_ATTRS:
            self._block_op(f".{attr}()", line)
            return
        # same-class method call: feeds interprocedural propagation
        if isinstance(fn.value, ast.Name) and fn.value.id == "self":
            self.info.calls.append(
                (attr, tuple(h for h, _k in self.held), line,
                 getattr(node, "end_lineno", line) or line))

    def _visit_wait(self, node: ast.Call, fn: ast.Attribute,
                    has_timeout: bool, loop_depth: int) -> None:
        line = node.lineno
        target = self._resolve(fn.value)
        if target is not None and target[1] == "condition":
            ident = target[0]
            held_idents = [h for h, _k in self.held]
            if ident in held_idents:
                # waiting on the CV we hold: releases it. Check the
                # predicate-loop discipline …
                idx = held_idents.index(ident)
                if self.loops_since[idx] == 0:
                    if not _suppressed(self.m.lines, line,
                                       "cv-wait-no-recheck"):
                        self._finding(
                            "cv-wait-no-recheck", Severity.WARNING,
                            f"Condition.wait on {ident!r} outside a while-"
                            "predicate loop: wakeups are spurious and racy "
                            "by contract", line,
                            "wrap the wait in 'while not <predicate>:'")
                    else:
                        self.m.emit_waived("cv-wait-no-recheck", line)
                # … and whether any OTHER lock stays held across the wait
                self._block_op(f"Condition.wait on {ident}", line,
                               exempt_cv=ident)
            else:
                self._block_op(f"Condition.wait on {ident}", line)
            if not has_timeout:
                self._unbounded(f"Condition.wait() on {ident!r}", line)
        elif target is not None and target[1] == "event":
            self._block_op(f"Event.wait on {target[0]}", line)
            if not has_timeout:
                self._unbounded(f"Event.wait() on {target[0]!r}", line)
        else:
            # unknown receiver (subprocess handle, queue, foreign object):
            # only the under-lock hazard is knowable
            if self.held:
                self._block_op(".wait()", line)

    def _visit_join(self, node: ast.Call, fn: ast.Attribute,
                    has_timeout: bool) -> None:
        line = node.lineno
        # only receivers provably threads count — `"".join`, `os.path.join`
        # and queue.join must not trip thread rules
        threadish = self._is_threadish(fn.value) or (
            isinstance(fn.value, ast.Name)
            and (fn.value.id in self.threadlist_locals
                 or fn.value.id in self.m.loopvar_threads.get(
                     self.info.qualname, set())))
        if not threadish:
            return
        timeout_kw = any(kw.arg == "timeout" for kw in node.keywords) \
            or bool(node.args)
        self._block_op("Thread.join()", line)
        if not timeout_kw:
            self._unbounded("Thread.join() with no timeout", line)
        else:
            if not _suppressed(self.m.lines, line, "join-timeout-unchecked"):
                self.m.pending_joins.append(
                    (self.info.qualname, line, self))
            else:
                self.m.emit_waived("join-timeout-unchecked", line)

    def _unbounded(self, what: str, line: int) -> None:
        if not _suppressed(self.m.lines, line, "unbounded-wait"):
            self._finding(
                "unbounded-wait", Severity.WARNING,
                f"{what}: a lost wakeup or dead peer hangs this thread "
                "forever", line,
                "pass a timeout and handle expiry (re-check / give up / "
                "escalate)")
        else:
            self.m.emit_waived("unbounded-wait", line)


class _ModuleLinter:
    """One file: identity collection, per-function walks, class-level
    interprocedural fixpoint."""

    def __init__(self, src: str, filename: str):
        self.filename = filename
        self.lines = src.splitlines()
        self.findings: List[Finding] = []
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.tree: Optional[ast.AST] = None
        self.module_scope = _Scope(
            os.path.splitext(os.path.basename(filename))[0])
        self.module_funcs: Set[str] = set()
        self.pending_joins: List[Tuple[str, int, _FuncWalker]] = []
        # qualname -> loop vars known to iterate thread lists
        self.loopvar_threads: Dict[str, Set[str]] = {}
        try:
            self.tree = ast.parse(src, filename=filename)
        except SyntaxError as e:
            self.findings.append(Finding(
                "syntax-error", Severity.ERROR, str(e),
                location=f"{filename}:{e.lineno or 0}"))

    # -- emit helpers ---------------------------------------------------
    def emit(self, rule: str, severity: str, msg: str, line: int,
             fix: str, end_line: Optional[int] = None, **details) -> None:
        # a multi-line statement's waiver may sit on any of its lines
        for ln in range(line, (end_line or line) + 1):
            if _suppressed(self.lines, ln, rule):
                self.emit_waived(rule, line)
                return
        self.findings.append(Finding(
            rule, severity, msg, fix_hint=fix,
            location=f"{self.filename}:{line}", details=details or {}))

    def emit_waived(self, rule: str, line: int) -> None:
        self.findings.append(Finding(
            rule, Severity.INFO, "waived in source (lint: disable)",
            location=f"{self.filename}:{line}", details={"waived": True}))

    def add_edge(self, src: str, dst: str, line: int) -> None:
        if _suppressed(self.lines, line, "lock-order-cycle"):
            return
        self.edges.setdefault((src, dst), (self.filename, line))

    # -- analysis -------------------------------------------------------
    def run(self) -> None:
        if self.tree is None:
            return
        classes: List[Tuple[_Scope, List[ast.AST]]] = []
        module_fns: List[ast.AST] = []
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                scope = _Scope(node.name)
                methods = [n for n in node.body
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
                self._collect_attrs(scope, methods)
                classes.append((scope, methods))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                module_fns.append(node)
                self.module_funcs.add(node.name)
            elif isinstance(node, ast.Assign):
                kind = _ctor_kind(node.value)
                if kind is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.module_scope.lock_attrs[tgt.id] = kind

        # module-level functions share a pseudo-scope for self-free lint
        groups: List[Tuple[_Scope, List[ast.AST]]] = list(classes)
        if module_fns:
            groups.append((self.module_scope, module_fns))

        for scope, fns in groups:
            infos: Dict[str, _FuncInfo] = {}
            for fn in fns:
                for sub, qual in self._with_nested(fn, scope.name):
                    self._prescan_thread_loops(sub, qual)
                    infos[qual.split(".")[-1]] = _FuncWalker(
                        self, scope, sub, qual).run()
            self._propagate(scope, infos)

        # join-timeout-unchecked resolves after the walk (needs the whole
        # function's is_alive verdict)
        for qual, line, walker in self.pending_joins:
            if walker.info.has_is_alive:
                continue
            self.emit(
                "join-timeout-unchecked", Severity.WARNING,
                "join(timeout=...) returns None either way; without an "
                "is_alive() check a leaked thread goes unnoticed", line,
                "check t.is_alive() after the join (log/count the leak), "
                "or waive with '# lint: disable=join-timeout-unchecked'")

    def _with_nested(self, fn, prefix: str):
        qual = f"{prefix}.{fn.name}"
        yield fn, qual
        for node in ast.walk(fn):
            if node is not fn and isinstance(node, (ast.FunctionDef,
                                                    ast.AsyncFunctionDef)):
                yield node, f"{qual}.{node.name}"

    def _prescan_thread_loops(self, fn, qual: str) -> None:
        """``for t in threads: t.join(...)`` — learn which loop vars range
        over lists of Thread objects, built either as a listcomp of Thread
        ctors or by appending Thread locals."""
        thread_locals: Set[str] = set()
        thread_lists: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    if _is_thread_ctor(node.value):
                        thread_locals.add(tgt.id)
                    elif isinstance(node.value, ast.ListComp) \
                            and _is_thread_ctor(node.value.elt):
                        thread_lists.add(tgt.id)
        # appends of thread locals into a list also make it a thread list
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "append" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.args and (
                        _is_thread_ctor(node.args[0])
                        or (isinstance(node.args[0], ast.Name)
                            and node.args[0].id in thread_locals)):
                thread_lists.add(node.func.value.id)
        loopvars: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.For) and isinstance(node.iter, ast.Name) \
                    and isinstance(node.target, ast.Name) \
                    and node.iter.id in thread_lists:
                loopvars.add(node.target.id)
        self.loopvar_threads[qual] = loopvars

    def _collect_attrs(self, scope: _Scope, methods) -> None:
        for m in methods:
            for node in ast.walk(m):
                if not isinstance(node, ast.Assign):
                    continue
                kind = _ctor_kind(node.value)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self":
                        if kind is not None:
                            scope.lock_attrs[tgt.attr] = kind
                        elif _is_thread_ctor(node.value):
                            scope.thread_attrs.add(tgt.attr)
                    elif isinstance(tgt, ast.Subscript) and kind is not None:
                        base = tgt.value
                        if isinstance(base, ast.Attribute) \
                                and isinstance(base.value, ast.Name) \
                                and base.value.id == "self":
                            scope.lockdict_attrs.add(base.attr)

    def _propagate(self, scope: _Scope, infos: Dict[str, _FuncInfo]) -> None:
        """Fixpoint: a method's may-acquire/may-block includes its
        same-class callees'. Then call sites under held locks contribute
        edges and blocking findings."""
        may_acquire = {n: set(i.acquires) for n, i in infos.items()}
        may_block = {n: list(i.blocks) for n, i in infos.items()}
        changed = True
        while changed:
            changed = False
            for n, info in infos.items():
                for callee, _held, _line, _end in info.calls:
                    if callee not in infos:
                        continue
                    before = len(may_acquire[n])
                    may_acquire[n] |= may_acquire[callee]
                    if len(may_acquire[n]) != before:
                        changed = True
                    if may_block[callee] and not may_block[n]:
                        may_block[n] = [
                            (f"{callee}() → {may_block[callee][0][0]}",
                             _line)]
                        changed = True
        for n, info in infos.items():
            for callee, held, line, end in info.calls:
                if callee not in infos or not held:
                    continue
                for ident in may_acquire[callee]:
                    if ident in held:
                        continue
                    for h in held:
                        if h != ident:
                            self.add_edge(h, ident, line)
                if may_block[callee]:
                    desc, _bl = may_block[callee][0]
                    self.emit(
                        "blocking-call-under-lock", Severity.WARNING,
                        f"self.{callee}() blocks ({desc}) and is called "
                        f"while holding {held[-1]!r}", line,
                        "restructure so the blocking work happens outside "
                        "the lock, or waive with a justification",
                        end_line=end, held=list(held), via=callee)


# ---------------------------------------------------------------------------
# cycle detection over the merged acquisition graph
# ---------------------------------------------------------------------------

def _cycle_findings(edges: Dict[Tuple[str, str], Tuple[str, int]]
                    ) -> List[Finding]:
    graph: Dict[str, Set[str]] = {}
    for (src, dst) in edges:
        graph.setdefault(src, set()).add(dst)
        graph.setdefault(dst, set())

    # Tarjan SCC, iterative
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(graph[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for node in sorted(graph):
        if node not in index:
            strongconnect(node)

    out = []
    for comp in sccs:
        comp_set = set(comp)
        cyc_edges = sorted(
            (s, d) for (s, d) in edges
            if s in comp_set and d in comp_set)
        locs = {f"{s}->{d}": f"{edges[(s, d)][0]}:{edges[(s, d)][1]}"
                for s, d in cyc_edges}
        first = edges[cyc_edges[0]]
        out.append(Finding(
            "lock-order-cycle", Severity.ERROR,
            "lock-acquisition cycle over {" + ", ".join(comp) + "}: some "
            "thread interleaving deadlocks",
            location=f"{first[0]}:{first[1]}",
            fix_hint="pick one global order for these locks and acquire "
                     "them in it everywhere (or collapse them into one)",
            details={"locks": comp, "edges": locs}))
    return out


# ---------------------------------------------------------------------------
# protocol pass (reads mxnet_tpu.wire, cross-checks handler ASTs)
# ---------------------------------------------------------------------------

_DEDUP_EVIDENCE = {
    "seq": {"_applied_seq", "_record_seq"},
    "token": {"_committed_tokens", "_telemetry_tokens"},
}
_WAL_EVIDENCE = {"_wal"}


def check_registry(reg) -> List[Finding]:
    """Data-level invariants of one :class:`~mxnet_tpu.wire.WireRegistry`."""
    from .. import wire

    out = []
    for op in reg:
        if op.mutating and op.dedup not in wire.DEDUP_KINDS:
            out.append(Finding(
                "mutating-op-no-dedup", Severity.ERROR,
                f"{reg.plane}:{op.name} (code {op.code}) mutates state but "
                f"declares no exactly-once discipline (dedup={op.dedup!r})",
                node=f"{reg.plane}:{op.name}",
                fix_hint="declare dedup='seq'|'token'|'idempotent' (or "
                         "'legacy' for a documented at-least-once op)"))
    return out


def _branch_table(dispatch_fn: ast.AST):
    """``[(const_name, test_line, body)]`` from ``opcode == OP_X``
    dispatch branches."""
    out = []
    for node in ast.walk(dispatch_fn):
        if not isinstance(node, ast.If):
            continue
        t = node.test
        if isinstance(t, ast.Compare) and len(t.ops) == 1 \
                and isinstance(t.ops[0], ast.Eq) \
                and isinstance(t.left, ast.Name) and t.left.id == "opcode" \
                and isinstance(t.comparators[0], ast.Name):
            out.append((t.comparators[0].id, t.lineno, node.body))
    return out


def _find_func(tree: ast.AST, name: str):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _names_in(nodes) -> Set[str]:
    seen: Set[str] = set()
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Attribute):
                seen.add(sub.attr)
            elif isinstance(sub, ast.Name):
                seen.add(sub.id)
    return seen


def check_handlers(reg, src: str, filename: str) -> List[Finding]:
    """Cross-check one registry against its handler module's source."""
    out = list(check_registry(reg))
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError:
        return out  # the per-file lint already reported it
    loop_fn = _find_func(tree, reg.loop_fn)
    # the tracing contract is loop-level (context is stripped before
    # dispatch); it is required iff any op in the registry declares it
    if loop_fn is not None and any(op.traced for op in reg):
        calls = {n.func.attr if isinstance(n.func, ast.Attribute)
                 else getattr(n.func, "id", None)
                 for n in ast.walk(loop_fn) if isinstance(n, ast.Call)}
        if "extract_key" not in calls:
            out.append(Finding(
                "trace-propagation-missing", Severity.ERROR,
                f"{reg.plane} receive loop {reg.loop_fn!r} never extracts "
                "wire trace context: this plane's spans fall out of the "
                "merged timeline",
                location=f"{filename}:{loop_fn.lineno}",
                fix_hint="strip context first: key, wctx = "
                         "obs_context.extract_key(key)"))
    dispatch = _find_func(tree, reg.dispatch_fn)
    if dispatch is None:
        out.append(Finding(
            "opcode-missing-handler", Severity.ERROR,
            f"{reg.plane}: dispatch function {reg.dispatch_fn!r} not found "
            f"in {filename}",
            location=f"{filename}:1",
            fix_hint="keep the registry's handler metadata in sync"))
        return out
    const_map = reg.by_const()
    seen: Dict[str, int] = {}
    bodies: Dict[str, list] = {}
    for const, line, body in _branch_table(dispatch):
        if const not in const_map:
            out.append(Finding(
                "opcode-unknown-handler", Severity.ERROR,
                f"{reg.plane}: handler branch tests {const}, which is not "
                "a registered opcode",
                location=f"{filename}:{line}",
                fix_hint="register the op in mxnet_tpu/wire.py or delete "
                         "the stale branch"))
            continue
        if const in seen:
            out.append(Finding(
                "opcode-duplicate-handler", Severity.ERROR,
                f"{reg.plane}: second handler branch for {const} (first at "
                f"line {seen[const]}): one of them is dead",
                location=f"{filename}:{line}",
                fix_hint="exactly one dispatch branch per opcode"))
            continue
        seen[const] = line
        bodies[const] = body
    # same-class one-level call follow for machinery evidence
    class_methods: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    class_methods.setdefault(m.name, m)
    for op in reg:
        const = op.const_name
        if op.direction != "request":
            continue
        if const not in seen:
            out.append(Finding(
                "opcode-missing-handler", Severity.ERROR,
                f"{reg.plane}:{op.name} (code {op.code}) has no "
                f"'opcode == {const}' branch in {reg.dispatch_fn}",
                location=f"{filename}:{dispatch.lineno}",
                fix_hint="add the dispatch branch (or retire the op from "
                         "the registry)"))
            continue
        needed: Set[str] = set()
        if op.dedup in _DEDUP_EVIDENCE:
            needed |= _DEDUP_EVIDENCE[op.dedup]
        if op.wal:
            needed |= _WAL_EVIDENCE
        if not needed:
            continue
        scan_nodes = list(bodies[const])
        for n in bodies[const]:
            for sub in ast.walk(n):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id == "self" \
                        and sub.func.attr in class_methods:
                    scan_nodes.append(class_methods[sub.func.attr])
        present = _names_in(scan_nodes)
        # seq/token evidence: ANY name of the kind's set suffices; wal
        # evidence is its own set
        missing: Set[str] = set()
        if op.dedup in _DEDUP_EVIDENCE \
                and not (present & _DEDUP_EVIDENCE[op.dedup]):
            missing |= _DEDUP_EVIDENCE[op.dedup]
        if op.wal and not (present & _WAL_EVIDENCE):
            missing |= _WAL_EVIDENCE
        if missing:
            out.append(Finding(
                "dedup-machinery-missing", Severity.ERROR,
                f"{reg.plane}:{op.name} declares "
                f"dedup={op.dedup!r}/wal={op.wal} but its handler branch "
                f"never touches {sorted(missing)}",
                location=f"{filename}:{seen[const]}",
                fix_hint="apply the declared exactly-once machinery in "
                         "the branch, or correct the OpSpec"))
    return out


def lint_protocol(files: Dict[str, str]) -> List[Finding]:
    """Run the protocol pass for every registry whose handler module is in
    ``files`` (``{path: source}``)."""
    from .. import wire

    out: List[Finding] = []
    for reg in (wire.PS_WIRE, wire.SERVE_WIRE):
        suffix = reg.handler_path.replace("/", os.sep)
        match = next((p for p in files
                      if os.path.normpath(p).endswith(suffix)), None)
        if match is None:
            continue
        out.extend(check_handlers(reg, files[match], match))
    return out


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------

def unwaived(report: Report) -> List[Finding]:
    return [f for f in report if not f.details.get("waived")]


def lint_source(src: str, filename: str = "<string>") -> List[Finding]:
    """Single-file lint (rule unit tests): per-file rules + a per-file
    cycle detection. Protocol checks need the real tree — see
    :func:`lint_paths`."""
    m = _ModuleLinter(src, filename)
    m.run()
    return m.findings + _cycle_findings(m.edges)


def lint_paths(paths: Iterable[str], exclude: Iterable[str] = ()) -> Report:
    """Repo lint: per-file rules, a GLOBAL lock-order graph (cycles may
    span modules when identities are shared), and the wire-protocol pass
    when a plane's handler module is in scope."""
    report = Report()
    exclude = tuple(exclude)
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    sources: Dict[str, str] = {}
    files: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            files.append(path)
        else:
            for root, _dirs, names in os.walk(path):
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(root, n))
    for f in sorted(files):
        if any(x in f for x in exclude):
            continue
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        sources[f] = src
        m = _ModuleLinter(src, f)
        m.run()
        report.extend(m.findings)
        for k, v in m.edges.items():
            edges.setdefault(k, v)
    report.extend(_cycle_findings(edges))
    report.extend(lint_protocol(sources))
    return report


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis concurrency",
        description="Concurrency-correctness lint: lock-order cycles, "
                    "blocking-under-lock, CV/thread discipline, and the "
                    "wire-protocol registry checks.")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: mxnet_tpu)")
    ap.add_argument("--exclude", action="append", default=[],
                    help="path substring to skip")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog")
    ap.add_argument("--no-waived", action="store_true",
                    help="hide waived findings from the report")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}: {desc}")
        return 0

    report = lint_paths(args.paths or ["mxnet_tpu"], exclude=args.exclude)
    shown = Report(unwaived(report)) if args.no_waived else report
    print(shown.to_json() if args.json else shown.format())
    bad = unwaived(report)
    if bad:
        print(f"\n{len(bad)} unwaived finding(s) "
              f"({len(report) - len(bad)} waived)")
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
