"""Uniform graph view for the analyzers.

``GraphView`` adapts both an in-memory :class:`~mxnet_tpu.symbol.Symbol`
DAG and a serialized ``tojson()`` graph (the CLI path) to one node-table
shape, so every pass is written once. JSON views keep *all* nodes from the
file — including ones unreachable from the heads — which is what the
dead-node pass inspects; ``Symbol._topo`` views are reachable-only by
construction.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["NodeInfo", "GraphView"]


@dataclass
class NodeInfo:
    idx: int
    op: Optional[str]               # None => variable ("null" in JSON)
    name: str
    attrs: Dict[str, Any] = field(default_factory=dict)
    inputs: List[Tuple[int, int]] = field(default_factory=list)
    sym: Any = None                 # backing Symbol node, when available

    @property
    def is_variable(self) -> bool:
        return self.op is None

    def kwargs(self) -> Dict[str, Any]:
        """Op kwargs: non-dunder attrs, string values coerced."""
        from ..ops.registry import coerce_kwargs

        return coerce_kwargs({k: v for k, v in self.attrs.items()
                              if not k.startswith("__")})


class GraphView:
    """Node table + consumer index over a Symbol or JSON graph."""

    def __init__(self, nodes: List[NodeInfo], heads: List[Tuple[int, int]],
                 symbol=None):
        self.nodes = nodes
        self.heads = heads
        self.symbol = symbol
        self.consumers: Dict[int, List[Tuple[int, int]]] = {n.idx: []
                                                            for n in nodes}
        for n in nodes:
            for pos, (src, _out) in enumerate(n.inputs):
                self.consumers[src].append((n.idx, pos))

    # ------------------------------------------------------------------
    @classmethod
    def from_symbol(cls, sym) -> "GraphView":
        topo = sym._topo()
        idx = {id(n): i for i, n in enumerate(topo)}
        nodes = []
        for i, n in enumerate(topo):
            ins = [(idx[id(s._base())], s._index or 0) for s in n._inputs]
            nodes.append(NodeInfo(i, n._op, n._name, dict(n._attrs), ins,
                                  sym=n))
        if sym._op == "_group":
            heads = [(idx[id(s._base())], s._index or 0) for s in sym._inputs]
        else:
            heads = [(idx[id(sym._base())], sym._index or 0)]
        return cls(nodes, heads, symbol=sym)

    @classmethod
    def from_json(cls, graph) -> "GraphView":
        if isinstance(graph, str):
            graph = json.loads(graph)
        nodes = []
        for i, nd in enumerate(graph["nodes"]):
            op = None if nd["op"] == "null" else nd["op"]
            ins = [(inp[0], inp[1] if len(inp) > 1 else 0)
                   for inp in nd.get("inputs", [])]
            attrs = dict(nd.get("attrs", nd.get("param", {})))
            nodes.append(NodeInfo(i, op, nd["name"], attrs, ins))
        heads = [(h[0], h[1] if len(h) > 1 else 0)
                 for h in graph.get("heads", [])]
        symbol = None
        try:  # reachable subgraph as a live Symbol (for shape passes)
            from ..symbol.symbol import load_json

            symbol = load_json(json.dumps(graph))
        except Exception:
            symbol = None  # e.g. unknown ops; the registry pass reports them
        return cls(nodes, heads, symbol=symbol)

    # ------------------------------------------------------------------
    def reachable(self) -> set:
        """Node indices reachable from the heads (the live graph)."""
        seen: set = set()
        stack = [h for h, _ in self.heads]
        while stack:
            i = stack.pop()
            if i in seen:
                continue
            seen.add(i)
            stack.extend(src for src, _ in self.nodes[i].inputs)
        return seen

    def variables(self) -> List[NodeInfo]:
        return [n for n in self.nodes if n.is_variable]

    def op_nodes(self) -> List[NodeInfo]:
        return [n for n in self.nodes if n.op is not None
                and n.op != "_group"]

    def head_indices(self) -> set:
        return {h for h, _ in self.heads}
