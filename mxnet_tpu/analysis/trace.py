"""TraceLinter — jit-trace hygiene checks for HybridBlocks.

Three classes of silent perf/correctness bugs the tracer can't flag itself:

- ``retrace-churn``: every distinct (shapes, dtypes, train-mode) signature
  recompiles the CachedOp; a loop feeding ragged shapes compiles forever.
- ``concretization-leak``: ``float()``/``bool()``/``.asnumpy()`` on a traced
  value either crashes under jit or silently forces a host sync per step.
- ``weak-dtype-promotion``: mixed param/input float dtypes promote inside
  the trace, upcasting the whole model (bf16 params + fp32 inputs run fp32).

Usage::

    report = TraceLinter().lint(net, example_x)      # static + cache checks
    with TraceLinter().watch(net) as tl:             # observe a train loop
        for batch in loader: net(batch)
    report = tl.report()
"""
from __future__ import annotations

import ast
import contextlib
import inspect
import textwrap
from typing import List, Optional

from .findings import Finding, Report, Severity

__all__ = ["TraceLinter"]

# host-sync call forms flagged inside hybrid_forward/forward bodies
_HOST_BUILTINS = {"float", "bool"}
_HOST_NP_FUNCS = {"asarray", "array"}
_HOST_METHODS = {"asnumpy", "item", "tolist"}
_NP_MODULES = {"np", "numpy", "_np", "onp"}


def _is_constant(node) -> bool:
    return isinstance(node, (ast.Constant, ast.Num, ast.Str)) or \
        (isinstance(node, ast.UnaryOp) and _is_constant(node.operand))


class _HostCallScanner(ast.NodeVisitor):
    def __init__(self, filename: str, lineno_base: int):
        self.filename = filename
        self.lineno_base = lineno_base
        self.findings: List[Finding] = []

    def _flag(self, node, what):
        line = self.lineno_base + node.lineno - 1
        self.findings.append(Finding(
            "concretization-leak", Severity.WARNING,
            f"{what} inside a traced forward: crashes under hybridize/jit "
            "(ConcretizationTypeError) or forces a device->host sync every "
            "call when eager",
            location=f"{self.filename}:{line}",
            fix_hint="keep the math in the graph (use ops / lax.cond), or "
                     "compute it outside forward"))

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _HOST_BUILTINS \
                and node.args and not _is_constant(node.args[0]):
            self._flag(node, f"{fn.id}(...)")
        elif isinstance(fn, ast.Attribute):
            if fn.attr in _HOST_METHODS and not node.args:
                self._flag(node, f".{fn.attr}()")
            elif fn.attr in _HOST_NP_FUNCS \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id in _NP_MODULES \
                    and node.args and not _is_constant(node.args[0]):
                self._flag(node, f"{fn.value.id}.{fn.attr}(...)")
        self.generic_visit(node)


class TraceLinter:
    """Static + cache-observing lint for HybridBlock tracing hygiene."""

    def __init__(self, retrace_threshold: int = 3, **options):
        self.retrace_threshold = int(retrace_threshold)
        self.options = options
        self._watch_baseline = None
        self._watched = None

    # ------------------------------------------------------------- static
    def scan_source(self, block) -> List[Finding]:
        """AST scan of every distinct forward/hybrid_forward in the tree."""
        findings: List[Finding] = []
        seen_fns = set()
        for blk in self._walk_blocks(block):
            for meth_name in ("hybrid_forward", "forward"):
                meth = getattr(type(blk), meth_name, None)
                if meth is None or meth in seen_fns:
                    continue
                seen_fns.add(meth)
                if getattr(meth, "__module__", "").startswith(
                        "mxnet_tpu.gluon.block"):
                    continue  # framework dispatch glue, not user math
                try:
                    src = textwrap.dedent(inspect.getsource(meth))
                    fname = inspect.getsourcefile(meth) or "<unknown>"
                    base = inspect.getsourcelines(meth)[1]
                except (OSError, TypeError):
                    continue
                try:
                    tree = ast.parse(src)
                except SyntaxError:
                    continue
                scanner = _HostCallScanner(fname, base)
                scanner.visit(tree)
                findings.extend(scanner.findings)
        return findings

    @staticmethod
    def _walk_blocks(block):
        yield block
        for c in getattr(block, "_children", {}).values():
            yield from TraceLinter._walk_blocks(c)

    # ------------------------------------------------------------- dtypes
    def check_dtypes(self, block, *example_inputs) -> List[Finding]:
        import numpy as np

        findings: List[Finding] = []
        param_dts = set()
        for p in getattr(block, "_iter_params", lambda: ())():
            if p._data is not None:
                param_dts.add(np.dtype(p.data().dtype))
        float_params = {d for d in param_dts if d.kind == "f" or
                        "bfloat" in d.name}
        for i, x in enumerate(example_inputs):
            dt = np.dtype(getattr(x, "dtype", np.float32))
            if (dt.kind == "f" or "bfloat" in dt.name) and float_params \
                    and dt not in float_params:
                pd = ", ".join(sorted(d.name for d in float_params))
                findings.append(Finding(
                    "weak-dtype-promotion", Severity.WARNING,
                    f"input #{i} is {dt.name} but parameters are {pd}; "
                    "promotion inside the trace silently runs the model at "
                    "the wider dtype (and retraces per input dtype)",
                    node=f"input#{i}",
                    fix_hint="cast inputs to the parameter dtype (or use "
                             "amp/cast policy) before the traced call"))
        return findings

    # -------------------------------------------------------------- cache
    @staticmethod
    def _cache_keys(block):
        keys = []
        for blk in TraceLinter._walk_blocks(block):
            op = getattr(blk, "_cached_op", None)
            if op is not None:
                keys.extend(op._cache.keys())
        return keys

    def check_cache(self, block, baseline: int = 0) -> List[Finding]:
        keys = self._cache_keys(block)
        n_new = len(keys) - baseline
        findings: List[Finding] = []
        if n_new <= self.retrace_threshold:
            return findings
        # diagnose which signature component varies
        by_train = {}
        for train, pav, iav in keys:
            by_train.setdefault(train, []).append((pav, iav))
        shapes = {tuple(s for s, _ in iav) for _t, _p, iav in keys}
        dtypes = {tuple(d for _, d in iav) for _t, _p, iav in keys}
        varying = []
        if len(shapes) > 1:
            varying.append(f"input shapes ({len(shapes)} distinct)")
        if len(dtypes) > 1:
            varying.append(f"input dtypes ({len(dtypes)} distinct)")
        if len(by_train) > 1:
            varying.append("train/eval mode (expected, costs one retrace)")
        sample = ", ".join(str(s) for s in list(shapes)[:3])
        findings.append(Finding(
            "retrace-churn", Severity.WARNING,
            f"{n_new} distinct jit signatures compiled (threshold "
            f"{self.retrace_threshold}); varying: "
            f"{'; '.join(varying) or 'unknown'}; e.g. shapes {sample}",
            node=getattr(block, "name", None),
            fix_hint="bucket/pad inputs to a fixed set of shapes and cast "
                     "to one dtype so compiled programs are reused"))
        return findings

    # ------------------------------------------------- fused update engine
    @staticmethod
    def _engines_of(obj):
        """Yield FusedUpdateEngine instances reachable from a Trainer,
        Updater, Module, or a bare engine."""
        if hasattr(obj, "compile_log") and hasattr(obj, "apply"):
            yield obj  # already an engine
            return
        updaters = []
        if hasattr(obj, "_updaters"):  # gluon Trainer
            updaters.extend(obj._updaters)
        if hasattr(obj, "_updater") and obj._updater is not None:  # Module
            updaters.append(obj._updater)
        if hasattr(obj, "states") and hasattr(obj, "optimizer"):  # Updater
            updaters.append(obj)
        for u in updaters:
            eng = getattr(u, "_engine", None)
            if eng is not None:
                yield eng

    def check_update_engine(self, obj, baseline: int = 0) -> List[Finding]:
        """Flag a training loop that keeps recompiling the fused update
        program.  Per-step scalars (lr after scheduler, wd, loss scale,
        update counts) are traced arguments by design — churn means a
        *static* component varies per step: a mutated hyperparameter
        (e.g. ``optimizer.momentum`` rewritten from a python float each
        iteration), ragged parameter shapes, or a flapping scaler/clip
        toggle."""
        findings: List[Finding] = []
        for eng in self._engines_of(obj):
            log = eng.compile_log[baseline:]
            if len(log) <= self.retrace_threshold:
                continue
            varying = []
            for field, label in (("static", "static hyperparameters"),
                                 ("avals", "parameter shapes/dtypes"),
                                 ("state_structure", "optimizer state structure"),
                                 ("flags", "loss-scaler/clip toggles"),
                                 ("optimizer", "optimizer class")):
                distinct = {repr(e.get(field)) for e in log}
                if len(distinct) > 1:
                    varying.append(f"{label} ({len(distinct)} distinct)")
            findings.append(Finding(
                "update-retrace-churn", Severity.WARNING,
                f"the fused update program recompiled {len(log)} times "
                f"(threshold {self.retrace_threshold}); varying: "
                f"{'; '.join(varying) or 'unknown'}. Each recompile stalls "
                "a training step on XLA compilation",
                node=type(eng.optimizer).__name__,
                fix_hint="don't rebind static optimizer hyperparameters per "
                         "step — per-step values (lr/wd/scale) are already "
                         "traced arguments; use set_learning_rate or an "
                         "lr_scheduler instead of mutating e.g. momentum, "
                         "and keep parameter shapes fixed"))
        return findings

    # ---------------------------------------------------- serving engine
    def check_serve_engine(self, engine, baseline: int = 0) -> List[Finding]:
        """Prove the serving engine's compiled-program bound
        (``serve/engine.py``): every ``compile_log`` entry must carry a
        distinct input signature (a repeated key means jax retraced a
        program the engine believed cached), and the distinct-signature
        count must not exceed buckets × feature signatures — more means
        bucketing is leaking ragged shapes straight to the compiler.
        An empty finding list IS the proof tests assert on."""
        findings: List[Finding] = []
        log = engine.compile_log[baseline:]
        if not log:
            return findings
        sigs = [e["sig"] for e in log]
        dupes = {repr(s) for s in sigs if sigs.count(s) > 1}
        if dupes:
            findings.append(Finding(
                "serve-retrace-churn", Severity.ERROR,
                f"{len(dupes)} input signature(s) compiled more than once "
                f"(e.g. {sorted(dupes)[0][:120]}); the per-signature "
                "program cache is being bypassed",
                node=type(engine).__name__,
                fix_hint="keep parameter avals stable across reload() and "
                         "don't mutate engine buckets after warmup"))
        n_feat = len({tuple((shape[1:], dt) for shape, dt in s)
                      for s in sigs})
        bound = len(engine.buckets) * max(n_feat, 1)
        if len(set(map(repr, sigs))) > bound:
            findings.append(Finding(
                "serve-retrace-churn", Severity.WARNING,
                f"{len(set(map(repr, sigs)))} compiled programs exceed the "
                f"bucket bound ({len(engine.buckets)} buckets × {n_feat} "
                "feature signatures); ragged batch sizes are escaping "
                "bucketing",
                node=type(engine).__name__,
                fix_hint="route all traffic through engine.infer (it pads "
                         "to buckets); check for direct _jitted calls"))
        return findings

    def check_decode_engine(self, engine, baseline: int = 0
                            ) -> List[Finding]:
        """Prove the decode engine's two-program bound
        (``serve/decode.py``): across ANY traffic mix the engine may
        compile at most one prefill program per prompt bucket plus ONE
        shared decode-step program. A repeated signature means a retrace
        behind the engine's back; more prefill programs than buckets (or
        a second step program) means a dynamic shape is leaking into a
        trace — every extra program is a multi-second compile stall in a
        latency-bound token loop. An empty finding list IS the proof
        tests assert on."""
        findings: List[Finding] = []
        log = engine.compile_log[baseline:]
        if not log:
            return findings
        sigs = [e["sig"] for e in log]
        dupes = {repr(s) for s in sigs if sigs.count(s) > 1}
        if dupes:
            findings.append(Finding(
                "decode-retrace-churn", Severity.ERROR,
                f"{len(dupes)} decode signature(s) compiled more than once "
                f"(e.g. {sorted(dupes)[0][:120]}); the per-signature "
                "program cache is being bypassed",
                node=type(engine).__name__,
                fix_hint="keep parameter avals stable and never resize the "
                         "page pool or slot count after construction"))
        n_prefill = len({repr(e["sig"]) for e in log
                         if e["kind"] == "prefill"})
        n_step = len({repr(e["sig"]) for e in log if e["kind"] == "step"})
        if n_prefill > len(engine.buckets):
            findings.append(Finding(
                "decode-retrace-churn", Severity.ERROR,
                f"{n_prefill} prefill programs exceed the bucket bound "
                f"({len(engine.buckets)} buckets); ragged prompt lengths "
                "are escaping bucketing",
                node=type(engine).__name__,
                fix_hint="route all prompts through engine.prefill (it "
                         "pads to prompt_buckets); check for direct "
                         "_prefill_fn calls"))
        if n_step > 1:
            findings.append(Finding(
                "decode-retrace-churn", Severity.ERROR,
                f"{n_step} decode-step programs compiled; the step must "
                "be ONE fixed-shape program regardless of which slots "
                "are live",
                node=type(engine).__name__,
                fix_hint="keep the step batch at the fixed slot count and "
                         "park inactive slots on the scratch page instead "
                         "of reshaping the batch"))
        return findings

    # ------------------------------------------------------------- public
    def lint(self, block, *example_inputs) -> Report:
        report = Report(self.scan_source(block))
        if example_inputs:
            report.extend(self.check_dtypes(block, *example_inputs))
        report.extend(self.check_cache(block))
        report.extend(self.check_update_engine(block))
        return report

    @contextlib.contextmanager
    def watch(self, block):
        """Observe a training/eval loop; ``report()`` afterwards. Accepts a
        Block, a gluon Trainer, or a Module (the latter two are watched for
        fused-update retrace churn)."""
        self._watched = block
        self._watch_baseline = len(self._cache_keys(block))
        self._watch_engine_baseline = sum(
            len(e.compile_log) for e in self._engines_of(block))
        try:
            yield self
        finally:
            pass

    def report(self) -> Report:
        if self._watched is None:
            raise RuntimeError("report() requires a completed watch() block")
        rep = Report(self.scan_source(self._watched))
        rep.extend(self.check_cache(self._watched,
                                    baseline=self._watch_baseline))
        rep.extend(self.check_update_engine(
            self._watched, baseline=getattr(self, "_watch_engine_baseline", 0)))
        return rep
