"""AST-based repo self-lint: framework invariants for ``mxnet_tpu/``.

The op registry's whole design rests on every registered op being a pure
traced function. These checks keep that true as the codebase grows:

- ``op-missing-ndarray-inputs`` (error): every ``@register(...)`` op must
  declare ``ndarray_inputs`` (list of tensor-arg names, or ``"*"`` for
  variadic ops) so symbol binding never guesses from signatures.
- ``host-call-in-op`` (error): no ``np.*``/``float()``/``bool()``/``int()``
  /``.asnumpy()``/``.item()`` applied to a tensor input inside a registered
  op body — each is a silent device->host sync (or a trace-time crash).
- ``bare-except`` (error): no ``except:`` — it swallows KeyboardInterrupt
  and jit tracer errors alike.

Suppress a deliberate violation with ``# lint: disable=<rule-id>`` on the
offending line (document why in a nearby comment).
"""
from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional, Set

from .findings import Finding, Report, Severity

__all__ = ["lint_source", "lint_paths", "main"]

# mirror of symbol.symbol._TENSOR_ARGS: kwargs that are tensors by convention
_TENSOR_ARG_NAMES = {
    "data", "weight", "bias", "gamma", "beta", "moving_mean", "moving_var",
    "running_mean", "running_var", "lhs", "rhs", "condition", "x", "y",
    "label", "grad", "indices", "index", "parameters", "state", "state_cell",
    "sequence_length", "mean", "var", "mom", "a", "b", "loss", "value",
    "mask", "anchors", "cls_pred", "loc_pred",
}
_NP_MODULES = {"np", "numpy", "_np", "onp"}
_HOST_BUILTINS = {"float", "bool", "int"}
_HOST_METHODS = {"asnumpy", "item", "tolist"}


def _suppressed(lines: List[str], lineno: int, rule_id: str) -> bool:
    if 1 <= lineno <= len(lines):
        line = lines[lineno - 1]
        if "lint: disable" in line:
            _, _, rest = line.partition("lint: disable")
            rest = rest.strip()
            if not rest.startswith("="):
                return True
            names = rest[1:].split()[0] if rest[1:].split() else ""
            return rule_id in {r.strip() for r in names.split(",")}
    return False


def _register_call(dec) -> Optional[ast.Call]:
    """The ast.Call if a decorator is ``register(...)`` / ``x.register(...)``."""
    if isinstance(dec, ast.Call):
        fn = dec.func
        if isinstance(fn, ast.Name) and fn.id == "register":
            return dec
        if isinstance(fn, ast.Attribute) and fn.attr == "register":
            return dec
    return None


def _tensor_names(fndef: ast.FunctionDef, reg_call: ast.Call) -> Set[str]:
    """Tensor-input names of a registered op, from its declaration."""
    names: Set[str] = set()
    declared = None
    for kw in reg_call.keywords:
        if kw.arg == "ndarray_inputs":
            declared = kw.value
    if isinstance(declared, (ast.List, ast.Tuple)):
        for elt in declared.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                names.add(elt.value)
    elif isinstance(declared, ast.Constant) and declared.value == "*" \
            and fndef.args.vararg is not None:
        names.add(fndef.args.vararg.arg)
    else:  # undeclared: leading positional args without defaults
        args = fndef.args.args
        n_default = len(fndef.args.defaults)
        required = args[:len(args) - n_default] if n_default else args
        names.update(a.arg for a in required)
        if fndef.args.vararg is not None:
            names.add(fndef.args.vararg.arg)
    # tensor-by-convention kwargs (optional tensor inputs like label=None)
    names.update(a.arg for a in fndef.args.args
                 if a.arg in _TENSOR_ARG_NAMES)
    return names


class _OpBodyScanner(ast.NodeVisitor):
    """Flags host calls on tensor inputs inside one registered op body."""

    def __init__(self, tensor_names: Set[str], filename: str,
                 lines: List[str], findings: List[Finding]):
        self.tensor_names = tensor_names
        self.filename = filename
        self.lines = lines
        self.findings = findings

    def _flag(self, node, what):
        if _suppressed(self.lines, node.lineno, "host-call-in-op"):
            return
        self.findings.append(Finding(
            "host-call-in-op", Severity.ERROR,
            f"{what} on a tensor input inside a registered op body: forces "
            "a device->host sync (or crashes under trace)",
            location=f"{self.filename}:{node.lineno}",
            fix_hint="use jnp/lax on the traced value, or mark the line "
                     "'# lint: disable=host-call-in-op' with justification"))

    def _tensor_arg(self, node) -> bool:
        return isinstance(node, ast.Name) and node.id in self.tensor_names

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _HOST_BUILTINS \
                and node.args and self._tensor_arg(node.args[0]):
            self._flag(node, f"{fn.id}({node.args[0].id})")
        elif isinstance(fn, ast.Attribute):
            if fn.attr in _HOST_METHODS and self._tensor_arg(fn.value):
                self._flag(node, f"{fn.value.id}.{fn.attr}()")
            elif isinstance(fn.value, ast.Name) \
                    and fn.value.id in _NP_MODULES:
                for a in node.args:
                    if self._tensor_arg(a):
                        self._flag(node,
                                   f"{fn.value.id}.{fn.attr}({a.id})")
                        break
        self.generic_visit(node)


def lint_source(src: str, filename: str = "<string>") -> List[Finding]:
    findings: List[Finding] = []
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        findings.append(Finding(
            "syntax-error", Severity.ERROR, str(e),
            location=f"{filename}:{e.lineno or 0}"))
        return findings

    # does this module use the OP registry's register()? (`.registry`
    # relative inside ops/, or absolute ops.registry — NOT the generic
    # mxnet_tpu.registry used for metrics/initializers)
    uses_op_registry = any(
        isinstance(n, ast.ImportFrom) and n.module
        and (n.module.endswith("ops.registry")
             or (n.module == "registry" and n.level == 1))
        and any(a.name == "register" for a in n.names)
        for n in ast.walk(tree))

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if not _suppressed(lines, node.lineno, "bare-except"):
                findings.append(Finding(
                    "bare-except", Severity.ERROR,
                    "bare 'except:' swallows KeyboardInterrupt/SystemExit "
                    "and tracer errors",
                    location=f"{filename}:{node.lineno}",
                    fix_hint="catch Exception (or the specific error)"))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                reg = _register_call(dec)
                if reg is None or not uses_op_registry:
                    continue
                if not any(kw.arg == "ndarray_inputs"
                           for kw in reg.keywords):
                    if not _suppressed(lines, dec.lineno,
                                       "op-missing-ndarray-inputs"):
                        findings.append(Finding(
                            "op-missing-ndarray-inputs", Severity.ERROR,
                            f"registered op {node.name!r} does not declare "
                            "ndarray_inputs; symbol binding would fall back "
                            "to signature guessing",
                            location=f"{filename}:{dec.lineno}",
                            fix_hint='declare ndarray_inputs=["data", ...] '
                                     '(or "*" for variadic ops)'))
                scanner = _OpBodyScanner(_tensor_names(node, reg),
                                         filename, lines, findings)
                for stmt in node.body:
                    scanner.visit(stmt)
    return findings


def lint_paths(paths: Iterable[str],
               exclude: Iterable[str] = ()) -> Report:
    report = Report()
    exclude = tuple(exclude)
    for path in paths:
        if os.path.isfile(path):
            files = [path]
        else:
            files = []
            for root, _dirs, names in os.walk(path):
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(root, n))
        for f in sorted(files):
            if any(x in f for x in exclude):
                continue
            with open(f, encoding="utf-8") as fh:
                report.extend(lint_source(fh.read(), filename=f))
    return report


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="mxnet_tpu repo self-lint (framework invariants)")
    ap.add_argument("paths", nargs="*", default=["mxnet_tpu"],
                    help="files or directories to lint (default: mxnet_tpu)")
    ap.add_argument("--exclude", action="append", default=[],
                    help="path substring to skip")
    ap.add_argument("--json", action="store_true", help="JSON output")
    args = ap.parse_args(argv)
    report = lint_paths(args.paths or ["mxnet_tpu"], exclude=args.exclude)
    print(report.to_json() if args.json else report.format())
    return 1 if report.findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
