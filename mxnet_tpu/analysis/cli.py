"""CLI for the static analyzer: ``python -m mxnet_tpu.analysis``.

Lints a serialized Symbol graph (``Symbol.tojson()`` / ``Symbol.save``)
without binding or compiling it::

    python -m mxnet_tpu.analysis model-symbol.json --shape data=1,3,224,224
    python -m mxnet_tpu.analysis --self-lint            # repo invariants
    python -m mxnet_tpu.analysis concurrency            # lock/protocol lint
    python -m mxnet_tpu.analysis concurrency --list-rules
    python -m mxnet_tpu.analysis --list-rules

Exit status: 0 clean, 1 findings at/above --fail-on (default: error).
"""
from __future__ import annotations

import argparse
import sys

from .findings import Severity

__all__ = ["main"]


def _parse_shapes(items):
    shapes = {}
    for item in items or ():
        if "=" not in item:
            raise SystemExit(f"--shape wants name=d0,d1,...; got {item!r}")
        name, dims = item.split("=", 1)
        shapes[name] = tuple(int(d) for d in dims.split(",") if d != "")
    return shapes


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    # subcommand dispatch: `concurrency` is the lock/protocol linter,
    # `dataplane` the copy/sync/allocation linter (each has its own flags)
    if argv and argv[0] == "concurrency":
        from .concurrency import main as concurrency_main

        return concurrency_main(list(argv[1:]))
    if argv and argv[0] == "dataplane":
        from .dataplane import main as dataplane_main

        return dataplane_main(list(argv[1:]))
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="Pre-flight lint for Symbol graphs (no compilation).")
    ap.add_argument("graph", nargs="?", help="path to a tojson() graph file")
    ap.add_argument("--shape", action="append", metavar="name=d0,d1,...",
                    help="input shape, repeatable (enables shape pre-flight)")
    ap.add_argument("--passes", help="comma-separated pass subset")
    ap.add_argument("--disable", help="comma-separated rule ids to drop")
    ap.add_argument("--fail-on", choices=[Severity.ERROR, Severity.WARNING,
                                          Severity.INFO],
                    default=Severity.ERROR,
                    help="lowest severity that makes the exit status 1")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--self-lint", action="store_true",
                    help="run the repo self-lint instead of a graph lint")
    ap.add_argument("--list-rules", action="store_true",
                    help="print pass names and their rule ids")
    args = ap.parse_args(argv)

    if args.self_lint:
        from .repo_lint import main as repo_main

        return repo_main((["--json"] if args.json else []))

    from .graph_passes import GraphLinter, list_passes

    if args.list_rules:
        for name, rules in sorted(list_passes().items()):
            print(f"{name}: {', '.join(rules)}")
        return 0
    if not args.graph:
        ap.error("a graph file is required (or --self-lint / --list-rules)")

    with open(args.graph, encoding="utf-8") as f:
        graph_json = f.read()
    options = {}
    if args.disable:
        options["disable"] = {r.strip() for r in args.disable.split(",")}
    passes = [p.strip() for p in args.passes.split(",")] if args.passes \
        else None
    linter = GraphLinter(passes=passes, **options)
    report = linter.lint(graph_json, shapes=_parse_shapes(args.shape))
    print(report.to_json() if args.json else report.format())

    threshold = Severity.rank(args.fail_on)
    worst = min((Severity.rank(f.severity) for f in report),
                default=len(Severity.ORDER))
    return 1 if worst <= threshold else 0


if __name__ == "__main__":
    sys.exit(main())
