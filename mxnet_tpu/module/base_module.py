"""BaseModule with the high-level ``fit`` loop.

Reference: ``python/mxnet/module/base_module.py`` (TBV — SURVEY.md §2.3).
"""
from __future__ import annotations

import logging
import time

import numpy as np

from .. import metric as metric_mod
from ..callback import BatchEndParam

__all__ = ["BaseModule"]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.symbol = None

    # -- lifecycle hooks implemented by subclasses -----------------------
    def bind(self, *a, **kw):
        raise NotImplementedError

    def init_params(self, *a, **kw):
        raise NotImplementedError

    def init_optimizer(self, *a, **kw):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    # -- composite helpers ------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, reset=True, epoch=0,
              batch_end_callback=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback:
                bp = BatchEndParam(epoch, nbatch, eval_metric, locals())
                for cb in _as_list(batch_end_callback):
                    cb(bp)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, reset=True):
        from ..ndarray import NDArray, concat

        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            if batch.pad:
                outs = [o[:o.shape[0] - batch.pad] for o in outs]
            outputs.append([o.copy() for o in outs])
        if not outputs:
            return []
        n_out = len(outputs[0])
        merged = [concat(*[b[i] for b in outputs], dim=0) for i in range(n_out)]
        return merged[0] if n_out == 1 else merged

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=None,
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None, monitor=None):
        """The classic training loop (reference BaseModule.fit)."""
        assert num_epoch is not None, "num_epoch is required for fit"
        optimizer_params = optimizer_params or {"learning_rate": 0.01}
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback:
                    bp = BatchEndParam(epoch, nbatch, eval_metric, locals())
                    for cb in _as_list(batch_end_callback):
                        cb(bp)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, time.time() - tic)
            if epoch_end_callback:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric, epoch=epoch,
                                 batch_end_callback=eval_batch_end_callback)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
