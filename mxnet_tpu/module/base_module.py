"""BaseModule with the high-level ``fit`` loop.

Reference: ``python/mxnet/module/base_module.py`` (TBV — SURVEY.md §2.3).
"""
from __future__ import annotations

import logging
import time

import numpy as np

from .. import metric as metric_mod
from .. import obs
from ..obs import fleetstats as _fleetstats
from ..callback import BatchEndParam

__all__ = ["BaseModule"]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.symbol = None

    # -- lifecycle hooks implemented by subclasses -----------------------
    def bind(self, *a, **kw):
        raise NotImplementedError

    def init_params(self, *a, **kw):
        raise NotImplementedError

    def init_optimizer(self, *a, **kw):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    # -- composite helpers ------------------------------------------------
    def forward_backward(self, data_batch):
        # fleetstats.phase = the ordinary obs span (same names on the
        # timeline) + windowed per-rank step accounting + the MXNET_CHAOS
        # _SLOW straggler injection point (docs/OBSERVABILITY.md
        # "Training-fleet telemetry")
        with _fleetstats.phase("forward"):
            self.forward(data_batch, is_train=True)
        with _fleetstats.phase("backward"):
            self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, reset=True, epoch=0,
              batch_end_callback=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback:
                bp = BatchEndParam(epoch, nbatch, eval_metric, locals())
                for cb in _as_list(batch_end_callback):
                    cb(bp)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, reset=True):
        from ..ndarray import NDArray, concat

        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            if batch.pad:
                outs = [o[:o.shape[0] - batch.pad] for o in outs]
            outputs.append([o.copy() for o in outs])
        if not outputs:
            return []
        n_out = len(outputs[0])
        merged = [concat(*[b[i] for b in outputs], dim=0) for i in range(n_out)]
        return merged[0] if n_out == 1 else merged

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=None,
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None, monitor=None,
            checkpoint=None, resume="auto", checkpoint_period=1,
            checkpoint_batch_period=None, handle_preemption=True,
            health=None):
        """The classic training loop (reference BaseModule.fit).

        Crash-safe checkpointing (docs/ROBUSTNESS.md): pass ``checkpoint=``
        a directory or :class:`~mxnet_tpu.checkpoint.CheckpointManager` to
        snapshot full training state (params, optimizer slots/counters, RNG
        streams, iterator cursor) every ``checkpoint_period`` epochs and —
        when the iterator supports positioning — every
        ``checkpoint_batch_period`` batches. ``resume="auto"`` restores the
        newest *valid* checkpoint (corrupt ones are skipped via CRC) and
        continues mid-epoch such that the finished run is bitwise identical
        to an uninterrupted one on CPU; ``resume=<int>`` pins a step;
        ``resume="never"`` ignores existing checkpoints. With
        ``handle_preemption`` a SIGTERM/SIGINT flushes a final checkpoint
        after the in-flight batch and returns cleanly.

        Training health (docs/OBSERVABILITY.md "Training health"):
        ``health=`` takes ``True`` / a kwargs dict / a
        :class:`~mxnet_tpu.obs.health.HealthMonitor`. The sentinel samples
        loss, grad norms, and non-finite counts every K steps (one batched
        device fetch, zero extra program executions), fires ``on_breach``
        callbacks, and — when the monitor's ``actions`` allow and a
        ``checkpoint=`` manager is present — escalates warn → lr backoff →
        rollback to the last checkpoint whose arrays are finite (full PR-2
        state, so the retried segment is bitwise-reproducible). A
        non-finite breach also triggers the NaN-provenance blame pass,
        naming the first non-finite graph node as a tagged obs event.
        """
        assert num_epoch is not None, "num_epoch is required for fit"
        optimizer_params = optimizer_params or {"learning_rate": 0.01}

        from ..checkpoint import CheckpointManager, as_manager
        from ..obs import health as health_mod

        # elastic training (docs/ROBUSTNESS.md "Elastic training"): when
        # the kvstore carries an ElasticWorkerSession, membership is
        # resolved BEFORE the checkpoint resume below — a restarted worker
        # lands quarantined, blocks here until the live fleet's next epoch
        # boundary activates it, and then resumes from the newest shared
        # checkpoint (which the survivors flushed before that same
        # boundary's rendezvous): the checkpointed rejoin
        # a manager built from a bare directory is ours to close at the end;
        # a caller-supplied manager outlives the fit (only flushed).
        # (Resolved BEFORE the elastic join: a quarantined rejoiner warms
        # its update program from the newest shared checkpoint while it
        # waits for the activation boundary.)
        owns_manager = not isinstance(checkpoint, CheckpointManager)
        manager = as_manager(checkpoint)

        elastic = getattr(kvstore, "elastic", None) \
            if not isinstance(kvstore, str) else None
        elastic_info = None
        if elastic is not None:
            elastic_info = elastic.ensure_joined()
            if not elastic_info.active:
                self.logger.info(
                    "elastic: quarantined (generation %d, fleet at epoch "
                    "%d) — waiting for the next epoch boundary",
                    elastic_info.generation, elastic_info.epoch)
                # persistent program cache (docs/PERFORMANCE.md "Program
                # cache and cold start"): compile — or deserialize — the
                # fused update step NOW, overlapping the quarantine wait,
                # so activation → first lockstep reduce never stalls the
                # live fleet on this rank's XLA compile
                self._prewarm_update_programs(manager, optimizer,
                                              optimizer_params, train_data)
                elastic_info = elastic.await_activation()
                self.logger.info(
                    "elastic: activated at epoch %d generation %d, shard "
                    "%d/%d", elastic_info.epoch, elastic_info.generation,
                    elastic_info.part_index, elastic_info.num_parts)
            if hasattr(train_data, "set_partition"):
                try:
                    train_data.set_partition(elastic_info.part_index,
                                             elastic_info.num_parts)
                except NotImplementedError:
                    pass  # keep the construction-time shard

        if isinstance(resume, bool):  # bool is an int: keep True out of the
            resume = "auto" if resume else "never"  # pinned-step branch
        resume_state = None
        if manager is not None and resume not in (None, "never"):
            resume_state = (manager.load(resume) if isinstance(resume, int)
                            else manager.load_latest())
        mid_epoch = False
        if resume_state is not None:
            from ..checkpoint.state import restore_iterator

            arg_params = resume_state.arg_params()
            aux_params = resume_state.aux_params()
            force_init = True
            # put the iterator back exactly as captured — the shuffle order
            # matters even across epochs, because reshuffling permutes it
            # IN PLACE (same RNG state + different starting arrangement =
            # different epoch order)
            if elastic is not None:
                # elastic rejoin is epoch-boundary-only and the shard
                # assignment from activation (set_partition above) is
                # authoritative — the checkpoint's cursor/order describe
                # ANOTHER rank's (possibly differently-sized) shard
                restored = False
                mid_epoch = False
            else:
                restored = restore_iterator(train_data, resume_state)
                mid_epoch = resume_state.nbatch is not None
            if mid_epoch and not restored:
                self.logger.warning(
                    "checkpoint was taken mid-epoch (batch %d) but the "
                    "iterator cannot be positioned; skipping the remainder "
                    "of epoch %d rather than double-applying its batches",
                    resume_state.nbatch, resume_state.epoch)
                mid_epoch = False
            begin_epoch = resume_state.epoch + (0 if mid_epoch else 1)
            self.logger.info(
                "resuming from checkpoint step %d (epoch %d%s)",
                resume_state.global_step, begin_epoch,
                f", batch {resume_state.nbatch}" if mid_epoch else "")
        if elastic_info is not None and elastic_info.epoch > begin_epoch:
            # The fleet is ahead of this worker's newest checkpoint. With
            # live peers this is unrecoverable drift, not a warning: the
            # per-step sync averages GRADIENTS, never weights, so stale
            # params would never converge to the fleet's — every rank
            # would silently train a different model from here on. Fail
            # loudly unless explicitly overridden (e.g. a deliberate
            # whole-fleet restart against a durable server whose epoch
            # label survived — params then agree by construction).
            from ..base import MXNetError, get_env

            if elastic_info.active_count > 1 and not get_env(
                    "MXNET_ELASTIC_ALLOW_STALE_REJOIN", False, bool):
                raise MXNetError(
                    f"elastic: the fleet is at epoch {elastic_info.epoch} "
                    f"but this worker's newest shared checkpoint resumes "
                    f"at epoch {begin_epoch} — rejoining with stale "
                    f"parameters would silently desync the ranks (gradient "
                    f"sync never re-syncs weights). Share one checkpoint "
                    f"directory with checkpoint_period=1, or set "
                    f"MXNET_ELASTIC_ALLOW_STALE_REJOIN=1 to proceed "
                    f"anyway.")
            self.logger.warning(
                "elastic: fleet is at epoch %d but resume found epoch %d — "
                "fast-forwarding (parameters come from the newest shared "
                "checkpoint)", elastic_info.epoch, begin_epoch)
            begin_epoch = elastic_info.epoch
            mid_epoch = False
        if manager is not None and handle_preemption:
            manager.install_signal_handlers()

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        global_step = 0
        if resume_state is not None:
            self._restore_training_state(resume_state)
            global_step = resume_state.global_step
        if (elastic is not None and resume_state is None
                and elastic_info.active and elastic_info.epoch == 0):
            # cold co-start: broadcast the lead rank's initial params once.
            # Gradient sync alone never re-syncs weights, so ranks with
            # different init RNG state would silently train divergent
            # models forever. (Resumed workers already hold the shared
            # checkpoint's params; rejoiners go through the checkpointed
            # rejoin path instead.) Unconditional for every co-start
            # active — NOT gated on a join-time active_count, which can
            # differ across ranks and would split the fleet into divergent
            # collective sequences: a solo broadcast completes instantly,
            # and a straggler joining after it is answered from the
            # released-round cache with the root's params, which is
            # exactly the broadcast's meaning.
            with obs.trace.span("elastic.bcast_params"):
                self._elastic_broadcast_params(
                    kvstore, root=elastic_info.part_index == 0)
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric
        # mid-epoch saves need a positionable iterator; otherwise the resume
        # point must stay at the epoch boundary or replay would double-apply
        can_position = (train_data.get_checkpoint_state() is not None
                        if hasattr(train_data, "get_checkpoint_state")
                        else False)
        health_monitor = health_mod.as_monitor(health)
        if health_monitor is not None:
            # an attached monitor activates the in-graph stats even with
            # the wider obs layer off (fused.py asks inline_stats_active)
            health_mod.activate()
            if health_monitor.param_names is None and \
                    getattr(self, "_param_names", None):
                health_monitor.attach_names(list(self._param_names))

        # pending_batch set => enter the epoch mid-stream WITHOUT
        # reset/reshuffle (the cursor is already positioned): entry resume
        # and health rollback share this path
        pending_batch = resume_state.nbatch if mid_epoch else None
        epoch = begin_epoch
        try:
            while epoch < num_epoch:
                tic = time.time()
                eval_metric.reset()
                if pending_batch is not None:
                    nbatch = pending_batch
                    pending_batch = None
                else:
                    train_data.reset()
                    nbatch = -1
                batches = iter(train_data)
                rolled_back = False
                while True:
                    # data_wait = time the step loop blocks on the iterator
                    # (decode + host→device when PrefetchingIter is behind)
                    with _fleetstats.phase("data_wait"):
                        data_batch = next(batches, _STOP)
                    if data_batch is _STOP:
                        break
                    nbatch += 1
                    self.forward_backward(data_batch)
                    if elastic is not None:
                        # generation-scoped mean over the LIVE fleet: a
                        # worker SIGKILL'd mid-epoch shrinks the round's
                        # required set after K missed heartbeats and this
                        # returns over the survivors — no barrier timeout
                        with _fleetstats.phase("elastic.sync_grads"):
                            self._elastic_sync_grads(kvstore)
                    if health_monitor is not None:
                        # stats variant only on steps the sentinel will
                        # sample — the per-param norms' cost amortizes 1/K
                        health_mod.request_stats(health_monitor.will_sample())
                    with _fleetstats.phase("update"):
                        self.update()
                    global_step += 1
                    # live device memory, once per batch: the counter track
                    # in the chrome trace + the steady-state leak detector
                    # (one flag check when telemetry is off)
                    obs.device.sample(step=global_step)
                    with _fleetstats.phase("metric"):
                        self.update_metric(eval_metric, data_batch.label)
                    if health_monitor is not None:
                        # sampled every K steps; sits BEFORE this step's
                        # checkpoint save so a detected blowup can never
                        # commit poisoned params as "the newest snapshot"
                        health_monitor.record_metric(eval_metric)
                        rep = health_monitor.step(
                            global_step,
                            engine=getattr(getattr(self, "_updater", None),
                                           "_engine", None),
                            optimizer=getattr(self, "_optimizer", None))
                        if rep is not None and rep["breaches"]:
                            if health_monitor.should_blame(rep) and \
                                    getattr(self, "_exec", None) is not None:
                                with obs.trace.span("health.blame"):
                                    health_mod.blame_nonfinite(self._exec)
                            if rep["action"] == "rollback":
                                if elastic is not None:
                                    # a rollback is rank-local (this rank's
                                    # shard metrics breached) but elastic
                                    # sync is lockstep — one rank replaying
                                    # extra batches would issue reduce
                                    # rounds its peers never join and wedge
                                    # the fleet into timeouts
                                    self.logger.warning(
                                        "health: rollback requested but "
                                        "elastic lockstep sync is active — "
                                        "continuing without rollback "
                                        "(rank-local replay would desync "
                                        "the fleet's reduce rounds)")
                                elif manager is None:
                                    self.logger.warning(
                                        "health: rollback requested but fit "
                                        "has no checkpoint= manager — "
                                        "continuing (warn only)")
                                else:
                                    res = self._apply_health_rollback(
                                        manager, health_monitor, train_data)
                                    if res is not None:
                                        state, positioned = res
                                        global_step = state.global_step
                                        if (state.nbatch is not None
                                                and positioned):
                                            epoch = state.epoch
                                            pending_batch = state.nbatch
                                        else:
                                            epoch = state.epoch + 1
                                            pending_batch = None
                                        rolled_back = True
                                        break
                    if batch_end_callback:
                        bp = BatchEndParam(epoch, nbatch, eval_metric,
                                           locals())
                        for cb in _as_list(batch_end_callback):
                            cb(bp)
                    if (manager is not None and checkpoint_batch_period
                            and can_position
                            and global_step % checkpoint_batch_period == 0):
                        with _fleetstats.phase("checkpoint",
                                               step=global_step):
                            manager.save(self._capture_training_state(
                                epoch, nbatch, global_step, train_data),
                                global_step)
                    # close the step's fleet accounting: phases recorded
                    # above fold into this rank's current window; sealed
                    # windows ride the next heartbeat to the PS server
                    _fleetstats.step_complete(global_step)
                    # bounded-staleness async (docs/ROBUSTNESS.md): commit
                    # this rank's finished step to the PS committed-clock
                    # table; a no-op outside async-staleness mode (and
                    # kvstore may be a plain string spec here)
                    tick = getattr(kvstore, "step_complete", None)
                    if callable(tick):
                        tick(global_step)
                    if manager is not None and manager.preempted.is_set():
                        # flush a final snapshot after the in-flight batch;
                        # with a non-positionable iterator no mid-epoch point
                        # can be resumed exactly, so fall back to the last
                        # epoch-end checkpoint (cost: at most one interval)
                        if can_position:
                            with obs.trace.span("checkpoint",
                                                step=global_step,
                                                preemption=True):
                                manager.save(self._capture_training_state(
                                    epoch, nbatch, global_step, train_data),
                                    global_step, block=True)
                        manager.flush()
                        self.logger.info(
                            "preempted at epoch %d batch %d — final "
                            "checkpoint flushed at step %d",
                            epoch, nbatch, global_step)
                        import signal as _signal

                        if manager.preempt_signum == _signal.SIGINT:
                            # Ctrl-C keeps its meaning: flush first, then
                            # raise so the caller can't mistake an
                            # interrupted fit for a completed one
                            raise KeyboardInterrupt
                        return  # SIGTERM: the VM is going away — exit clean
                if rolled_back:
                    # re-enter the (possibly earlier) epoch at the restored
                    # cursor; eval_metric resets at the loop top, so the
                    # poisoned running averages die with the bad segment
                    continue
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
                self.logger.info("Epoch[%d] Time cost=%.3f",
                                 epoch, time.time() - tic)
                if epoch_end_callback:
                    arg_p, aux_p = self.get_params()
                    for cb in _as_list(epoch_end_callback):
                        cb(epoch, self.symbol, arg_p, aux_p)
                if (manager is not None and checkpoint_period
                        and (epoch + 1) % checkpoint_period == 0
                        and not (checkpoint_batch_period and can_position
                                 and global_step % checkpoint_batch_period
                                 == 0)):
                    # train_data rides along so resume can restore the
                    # shuffle order before the next epoch's in-place
                    # reshuffle. Skipped when the batch-period save above
                    # already committed this exact step: that snapshot
                    # resumes to bitwise-identical params (re-entering the
                    # finished epoch for zero batches), and the manager
                    # would discard a same-step rewrite anyway
                    with obs.trace.span("checkpoint", step=global_step,
                                        epoch_end=True):
                        manager.save(self._capture_training_state(
                            epoch, None, global_step, train_data),
                            global_step)
                if elastic is not None:
                    if manager is not None:
                        # the boundary snapshot must be durable BEFORE the
                        # rendezvous: a worker activated at this boundary
                        # resumes from it, and the rendezvous is the only
                        # ordering guarantee it has
                        manager.flush()
                    info = elastic.epoch_end(epoch)
                    if info.changed:
                        self.logger.info(
                            "elastic: membership changed at epoch %d "
                            "boundary (generation %d) — shard recut to "
                            "%d/%d", epoch, info.generation,
                            info.part_index, info.num_parts)
                        if hasattr(train_data, "set_partition"):
                            try:
                                train_data.set_partition(info.part_index,
                                                         info.num_parts)
                            except NotImplementedError:
                                pass
                if eval_data is not None:
                    res = self.score(eval_data, validation_metric,
                                     epoch=epoch,
                                     batch_end_callback=eval_batch_end_callback)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)
                epoch += 1
        finally:
            # seal the partial fleet-accounting window so a short fit's
            # step attribution still ships on the closing heartbeats
            _fleetstats.flush()
            if health_monitor is not None:
                health_mod.request_stats(None)
                health_mod.deactivate()
            # runs on normal completion, the preemption return, AND
            # exceptions: signal handlers must never outlive the fit
            if manager is not None:
                import sys

                unwinding = sys.exc_info()[0] is not None
                try:
                    if owns_manager:
                        manager.close()  # drain writer, restore handlers
                    else:
                        manager.flush()
                        manager.restore_signal_handlers()
                except BaseException:
                    if not unwinding:
                        raise  # clean run: a lost write must surface
                    # don't mask the in-flight training exception
                    self.logger.warning("checkpoint cleanup failed",
                                        exc_info=True)

    # -- elastic plumbing -------------------------------------------------
    def _elastic_broadcast_params(self, kv, root: bool):
        """One fused broadcast of the lead rank's params + aux states into
        every rank's bound executor (cold co-start only)."""
        exec_ = getattr(self, "_exec", None)
        if exec_ is None or not hasattr(kv, "broadcast_arrays"):
            return
        from ..ndarray import array as nd_array

        names = [n for n in getattr(self, "_param_names", [])
                 if n in exec_.arg_dict]
        targets = [exec_.arg_dict[n] for n in names] \
            + [exec_.aux_dict[n] for n in getattr(self, "_aux_names", [])
               if n in exec_.aux_dict]
        if not targets:
            return
        # intentional sync: elastic broadcast rides the host-side PS wire
        # (boundary event at join/rejoin, not the per-step path)
        vals = kv.broadcast_arrays([t.asnumpy() for t in targets], root)  # lint: disable=host-sync-on-hot-path
        if not root:
            for t, v in zip(targets, vals):
                t._set_data(nd_array(np.asarray(v, t.dtype))._data)

    def _prewarm_update_programs(self, manager, optimizer, optimizer_params,
                                 train_data) -> bool:
        """Best-effort elastic-rejoin warm (mxnet_tpu/progcache.py): build
        the fused update step's program for the parameter set in the
        newest SHARED checkpoint — deserializing from the persistent cache
        when a previous life of this worker (or any peer on this host)
        already compiled it, compiling into the cache otherwise — without
        touching optimizer counters or weights. Runs while quarantined, so
        the cost overlaps the activation wait; a mismatch in derived keys
        just means the real first step misses the cache (the pre-PR cost),
        never a wrong program. Returns whether a program was warmed."""
        from .. import progcache

        if not progcache.active() or manager is None:
            return False
        try:
            state = manager.load_latest()
            if state is None:
                return False
            arg_params = state.arg_params()
            fixed = getattr(self, "_fixed_param_names", set())
            names = [n for n in getattr(self, "_param_names", [])
                     if n not in fixed]
            if not names or any(n not in arg_params for n in names):
                return False
            from ..ndarray import array as nd_array
            from ..optimizer import create as opt_create
            from ..optimizer.optimizer import Optimizer, Updater

            if isinstance(optimizer, Optimizer):
                opt = optimizer
            else:
                # mirror Module.init_optimizer's construction (incl. the
                # 1/batch rescale default) so the static key matches the
                # one the real engine derives after activation
                params = dict(optimizer_params or {})
                provide = getattr(train_data, "provide_data", None)
                if "rescale_grad" not in params and provide:
                    params["rescale_grad"] = 1.0 / provide[0][1][0]
                opt = opt_create(optimizer, **params)
            indices = [i for i, n in enumerate(
                getattr(self, "_param_names", [])) if n not in fixed]
            weights = [nd_array(np.asarray(arg_params[n])) for n in names]
            warmed = Updater(opt).prewarm_batch(indices, weights)
            if warmed:
                self.logger.info(
                    "elastic: fused update program warmed from the "
                    "persistent cache while quarantined")
            return warmed
        except Exception as e:  # noqa: BLE001 — warm is strictly optional
            self.logger.debug("progcache prewarm skipped: %s", e)
            return False

    def _elastic_sync_grads(self, kv):
        """Mean-allreduce this step's gradients over the live fleet (one
        fused flat reduction through ``DistKVStore.allreduce_mean``) and
        write the means back into the bound executor's grad arrays, so the
        local optimizer applies an identical update on every surviving
        rank. The divisor is the count that actually contributed — fewer
        after a mid-epoch death (docs/ROBUSTNESS.md documents the
        tolerance)."""
        exec_ = getattr(self, "_exec", None)
        if exec_ is None or not hasattr(kv, "allreduce_mean"):
            return
        fixed = getattr(self, "_fixed_param_names", set())
        names = [n for n in getattr(self, "_param_names", [])
                 if n not in fixed and exec_.grad_dict.get(n) is not None]
        if not names:
            return
        from ..ndarray import array as nd_array

        grads = [exec_.grad_dict[n] for n in names]
        # intentional sync: the elastic reduce is host-mediated by design
        # (grads cross the PS wire as numpy; device reduce is kvstore ici)
        means, _n = kv.allreduce_mean([g.asnumpy() for g in grads])  # lint: disable=host-sync-on-hot-path
        for g, m in zip(grads, means):
            g._set_data(nd_array(np.asarray(m, g.dtype))._data)

    # -- checkpoint plumbing ----------------------------------------------
    def _capture_training_state(self, epoch, nbatch, global_step,
                                train_data=None, loss_scaler=None):
        """Snapshot everything a bitwise resume needs (host-side copies —
        safe to hand to the async writer while training continues)."""
        from ..checkpoint.state import capture_training_state

        arg, aux = self.get_params()
        return capture_training_state(
            arg_params=arg, aux_params=aux,
            updater=getattr(self, "_updater", None),
            optimizer=getattr(self, "_optimizer", None),
            epoch=epoch, nbatch=nbatch, global_step=global_step,
            train_data=train_data, loss_scaler=loss_scaler)

    def _restore_training_state(self, state):
        """Restore optimizer slots/counters and RNG streams (params went in
        through init_params; the iterator is restored inside fit's epoch
        loop so reset() can't clobber it)."""
        from ..checkpoint.state import restore_optimizer, restore_rng

        restore_optimizer(getattr(self, "_updater", None),
                          getattr(self, "_optimizer", None), state)
        restore_rng(state)

    def _apply_health_rollback(self, manager, monitor, train_data):
        """Divergence-sentinel auto-rollback: restore the newest checkpoint
        whose arrays are all finite (a CRC-valid snapshot written after the
        blowup is poisoned, not valid) — params, optimizer slots/counters,
        RNG streams, and the iterator cursor, exactly the PR-2 resume path,
        so the retried segment is bitwise-reproducible. Returns
        ``(state, iterator_positioned)`` or None when nothing usable
        exists."""
        from ..checkpoint.state import restore_iterator
        from ..obs import health as health_mod

        manager.flush()  # queued async saves must be on disk to be judged
        state = health_mod.find_rollback_target(manager)
        if state is None:
            self.logger.warning(
                "health: rollback requested but no valid finite checkpoint "
                "exists — continuing without rollback")
            return None
        self.init_params(arg_params=state.arg_params(),
                         aux_params=state.aux_params(), force_init=True)
        self._restore_training_state(state)
        positioned = restore_iterator(train_data, state)
        monitor.note_rollback(state.global_step)
        obs.event("health.rollback", step=state.global_step,
                  epoch=state.epoch, nbatch=state.nbatch)
        self.logger.warning(
            "health: rolled back to checkpoint step %d (epoch %s%s)",
            state.global_step, state.epoch,
            f", batch {state.nbatch}" if state.nbatch is not None
            and positioned else "")
        return state, positioned


_STOP = object()  # iterator-exhausted sentinel for the data_wait span


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
