"""Module — symbolic training over a bound Executor.

Reference: ``python/mxnet/module/module.py`` + ``executor_group.py``
(TBV — SURVEY.md §2.3). The reference's DataParallelExecutorGroup slices
the batch across a GPU context list; here one Executor compiles the graph
through XLA, and multi-chip data parallelism goes through the sharded
context list → mesh mapping (context list with >1 device = dp mesh) or
the parallel.ShardedTrainer path for Gluon.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import initializer as init_mod
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import NDArray
from ..optimizer import create as opt_create
from ..optimizer.optimizer import Updater
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=None, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        import logging

        super().__init__(logger or logging)
        self.symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        ctx = context if context is not None else current_context()
        self._context = ctx[0] if isinstance(ctx, (list, tuple)) else ctx
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._data_shapes = None
        self._label_shapes = None

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self.symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return [(n, o.shape) for n, o in zip(self.output_names,
                                             self._exec.outputs)]

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write", lint=None):
        """``lint="warn"|"error"|"off"`` runs the static analyzer over the
        graph (with these shapes) before any compilation; "error" raises a
        node-attributed GraphAnalysisError on error-severity findings.
        Default: the MXNET_GRAPH_LINT env var ("off")."""
        if self.binded and not force_rebind:
            return
        self._data_shapes = _as_descs(data_shapes)
        self._label_shapes = _as_descs(label_shapes) if label_shapes else []
        shapes = {n: s for n, s, *_ in
                  [(d[0], d[1]) for d in self._data_shapes + self._label_shapes]}
        self.for_training = for_training
        self._exec = self.symbol.simple_bind(
            ctx=self._context, grad_req=grad_req if for_training else "null",
            lint=lint, **shapes)
        if shared_module is not None and shared_module._exec is not None:
            for n, v in shared_module._exec.arg_dict.items():
                if n in self._exec.arg_dict and n in self._param_names:
                    self._exec.arg_dict[n] = v
        self.binded = True

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        initializer = initializer or init_mod.Uniform(0.01)
        if isinstance(initializer, str):
            initializer = init_mod.create(initializer)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params and name in arg_params:
                arr._set_data(NDArray(arg_params[name])._data)
            else:
                buf = np.array(arr.asnumpy())  # asnumpy views are read-only
                initializer(name, buf)
                arr._set_data(NDArray(buf)._data)
        for name in self._aux_names:
            if aux_params and name in aux_params:
                self._exec.aux_dict[name]._set_data(NDArray(aux_params[name])._data)
        self.params_initialized = True

    def get_params(self):
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: v.copy() for n, v in self._exec.aux_dict.items()}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        assert self.binded and self.params_initialized
        optimizer_params = dict(optimizer_params or {})
        if isinstance(optimizer, str):
            # reference Module scales grads by 1/batch_size unless overridden
            if "rescale_grad" not in optimizer_params and self._data_shapes:
                optimizer_params["rescale_grad"] = 1.0 / self._data_shapes[0][1][0]
            self._optimizer = opt_create(optimizer, **optimizer_params)
        else:
            self._optimizer = optimizer
        self._updater = Updater(self._optimizer)
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        for name, arr in zip(self._label_names, data_batch.label or []):
            feed[name] = arr
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        assert self.optimizer_initialized
        idxs, grads, weights = [], [], []
        for i, name in enumerate(self._param_names):
            if name in self._fixed_param_names:
                continue
            w = self._exec.arg_dict[name]
            g = self._exec.grad_dict.get(name)
            if g is None:
                continue
            idxs.append(i)
            grads.append(g)
            weights.append(w)
        if idxs:
            # one fused program per step (optimizer/fused.py);
            # MXNET_FUSED_UPDATE=0 restores the per-param eager loop
            self._updater.update_batch(idxs, grads, weights)

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    # ------------------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint

        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self.symbol, arg, aux)
        if save_optimizer_states and self._updater is not None:
            from ..checkpoint.atomic import atomic_write_bytes

            # atomic: a crash mid-save must not leave truncated .states
            atomic_write_bytes(f"{prefix}-{epoch:04d}.states",
                               self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint

        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (arg, aux)
        mod._init_from_preloaded = True

        orig_init = mod.init_params

        def init_params(initializer=None, arg_params=None, aux_params=None,
                        **kw):
            orig_init(initializer=initializer, arg_params=arg_params or arg,
                      aux_params=aux_params or aux, **kw)

        mod.init_params = init_params
        return mod


def _as_descs(shapes):
    out = []
    for s in shapes:
        if hasattr(s, "name"):
            out.append((s.name, tuple(s.shape)))
        else:
            name, shape = s[0], tuple(s[1])
            out.append((name, shape))
    return out
