"""BucketingModule — variable-length training via per-bucket executors.

Reference: ``python/mxnet/module/bucketing_module.py`` (TBV). The
reference keeps a {bucket_key: executor} cache sharing one parameter set;
here each bucket is a jit specialization (XLA compiles per shape) and the
parameter NDArrays are literally shared between bucket Modules.
"""
from __future__ import annotations

from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=None,
                 context=None, fixed_param_names=None, state_names=None,
                 compression_params=None):
        import logging

        super().__init__(logger or logging)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._init_args = None
        self._opt_args = None

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None

    @symbol.setter
    def symbol(self, v):
        pass

    def _get_module(self, bucket_key, data_shapes, label_shapes):
        if bucket_key not in self._buckets:
            sym, data_names, label_names = self._sym_gen(bucket_key)
            mod = Module(sym, data_names=data_names, label_names=label_names,
                         context=self._context,
                         fixed_param_names=self._fixed_param_names)
            mod.bind(data_shapes, label_shapes, for_training=self.for_training)
            master = self._buckets.get(self._default_bucket_key)
            if master is not None and master.params_initialized:
                # share parameter storage with the master bucket
                for n in mod._param_names:
                    if n in master._exec.arg_dict:
                        mod._exec.arg_dict[n] = master._exec.arg_dict[n]
                for n, v in master._exec.aux_dict.items():
                    if n in mod._exec.aux_dict:
                        mod._exec.aux_dict[n] = v
                mod.params_initialized = True
            elif self._init_args is not None:
                mod.init_params(**self._init_args)
            self._buckets[bucket_key] = mod
        return self._buckets[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        self.for_training = for_training
        sym, data_names, label_names = self._sym_gen(self._default_bucket_key)
        mod = Module(sym, data_names=data_names, label_names=label_names,
                     context=self._context,
                     fixed_param_names=self._fixed_param_names)
        mod.bind(data_shapes, label_shapes, for_training=for_training,
                 grad_req=grad_req)
        self._buckets[self._default_bucket_key] = mod
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def init_params(self, **kwargs):
        self._init_args = kwargs
        self._curr_module.init_params(**kwargs)
        self.params_initialized = True

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def init_optimizer(self, **kwargs):
        # one shared updater: optimizer state is keyed by param index, and all
        # buckets share parameter storage, so share the updater too
        master = self._buckets[self._default_bucket_key]
        master.init_optimizer(**kwargs)
        self._opt_args = kwargs
        for key, mod in self._buckets.items():
            if mod is not master:
                mod._optimizer = master._optimizer
                mod._updater = master._updater
                mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        mod = self._get_module(bucket_key, data_shapes, label_shapes)
        if self.optimizer_initialized and not mod.optimizer_initialized:
            master = self._buckets[self._default_bucket_key]
            mod._optimizer = master._optimizer
            mod._updater = master._updater
            mod.optimizer_initialized = True
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", None)
        if key is None:
            key = self._default_bucket_key
        self.switch_bucket(key,
                           data_batch.provide_data or
                           [(n, a.shape) for n, a in
                            zip(self._curr_module._data_names, data_batch.data)],
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)
