"""Evaluation metrics (reference python/mxnet/metric.py, TBV — SURVEY.md §5.5)."""
from __future__ import annotations

import numpy as np

from .ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "CrossEntropy", "Perplexity",
           "F1", "MCC", "NegativeLogLikelihood", "MAE", "MSE", "RMSE",
           "PearsonCorrelation", "Loss", "CompositeEvalMetric",
           "MApMetric", "VOC07MApMetric", "create"]

_REGISTRY = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        c = CompositeEvalMetric()
        for m in metric:
            c.add(create(m, *args, **kwargs))
        return c
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    name = metric.lower()
    aliases = {"acc": "accuracy", "top_k_accuracy": "topkaccuracy",
               "top_k_acc": "topkaccuracy", "ce": "crossentropy",
               "pearsonr": "pearsoncorrelation"}
    name = aliases.get(name, name)
    if name not in _REGISTRY:
        raise ValueError(f"unknown metric {metric!r}")
    return _REGISTRY[name](*args, **kwargs)


def _np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name, value = [name], [value]
        return list(zip(name, value))

    def update_dict(self, labels, preds):
        self.update(list(labels.values()), list(preds.values()))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred, label = _np(pred), _np(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(np.int64).reshape(-1)
            label = label.astype(np.int64).reshape(-1)
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred, label = _np(pred), _np(label).astype(np.int64).reshape(-1)
            topk = np.argsort(-pred, axis=-1)[:, : self.top_k]
            self.sum_metric += (topk == label[:, None]).any(axis=1).sum()
            self.num_inst += len(label)


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred, label = _np(pred), _np(label).astype(np.int64).reshape(-1)
            p = pred.reshape(-1, pred.shape[-1])[np.arange(len(label)), label]
            self.sum_metric += (-np.log(p + self.eps)).sum()
            self.num_inst += len(label)


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred, label = _np(pred), _np(label).astype(np.int64).reshape(-1)
            p = pred.reshape(-1, pred.shape[-1])[np.arange(len(label)), label]
            nll = -np.log(np.maximum(p, 1e-12))
            if self.ignore_label is not None:
                keep = label != self.ignore_label
                nll, cnt = nll[keep], keep.sum()
            else:
                cnt = len(label)
            self.sum_metric += nll.sum()
            self.num_inst += int(cnt)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, float(np.exp(self.sum_metric / self.num_inst))


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = 0

    def reset(self):
        super().reset()
        if hasattr(self, "tp"):
            self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred, label = _np(pred), _np(label).reshape(-1).astype(np.int64)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.reshape(-1).astype(np.int64)
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.num_inst += len(label)

    def get(self):
        prec = self.tp / max(self.tp + self.fp, 1)
        rec = self.tp / max(self.tp + self.fn, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return self.name, f1


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred, label = _np(pred), _np(label)
            self.sum_metric += np.abs(label.reshape(pred.shape) - pred).mean() * len(pred)
            self.num_inst += len(pred)


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred, label = _np(pred), _np(label)
            self.sum_metric += ((label.reshape(pred.shape) - pred) ** 2).mean() * len(pred)
            self.num_inst += len(pred)


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name=name, **kwargs)

    def get(self):
        name, mse = super().get()
        return name, float(np.sqrt(mse))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)
        self._preds, self._labels = [], []

    def reset(self):
        super().reset()
        self._preds, self._labels = [], []

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._preds.append(_np(pred).reshape(-1))
            self._labels.append(_np(label).reshape(-1))
            self.num_inst += len(self._preds[-1])

    def get(self):
        if not self._preds:
            return self.name, float("nan")
        p = np.concatenate(self._preds)
        l = np.concatenate(self._labels)
        return self.name, float(np.corrcoef(p, l)[0, 1])


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _as_list(preds):
            v = _np(pred)
            self.sum_metric += float(v.sum())
            self.num_inst += v.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kwargs):
        super().__init__(name, **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            v = self._feval(_np(label), _np(pred))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += v
                self.num_inst += 1


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def _box_iou(a, b):
    """IoU of one box [l,t,r,b] against (N,4) boxes."""
    il = np.maximum(a[0], b[:, 0])
    it = np.maximum(a[1], b[:, 1])
    ir = np.minimum(a[2], b[:, 2])
    ib = np.minimum(a[3], b[:, 3])
    iw = np.maximum(ir - il, 0.0)
    ih = np.maximum(ib - it, 0.0)
    inter = iw * ih
    area_a = max(a[2] - a[0], 0.0) * max(a[3] - a[1], 0.0)
    area_b = np.maximum(b[:, 2] - b[:, 0], 0.0) * \
        np.maximum(b[:, 3] - b[:, 1], 0.0)
    union = area_a + area_b - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


@register
class MApMetric(EvalMetric):
    """Detection mean Average Precision (reference
    example/ssd/evaluate/eval_metric.py::MApMetric — TBV).

    update(labels, preds):
      preds:  (B, N, 6) rows [cls_id, score, l, t, r, b] — the
              MultiBoxDetection / box_nms output; cls_id < 0 = invalid.
      labels: (B, M, 5+) rows [cls_id, l, t, r, b, (difficult)]; cls_id < 0
              pads.
    AP integration is area-under-PR (VOC 2010+); VOC07MApMetric overrides
    with the 11-point interpolation the reference publishes VOC07 mAP with.
    """

    def __init__(self, ovp_thresh=0.5, use_difficult=False, class_names=None,
                 pred_idx=0, name="mAP", **kwargs):
        self.ovp_thresh = float(ovp_thresh)
        self.use_difficult = bool(use_difficult)
        self.class_names = list(class_names) if class_names else None
        self.pred_idx = int(pred_idx)
        super().__init__(name, **kwargs)

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        # per-class: list of (score, is_tp) + ground-truth counts
        self._records = {}
        self._gt_counts = {}

    def update(self, labels, preds):
        labels = _as_list(labels)
        preds = _as_list(preds)
        pred = _np(preds[self.pred_idx])
        label = _np(labels[0])
        assert pred.ndim == 3 and pred.shape[-1] >= 6, \
            f"preds must be (B,N,6) detection rows, got {pred.shape}"
        for b in range(pred.shape[0]):
            self._update_one(label[b], pred[b])
        self.num_inst += 1

    def _update_one(self, gts, dets):
        gts = gts[gts[:, 0] >= 0]
        difficult = (gts[:, 5] > 0 if gts.shape[-1] > 5
                     else np.zeros(len(gts), bool))
        for c in np.unique(gts[:, 0]).astype(int):
            n_easy = int(((gts[:, 0] == c) & ~difficult).sum())
            self._gt_counts[c] = self._gt_counts.get(c, 0) + n_easy
        dets = dets[dets[:, 0] >= 0]
        dets = dets[np.argsort(-dets[:, 1])]  # score desc: greedy matching
        matched = np.zeros(len(gts), bool)
        for row in dets:
            c = int(row[0])
            rec = self._records.setdefault(c, [])
            # VOC devkit semantics: argmax IoU over ALL GTs of the class
            # (matched ones included) — a duplicate of an already-matched
            # GT is an FP, it must NOT fall back to the second-best GT
            cand = np.where(gts[:, 0] == c)[0]
            if len(cand) == 0:
                rec.append((float(row[1]), 0))
                continue
            ious = _box_iou(row[2:6], gts[cand, 1:5])
            j = int(np.argmax(ious))
            if ious[j] >= self.ovp_thresh:
                gi = cand[j]
                if difficult[gi] and not self.use_difficult:
                    # VOC devkit: a difficult match is ignored (not tp, not
                    # fp) and the difficult GT is NEVER consumed — later
                    # detections may still match it and be ignored too
                    continue
                if matched[gi]:
                    rec.append((float(row[1]), 0))  # duplicate hit: FP
                else:
                    matched[gi] = True
                    rec.append((float(row[1]), 1))
            else:
                rec.append((float(row[1]), 0))

    def _average_precision(self, rec, prec):
        """VOC 2010+ AP: area under the monotone precision envelope."""
        mrec = np.concatenate([[0.0], rec, [1.0]])
        mpre = np.concatenate([[0.0], prec, [0.0]])
        for i in range(len(mpre) - 2, -1, -1):
            mpre[i] = max(mpre[i], mpre[i + 1])
        idx = np.where(mrec[1:] != mrec[:-1])[0]
        return float(((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]).sum())

    def get(self):
        classes = [c for c, n in self._gt_counts.items() if n > 0]
        if not classes:
            return self.name, float("nan")
        aps = []
        for c in sorted(classes):
            rec = sorted(self._records.get(c, []), key=lambda t: -t[0])
            if not rec:
                aps.append(0.0)
                continue
            tps = np.array([t[1] for t in rec], np.float64)
            tp_cum = np.cumsum(tps)
            fp_cum = np.cumsum(1.0 - tps)
            recall = tp_cum / self._gt_counts[c]
            precision = tp_cum / np.maximum(tp_cum + fp_cum, 1e-12)
            aps.append(self._average_precision(recall, precision))
        if self.class_names:
            names = [f"{self.class_names[c]}_AP" if c < len(self.class_names)
                     else f"class{c}_AP" for c in sorted(classes)]
            return ([self.name] + names,
                    [float(np.mean(aps))] + [float(a) for a in aps])
        return self.name, float(np.mean(aps))


@register
class VOC07MApMetric(MApMetric):
    """11-point interpolated AP (reference VOC07MApMetric — the metric the
    reference's published SSD VOC07 numbers use)."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("name", "VOC07_mAP")
        super().__init__(*args, **kwargs)

    def _average_precision(self, rec, prec):
        ap = 0.0
        for t in np.linspace(0.0, 1.0, 11):
            mask = rec >= t
            ap += (float(prec[mask].max()) if mask.any() else 0.0) / 11.0
        return ap


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient for binary classification
    (reference metric.MCC — TBV): computed from accumulated confusion
    counts so it composes across batches."""

    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self._tp = self._tn = self._fp = self._fn = 0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred, label = _np(pred), _np(label).reshape(-1).astype(np.int64)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.reshape(-1).astype(np.int64)
            self._tp += int(((pred == 1) & (label == 1)).sum())
            self._tn += int(((pred == 0) & (label == 0)).sum())
            self._fp += int(((pred == 1) & (label == 0)).sum())
            self._fn += int(((pred == 0) & (label == 1)).sum())
            self.num_inst += len(label)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        tp, tn, fp, fn = self._tp, self._tn, self._fp, self._fn
        denom = np.sqrt(float(tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        mcc = (tp * tn - fp * fn) / denom if denom > 0 else 0.0
        return self.name, float(mcc)


@register
class NegativeLogLikelihood(EvalMetric):
    """Mean NLL of the labeled class (reference metric.NegativeLogLikelihood)."""

    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _np(pred)
            label = _np(label).astype(np.int64).reshape(-1)
            p = pred.reshape(-1, pred.shape[-1])[np.arange(len(label)), label]
            self.sum_metric += float(-np.log(p + self.eps).sum())
            self.num_inst += len(label)
