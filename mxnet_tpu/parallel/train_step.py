"""ShardedTrainer — the whole training step as ONE sharded XLA program.

Replaces the reference's eager loop + KVStore gradient push/pull
(SURVEY.md §3.2): forward, backward, cross-replica gradient reduction,
and the fused optimizer update all live inside a single ``jax.jit`` over a
device Mesh. Gradient all-reduce over the ``dp`` axis is not a library
call — it falls out of sharding propagation (params replicated over dp,
batch sharded over dp ⇒ XLA inserts psum on the ICI). Tensor-parallel
params shard over ``tp`` by rule table; buffers are donated so weights
update in place in HBM.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ndarray import NDArray
from .functional import functionalize
from .sharding import ShardingRules, batch_sharding

__all__ = ["ShardedTrainer"]

_SUPPORTED = ("sgd", "adam", "adamw")


class ShardedTrainer:
    """Train a gluon Block over a mesh with dp/tp(/sp) shardings.

    Usage::

        mesh = parallel.make_mesh({"dp": 4, "tp": 2})
        trainer = parallel.ShardedTrainer(net, loss_fn, mesh,
                                          rules=net.sharding_rules(),
                                          optimizer="adam",
                                          optimizer_params={"learning_rate": 1e-4})
        for x, y in loader:
            loss = trainer.step(x, y)     # one fused XLA program
        trainer.sync_to_net()             # write weights back for save/eval
    """

    def __init__(self, net, loss_fn, mesh: Mesh, rules: Optional[ShardingRules] = None,
                 optimizer: str = "sgd", optimizer_params: Optional[Dict] = None,
                 input_specs=P("dp"), label_specs=P("dp"), grad_clip: float = -1.0,
                 donate: bool = True, compute_dtype=None,
                 preprocess: Optional[Callable] = None, remat: bool = False,
                 grad_accum: int = 1):
        if optimizer not in _SUPPORTED:
            raise ValueError(f"optimizer {optimizer!r} not in {_SUPPORTED}")
        self.net = net
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.rules = rules or ShardingRules()
        opt = dict(optimizer_params or {})
        self._lr = float(opt.pop("learning_rate", opt.pop("lr", 0.01)))
        self._opt_name = optimizer
        self._opt = opt
        self._grad_clip = grad_clip
        # the per-param update math is the fused engine's lowering
        # (optimizer/fused.py) applied to an Optimizer instance — the sharded
        # and eager/Trainer paths share one implementation and cannot diverge
        from ..optimizer import create as _opt_create

        self._opt_obj = _opt_create(
            optimizer, learning_rate=self._lr,
            clip_gradient=(grad_clip if grad_clip and grad_clip > 0 else None),
            **{k: v for k, v in opt.items() if k != "lr"})
        self._donate = donate
        # AMP: fwd/bwd in compute_dtype (bf16 on the MXU), fp32 master
        # weights + optimizer state. No loss scaling — bf16's exponent range
        # matches fp32 (amp.py documents the same policy).
        self._compute_dtype = (jnp.dtype(compute_dtype)
                               if compute_dtype is not None else None)
        # Traced into the step program, applied to each input before the AMP
        # cast — the fusion point for input normalization when the data
        # pipeline ships raw uint8 (ImageRecordIter(dtype="uint8")): the
        # (x-mean)/std math rides the first conv's HBM read for free instead
        # of burning host CPU + 4x host→device bandwidth.
        self._preprocess = preprocess
        # Rematerialization (jax.checkpoint over the whole forward, matmul
        # results saved): trades recompute FLOPs for activation memory —
        # the long-context lever for sequences whose activations don't fit
        # (and for compile-side buffer pressure). Reference counterpart:
        # mxnet memonger / mirror mode (TBV).
        self._remat = bool(remat)
        # Gradient accumulation: the global batch splits into `grad_accum`
        # micro-batches scanned inside ONE jitted step (grads averaged, one
        # optimizer update). The activation/compile footprint is that of a
        # single micro-batch — the fallback for configs whose full-batch
        # program crashes the compiler (bench seq-4096) or exceeds HBM.
        # BatchNorm-style aux stats keep the LAST micro-batch's update.
        self._grad_accum = int(grad_accum)
        if self._grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")

        self._t = 0
        # XLA cost/memory record of the compiled step (obs/device.py),
        # filled at first compile when device capture is active — the
        # analytic-MFU numerator bench.py reports beside measured MFU;
        # _aot_step holds (batch avals, AOT executable) for that signature
        self.step_cost: Optional[Dict] = None
        self._aot_step = None
        self._in_sh = batch_sharding(mesh, input_specs if isinstance(input_specs, P)
                                     else P(*input_specs))
        self._label_sh = batch_sharding(mesh, label_specs if isinstance(label_specs, P)
                                        else P(*label_specs))
        self._step_fn = None
        self._captured = False
        self._params = {}
        self._grad_names = []
        self.param_vals = {}
        self._param_shardings = {}
        self.opt_state = {}
        # Deferred-shape params (BatchNorm with in_channels=0 etc.) are still
        # None here; capture must wait until the first step resolves shapes —
        # capturing early would silently freeze those params out of training.
        if not any(p._data is None for p in net._iter_params()):
            self._capture()

    def _capture(self):
        """Snapshot the (now fully materialized) parameter set into sharded
        device values + optimizer state. Runs once, at construction when all
        shapes are known, else at the first step()."""
        net, mesh = self.net, self.mesh
        self._params = {p.name: p for p in net._iter_params() if p._data is not None}
        self._grad_names = [n for n, p in self._params.items() if p.grad_req != "null"]
        names, self._apply = functionalize(net, train=True)
        self._names = names

        # place parameter values per the rule table
        self.param_vals = {}
        self._param_shardings = {}
        for n, p in self._params.items():
            sh = self.rules.sharding_for(n, mesh, p.data().shape)
            self._param_shardings[n] = sh
            val = p.data()._data
            if self._donate:
                # donation consumes the step's param inputs, and a no-op
                # device_put ALIASES val with the gluon parameter's own
                # buffer — step 1 would then delete the parameter under
                # gluon's feet (net() after step() raised "Array has been
                # deleted"). A private copy keeps the donated generation
                # exclusively the trainer's; sync_to_net() still writes
                # trained weights back.
                val = jnp.array(val, copy=True)
            self.param_vals[n] = jax.device_put(val, sh)
        self.opt_state = {n: self._init_state(self.param_vals[n])
                          for n in self._grad_names}
        self._captured = True

    # ------------------------------------------------------------------
    def _init_state(self, val):
        zeros = lambda: jnp.zeros_like(val)  # noqa: E731
        if self._opt_name == "sgd":
            if self._opt.get("momentum", 0.0):
                return (zeros(),)
            return ()
        return (zeros(), zeros())  # adam/adamw mean, var

    def _update_one(self, w, g, state, lr, t):
        from ..optimizer.fused import lower_update

        o = self._opt
        # map the sharded state tuples onto the Updater slot layout the
        # lowering expects: sgd () -> None, sgd-momentum (m,) -> m
        if self._opt_name == "sgd":
            st = state[0] if state else None
        else:
            st = state
        new_w, new_st, _ = lower_update(
            self._opt_obj, w, g, st, lr=lr, wd=o.get("wd", 0.0), t=t,
            rescale=o.get("rescale_grad", 1.0))
        if self._opt_name == "sgd":
            return new_w, (() if new_st is None else (new_st,))
        return new_w, new_st

    # ------------------------------------------------------------------
    def _build(self, n_extra_inputs):
        grad_names = self._grad_names

        cdt = self._compute_dtype
        # AMP policy (reference contrib/amp: FP32 op list keeps norms' stats):
        # cast trainable weights + inputs to the compute dtype; statistics
        # buffers (grad_req="null" — BN running mean/var) keep the master
        # dtype so moving averages don't accumulate bf16 rounding.
        stat_names = {n for n, p in self._params.items() if p.grad_req == "null"}

        def _cast(x):
            if cdt is not None and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(cdt)
            return x

        pre = self._preprocess

        accum = self._grad_accum

        def step_fn(param_vals, opt_state, lr, t, *batch):
            if pre is not None:
                batch = tuple(pre(b) for b in batch[:-1]) + batch[-1:]
            if cdt is not None:
                batch_cast = tuple(_cast(b) for b in batch[:-1]) + batch[-1:]
            else:
                batch_cast = batch

            def loss_f(grad_part, batch_c):
                full = dict(param_vals)
                full.update(grad_part)
                if cdt is not None:
                    full = {k: (v if k in stat_names else _cast(v))
                            for k, v in full.items()}
                out, aux = self._apply(full, *batch_c[:-1])
                outs = out if isinstance(out, tuple) else (out,)
                loss_nd = self.loss_fn(*[NDArray(o) for o in outs],
                                       NDArray(batch_c[-1]))
                loss_val = jnp.mean(loss_nd._data)
                return loss_val, aux

            grad_part = {n: param_vals[n] for n in grad_names}
            loss_f_used = loss_f
            if self._remat:
                # save matmul outputs, recompute the elementwise tail — the
                # standard transformer remat policy
                policy = getattr(jax.checkpoint_policies,
                                 "dots_with_no_batch_dims_saveable", None)
                loss_f_used = jax.checkpoint(loss_f, policy=policy)
            if accum > 1:
                for b in batch_cast:
                    if b.shape[0] % accum:
                        raise ValueError(
                            f"grad_accum={accum} does not divide batch "
                            f"dim {b.shape[0]}")
                micro = tuple(
                    b.reshape((accum, b.shape[0] // accum) + b.shape[1:])
                    for b in batch_cast)

                def body(acc, mb):
                    (l_, aux_), g_ = jax.value_and_grad(
                        loss_f_used, has_aux=True)(grad_part, mb)
                    return (jax.tree_util.tree_map(jnp.add, acc, g_),
                            (l_, aux_))

                zero = jax.tree_util.tree_map(jnp.zeros_like, grad_part)
                grads, (losses, auxs) = jax.lax.scan(body, zero, micro)
                grads = jax.tree_util.tree_map(lambda g_: g_ / accum, grads)
                loss = jnp.mean(losses)
                aux = jax.tree_util.tree_map(lambda ys: ys[-1], auxs)
            else:
                (loss, aux), grads = jax.value_and_grad(
                    loss_f_used, has_aux=True)(grad_part, batch_cast)
            new_params = dict(param_vals)
            new_state = {}
            for n in grad_names:
                new_w, st = self._update_one(param_vals[n], grads[n],
                                             opt_state[n], lr, t)
                new_params[n] = new_w.astype(param_vals[n].dtype)
                new_state[n] = st
            # BatchNorm moving stats etc. — keep master dtype under AMP
            new_params.update({k: (v.astype(param_vals[k].dtype)
                                   if k in param_vals else v)
                               for k, v in aux.items()})
            return loss, new_params, new_state

        in_shardings = (
            self._param_shardings,
            {n: tuple(self._param_shardings[n] for _ in self.opt_state[n])
             for n in grad_names},
            None, None,
            *([self._in_sh] * n_extra_inputs),
            self._label_sh,
        )
        out_shardings = (NamedSharding(self.mesh, P()), self._param_shardings,
                         in_shardings[1])
        donate = (0, 1) if self._donate else ()
        # kept for profiling harnesses (tools/profile_lm.py): the un-jitted
        # step can be lax.scan-chained to time pure device work with one
        # dispatch, which per-call wall timing through the axon tunnel can't
        self._raw_step_fn = step_fn
        return jax.jit(step_fn, in_shardings=in_shardings,
                       out_shardings=out_shardings, donate_argnums=donate)

    # ------------------------------------------------------------------
    def step(self, *batch):
        """batch = (*inputs, labels); returns the (device) loss scalar."""
        if not self._captured:
            if any(p._data is None for p in self.net._iter_params()):
                # resolve deferred shapes with one throwaway eager forward
                # (pause() also switches training mode off for the duration)
                from .. import autograd

                with autograd.pause():
                    ins = [b._data if isinstance(b, NDArray) else jnp.asarray(b)
                           for b in batch[:-1]]
                    if self._preprocess is not None:
                        ins = [self._preprocess(b) for b in ins]
                    self.net(*[NDArray(b) for b in ins])
            self._capture()
        vals = [b._data if isinstance(b, NDArray) else jnp.asarray(b) for b in batch]
        vals = [jax.device_put(v, self._in_sh if i < len(vals) - 1 else self._label_sh)
                for i, v in enumerate(vals)]
        from .mesh import mesh_scope

        if self._step_fn is None:
            self._step_fn = self._build(len(vals) - 1)
            from ..obs import device as _device

            if _device.active():
                # device-plane accounting (obs/device.py): AOT-compile the
                # step ONCE inside the mesh scope — XLA flops/bytes/HBM into
                # step_cost (bench.py's analytic-MFU source), the same
                # executable kept for matching batches. Keyed by the batch
                # avals: an AOT Compiled cannot retrace, so a later ragged
                # batch must fall back to the jit wrapper, not crash
                sig = tuple((tuple(v.shape), str(v.dtype)) for v in vals)
                with mesh_scope(self.mesh):
                    compiled, cost = _device.capture(
                        self._step_fn,
                        (self.param_vals, self.opt_state,
                         jnp.float32(self._lr), jnp.float32(self._t + 1),
                         *vals),
                        site="train_step", label=type(self.net).__name__)
                if compiled is not None:
                    self._aot_step = (sig, compiled)
                self.step_cost = cost
        self._t += 1
        step = self._step_fn
        if self._aot_step is not None and self._aot_step[0] == tuple(
                (tuple(v.shape), str(v.dtype)) for v in vals):
            step = self._aot_step[1]
        with mesh_scope(self.mesh):  # attention layers pick sp/ring impls
            loss, self.param_vals, self.opt_state = step(
                self.param_vals, self.opt_state, jnp.float32(self._lr),
                jnp.float32(self._t), *vals)
        return NDArray(loss)

    # ------------------------------------------------------------------
    @property
    def learning_rate(self):
        return self._lr

    def set_learning_rate(self, lr):
        self._lr = float(lr)

    def sync_to_net(self):
        """Copy sharded weights back into the gluon parameters (gathered)."""
        from .. import autograd

        for n, p in self._params.items():
            val = self.param_vals[n]
            gathered = jax.device_get(val)
            with autograd.pause():
                p.data()._set_data(jnp.asarray(gathered))

    def block_until_ready(self):
        jax.block_until_ready(self.param_vals)
