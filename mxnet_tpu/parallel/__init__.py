"""Distributed / parallel execution — the TPU-native replacement for the
reference's KVStore+NCCL+ps-lite stack (SURVEY.md §2.4, §5.8).

The reference scales by data parallelism in five flavors (local/device/
nccl/dist_sync/dist_async), all implemented as explicit gradient
communication around an eager training loop. On TPU the whole training
step — forward, backward, gradient all-reduce, optimizer — is ONE jitted
XLA program over a ``jax.sharding.Mesh``; XLA inserts the ICI collectives
from sharding annotations. This package provides:

- :mod:`mesh` — mesh construction over dp/tp/pp/sp axes (ICI-major order).
- :mod:`sharding` — regex rules mapping parameter names to PartitionSpecs.
- :mod:`functional` — lift a gluon Block into a pure ``apply(params, *in)``.
- :mod:`train_step` — :class:`ShardedTrainer`: the fused sharded train step
  (dp grad reduction + tp param sharding + optional bf16 compute).
- :mod:`ring_attention` — sequence-parallel blockwise attention over the
  mesh's ``sp`` axis via ``shard_map`` + ``ppermute`` (a capability the
  reference lacks — SURVEY.md §5.7).
"""
from .mesh import (make_mesh, mesh_axes, local_device_count, mesh_scope,  # noqa: F401
                   current_mesh, mesh_slices)
from .sharding import (ShardingRules, param_sharding, batch_sharding,  # noqa: F401
                       replicated)
from .functional import functionalize  # noqa: F401
from .train_step import ShardedTrainer  # noqa: F401
from .ring_attention import ring_attention, sequence_sharded_attention  # noqa: F401
