"""Lift a gluon Block into a pure function over a parameter dict.

This is the bridge between the mutable gluon API and pjit: the same
rebinding trick CachedOp uses (gluon/block.py), exposed standalone so the
sharded train step can ``jax.value_and_grad`` through any Block.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Tuple

import jax

from .. import autograd
from ..ndarray import NDArray

__all__ = ["functionalize"]


def functionalize(net, train: bool = True) -> Tuple[List[str], Callable]:
    """Return ``(param_names, apply)`` where
    ``apply(param_vals, *inputs) -> (outputs, aux_updates)``.

    - ``param_vals``: dict name → jax.Array (or tracer).
    - ``outputs``: jax value or tuple of them.
    - ``aux_updates``: dict name → new value for parameters the forward
      mutated in place (BatchNorm moving stats); merge these back after the
      step. The dict's key set is trace-stable for a fixed train mode.

    ``apply`` is pure/traceable: parameters are swapped in by name, the
    forward runs over tracers, and the original buffers are restored.
    """
    params = [p for p in net._iter_params() if p._data is not None]
    names = [p.name for p in params]
    if len(set(names)) != len(names):
        raise ValueError("duplicate parameter names; cannot functionalize")

    def apply(param_vals: Dict[str, jax.Array], *inputs):
        nds = [p.data() for p in params]
        saved = [nd_._data for nd_ in nds]
        injected = [param_vals[n] for n in names]
        try:
            for nd_, val in zip(nds, injected):
                nd_._data = val
            in_nds = [NDArray(x) if not isinstance(x, NDArray) else x for x in inputs]
            old_rec = autograd.set_recording(False)
            old_train = autograd.set_training(train)
            try:
                out = net(*in_nds)
            finally:
                autograd.set_recording(old_rec)
                autograd.set_training(old_train)
            aux = {}
            for nd_, name, inj in zip(nds, names, injected):
                if nd_._data is not inj:
                    aux[name] = nd_._data
            if isinstance(out, (list, tuple)):
                return tuple(o._data for o in out), aux
            return out._data, aux
        finally:
            for nd_, s in zip([p.data() for p in params], saved):
                nd_._data = s

    return names, apply
