"""Sharding rules: parameter-name regexes → PartitionSpecs.

The reference has no tensor-parallel sharding (SURVEY.md §2.4 — TP absent);
its only placement mechanism is whole-array device assignment
(``__ctx_group__``). Here placement is declarative: a rule table maps
parameter names to ``PartitionSpec`` axes over the mesh, XLA inserts the
collectives (the scaling-book recipe).
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ShardingRules", "param_sharding", "batch_sharding", "replicated"]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


class ShardingRules:
    """Ordered (regex, PartitionSpec) table; first match wins.

    Specs may name mesh axes absent from the actual mesh — those collapse to
    None (replicated), so one rule table serves dp-only, dp×tp, dp×tp×sp …
    meshes unchanged.
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, P]]] = None,
                 default: P = P()):
        self.rules: List[Tuple[re.Pattern, P]] = [
            (re.compile(pat), spec) for pat, spec in (rules or [])]
        self.default = default

    def add(self, pattern: str, spec: P) -> "ShardingRules":
        self.rules.append((re.compile(pattern), spec))
        return self

    def spec_for(self, name: str, shape=None, mesh: Optional[Mesh] = None) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                return _prune(spec, mesh, shape)
        return _prune(self.default, mesh, shape)

    def sharding_for(self, name: str, mesh: Mesh, shape=None) -> NamedSharding:
        return NamedSharding(mesh, self.spec_for(name, shape, mesh))

    def tree_shardings(self, mesh: Mesh, named_shapes: Dict[str, tuple]):
        return {name: self.sharding_for(name, mesh, shape)
                for name, shape in named_shapes.items()}


def _prune(spec: P, mesh: Optional[Mesh], shape=None) -> P:
    """Drop axes not present in the mesh or not dividing the dim size."""
    if mesh is None:
        return spec
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    out = []
    for i, ax in enumerate(spec):
        keep = None
        if ax is not None:
            axs = ax if isinstance(ax, tuple) else (ax,)
            if all(a in sizes for a in axs):
                total = 1
                for a in axs:
                    total *= sizes[a]
                if shape is None or (i < len(shape) and shape[i] % total == 0):
                    keep = ax
        out.append(keep)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def param_sharding(mesh: Mesh, rules: ShardingRules, named_shapes: Dict[str, tuple]):
    return rules.tree_shardings(mesh, named_shapes)


def batch_sharding(mesh: Mesh, spec: P = P("dp"), shape=None) -> NamedSharding:
    """Sharding for a batch-leading array. ``shape`` (optional) prunes axes
    that do not divide the corresponding dim — the serving engine passes
    each bucket's padded shape so a bucket not divisible by ``dp`` falls
    back to replicated instead of failing placement."""
    return NamedSharding(mesh, _prune(spec, mesh, shape))
