"""Ring attention — sequence/context parallelism over the mesh ``sp`` axis.

The reference has NO sequence parallelism (SURVEY.md §5.7); its max context
is bounded by one GPU's memory. This module removes that bound the TPU way:
Q stays resident per shard while K/V blocks rotate around the ring via
``lax.ppermute`` (neighbor exchanges ride the ICI torus), accumulating
online-softmax statistics — blockwise attention with O(seq/n_shards) live
memory per chip. Pattern follows the public ring-attention formulation
(Liu et al.) and the jax shard_map collective idiom.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

__all__ = ["ring_attention", "sequence_sharded_attention", "plain_attention"]


def plain_attention(q, k, v, mask=None, causal=False, scale=None):
    """Single-device reference attention. q,k,v: (B, H, S, D)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        scores = jnp.where(cm, scores, -jnp.inf)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def _ring_body(q, k, v, axis_name, causal, scale):
    """Per-shard ring loop. q,k,v are the LOCAL blocks (B, H, s_loc, D)."""
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    s_loc = q.shape[-2]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)

    def scores_for(k_blk, src_idx):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk).astype(jnp.float32) * scale
        if causal:
            # global positions: rows my_idx*s_loc + i, cols src_idx*s_loc + j
            rows = my_idx * s_loc + jnp.arange(s_loc)[:, None]
            cols = src_idx * s_loc + jnp.arange(s_loc)[None, :]
            s = jnp.where(rows >= cols, s, -jnp.inf)
        return s

    def step(carry, _):
        k_blk, v_blk, src_idx, m, num, den = carry
        s = scores_for(k_blk, src_idx)
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        # guard -inf rows (fully masked block): exp(-inf - -inf) -> exp(0)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m - new_m))
        p = jnp.exp(s - new_m)
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        num = num * corr + jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype),
                                      v_blk).astype(jnp.float32)
        den = den * corr + jnp.sum(p, axis=-1, keepdims=True)
        # rotate k/v to the next rank on the ring (neighbor ICI hop)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        src_next = (src_idx - 1) % n
        return (k_next, v_next, src_next, new_m, num, den), None

    b, h, _, d = q.shape
    m0 = jnp.full((b, h, s_loc, 1), -jnp.inf, jnp.float32)
    num0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    den0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    # mark device-invariant carry inits as varying over the ring axis (the
    # loop makes them device-dependent; required by shard_map's vma check)
    def _vary(x):
        # target: the same varying axes as the data (q is sharded over every
        # mesh axis in play, so its vma is the loop-carry's steady state)
        try:
            target = set(jax.typeof(q).vma) | {axis_name}
            missing = tuple(sorted(target - set(jax.typeof(x).vma)))
        except (AttributeError, TypeError):
            return x
        if not missing:
            return x
        if hasattr(lax, "pcast"):
            return lax.pcast(x, missing, to="varying")
        return lax.pvary(x, missing)

    my_idx, m0, num0, den0 = (_vary(x) for x in (my_idx, m0, num0, den0))
    (k_f, v_f, _, m, num, den), _ = lax.scan(
        step, (k, v, my_idx, m0, num0, den0), None, length=n)
    out = num / jnp.maximum(den, 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Call INSIDE shard_map with q,k,v sequence-sharded over ``axis_name``."""
    return _ring_body(q, k, v, axis_name, causal, scale)


def sequence_sharded_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                               causal: bool = False, scale=None,
                               batch_axis: str = "dp", head_axis: str = "tp"):
    """Global-view attention sharded (B over dp, H over tp, S over sp).

    q,k,v: (B, H, S, D) global arrays (or tracers under an enclosing pjit).
    Returns same-shaped output. Uses shard_map + ring rotation; degenerate
    1-shard meshes reduce to plain attention.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if sizes.get(axis_name, 1) == 1:
        return plain_attention(q, k, v, causal=causal, scale=scale)
    b_ax = batch_axis if sizes.get(batch_axis, 1) > 1 else None
    h_ax = head_axis if sizes.get(head_axis, 1) > 1 else None
    spec = P(b_ax, h_ax, axis_name, None)
    fn = shard_map(partial(_ring_body, axis_name=axis_name, causal=causal,
                           scale=scale),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
