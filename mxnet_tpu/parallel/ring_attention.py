"""Ring attention — sequence/context parallelism over the mesh ``sp`` axis.

The reference has NO sequence parallelism (SURVEY.md §5.7); its max context
is bounded by one GPU's memory. This module removes that bound the TPU way:
Q stays resident per shard while K/V blocks rotate around the ring via
``lax.ppermute`` (neighbor exchanges ride the ICI torus). Each visiting K/V
block is attended with a **blockwise kernel returning (out, lse)** — the
same statistics our Pallas flash kernel (ops/flash_attention.py) produces —
and per-block results merge with the standard logsumexp combine. So the ring
is literally flash attention distributed over chips: per-block math can run
the Pallas kernel (long local blocks) or fused XLA einsums (short blocks),
and live memory is O(seq/n_shards) per chip either way.

Pattern follows the public ring-attention formulation (Liu et al.) and the
jax shard_map collective idiom.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..ops.attention import plain_attention  # re-export (compat)
from ..ops.flash_attention import flash_attention_with_lse

__all__ = ["ring_attention", "sequence_sharded_attention", "plain_attention"]

_NEG = -1e30  # matches ops/flash_attention._NEG_INF
# per-shard sequence length at which the Pallas kernel takes over block math
_FLASH_BLOCK_MIN_SEQ = 1024


def _block_attn_einsum(q, k_blk, v_blk, rel, s_loc, my_idx, src_idx, scale,
                       causal):
    """(out, lse) of one K/V block via fused XLA einsums. rel unused."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk).astype(jnp.float32) * scale
    if causal:
        rows = my_idx * s_loc + jnp.arange(s_loc)[:, None]
        cols = src_idx * s_loc + jnp.arange(s_loc)[None, :]
        s = jnp.where(rows >= cols, s, _NEG)
    m = jnp.max(s, axis=-1)                       # (B,H,sq)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(s <= _NEG / 2, 0.0, p)
    den = jnp.sum(p, axis=-1)
    safe = jnp.maximum(den, 1e-30)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v_blk) \
        .astype(jnp.float32) / safe[..., None]
    lse = jnp.where(den > 0, m + jnp.log(safe), _NEG)
    return out, lse


def _block_attn_flash(q, k_blk, v_blk, rel, s_loc, my_idx, src_idx, scale,
                      causal):
    """(out, lse) of one block via the Pallas flash kernel.

    The kernel's dynamic causal offset makes one call serve every visiting
    block: offset = (my - src)·s_loc is ≥ s_loc for fully-visible blocks,
    0 on the diagonal, and ≤ -s_loc for masked blocks (which then run zero
    K/V iterations inside the kernel).
    """
    offset = (my_idx - src_idx) * s_loc
    o, l = flash_attention_with_lse(q, k_blk, v_blk, causal=causal,
                                    scale=scale, offset=offset)
    return o.astype(jnp.float32), l


def _combine(o, lse, o_blk, lse_blk):
    """Merge two normalized (out, lse) pairs — flash's logsumexp algebra."""
    new = jnp.maximum(lse, lse_blk)
    w1 = jnp.where(lse <= _NEG / 2, 0.0, jnp.exp(lse - new))
    w2 = jnp.where(lse_blk <= _NEG / 2, 0.0, jnp.exp(lse_blk - new))
    den = w1 + w2
    safe = jnp.maximum(den, 1e-30)
    o_new = (o * w1[..., None] + o_blk * w2[..., None]) / safe[..., None]
    lse_new = jnp.where(den > 0, new + jnp.log(safe), _NEG)
    return o_new, lse_new


def _ring_body(q, k, v, axis_name, causal, scale, use_flash=None):
    """Per-shard ring loop. q,k,v are the LOCAL blocks (B, H, s_loc, D)."""
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    s_loc = q.shape[-2]
    import math

    scale = float(scale) if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if use_flash is None:
        use_flash = (s_loc >= _FLASH_BLOCK_MIN_SEQ and s_loc % 8 == 0)
    block_attn = _block_attn_flash if use_flash else _block_attn_einsum

    b, h, _, d = q.shape
    o = jnp.zeros((b, h, s_loc, d), jnp.float32)
    lse = jnp.full((b, h, s_loc), _NEG, jnp.float32)

    # Unrolled ring (n is the static sp mesh size): attend the visiting K/V
    # block, merge via the lse combine, rotate K/V one neighbor hop.
    # Unrolling lets XLA overlap each ppermute with the next block's compute
    # (and sidesteps scan-around-custom_vjp lowering limits).
    k_blk, v_blk, src_idx = k, v, my_idx
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        rel = jnp.where(src_idx < my_idx, 0,
                        jnp.where(src_idx == my_idx, 1, 2))
        o_blk, lse_blk = block_attn(q, k_blk, v_blk, rel, s_loc, my_idx,
                                    src_idx, scale, causal)
        o, lse = _combine(o, lse, o_blk, lse_blk)
        if step != n - 1:  # last block needs no rotation
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            src_idx = (src_idx - 1) % n
    return o.astype(q.dtype)


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   use_flash=None):
    """Call INSIDE shard_map with q,k,v sequence-sharded over ``axis_name``."""
    return _ring_body(q, k, v, axis_name, causal, scale, use_flash=use_flash)


def sequence_sharded_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                               causal: bool = False, scale=None,
                               batch_axis: str = "dp", head_axis: str = "tp",
                               use_flash=None):
    """Global-view attention sharded (B over dp, H over tp, S over sp).

    q,k,v: (B, H, S, D) global arrays (or tracers under an enclosing pjit).
    Returns same-shaped output. Uses shard_map + ring rotation; degenerate
    1-shard meshes reduce to plain attention.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if sizes.get(axis_name, 1) == 1:
        return plain_attention(q, k, v, causal=causal, scale=scale)
    b_ax = batch_axis if sizes.get(batch_axis, 1) > 1 else None
    h_ax = head_axis if sizes.get(head_axis, 1) > 1 else None
    spec = P(b_ax, h_ax, axis_name, None)
    kwargs = {}
    try:  # replication tracking can't see through pallas_call yet (jax
        # suggests disabling it); the flag is check_rep up to jax 0.4.x
        # and check_vma after the shard_map graduation — probe for either
        import inspect

        params = inspect.signature(shard_map).parameters
        for flag in ("check_vma", "check_rep"):
            if flag in params:
                kwargs[flag] = False
                break
    except (ValueError, TypeError):
        pass
    fn = shard_map(partial(_ring_body, axis_name=axis_name, causal=causal,
                           scale=scale, use_flash=use_flash),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                   **kwargs)
    return fn(q, k, v)
