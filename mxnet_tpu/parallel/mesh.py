"""Device-mesh construction.

Replaces the reference's device-list plumbing (``ctx=[mx.gpu(i) ...]`` +
KVStore comm trees — SURVEY.md §2.4) with ``jax.sharding.Mesh``. Axis
conventions follow the scaling-book recipe: the innermost (fastest-varying)
mesh axes carry the heaviest collectives, so order axes ("pp", "dp", "sp",
"tp") — tp innermost rides the tightest ICI loops.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "mesh_axes", "local_device_count", "mesh_scope",
           "current_mesh", "mesh_slices"]

AXIS_ORDER = ("pp", "dp", "sp", "tp", "ep")


def local_device_count() -> int:
    return len(jax.devices())


def make_mesh(axes: Optional[Dict[str, int]] = None, devices=None) -> Mesh:
    """Build a Mesh from an axis-size dict, e.g. ``{"dp": 2, "tp": 4}``.

    A single ``-1`` axis absorbs the remaining devices. Axes are laid out in
    AXIS_ORDER with tp innermost (contiguous devices → shortest ICI paths).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(axes or {"dp": n})
    known = 1
    wild = None
    for k, v in axes.items():
        if v == -1:
            if wild is not None:
                raise ValueError("only one axis may be -1")
            wild = k
        else:
            known *= v
    if wild is not None:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        axes[wild] = n // known
        known *= axes[wild]
    if known > n:
        raise ValueError(f"mesh axes {axes} need {known} devices, have {n}")
    # fully-specified mesh smaller than the host: take the first `known`
    # devices (reference analog: ctx=[mx.gpu(i) for i in ...] picks a subset)
    if known < n:
        import warnings

        warnings.warn(
            f"make_mesh: axes {axes} cover {known} of {n} available devices; "
            f"using the first {known} (pass an axis of -1 to absorb the rest)",
            stacklevel=2)
    devices = devices[:known]
    names = [a for a in AXIS_ORDER if a in axes] + \
            [a for a in axes if a not in AXIS_ORDER]
    shape = tuple(axes[a] for a in names)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(names))


def mesh_axes(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def mesh_slices(mesh: Mesh, axis: str = "dp") -> List[Mesh]:
    """Split ``mesh`` along ``axis`` into independent submeshes — one per
    index along that axis, each keeping every remaining axis. This is the
    serve plane's replica-group placement (serve/fleet.py
    ``ReplicaPool.sharded``): a ``dp4×tp2`` mesh yields four disjoint
    2-device ``tp`` slices, each hosting one tensor-parallel replica while
    data parallelism happens *across* slices via the Router.

    A mesh without ``axis`` is a single slice (itself). A pure-``axis``
    mesh (no other axes) yields 1-device slices carrying a trivial
    ``("tp",)`` axis so sharding rule tables prune against them unchanged.
    """
    if axis not in mesh.axis_names:
        return [mesh]
    i = mesh.axis_names.index(axis)
    names = tuple(a for a in mesh.axis_names if a != axis)
    out = []
    for k in range(mesh.devices.shape[i]):
        # np.take collapses a 1-axis mesh to a bare Device scalar —
        # re-wrap so both branches hold an ndarray
        sub = np.asarray(np.take(mesh.devices, k, axis=i))
        if not names:
            out.append(Mesh(sub.reshape(1), ("tp",)))
        else:
            out.append(Mesh(sub, names))
    return out


# ---------------------------------------------------------------------------
# Mesh scope: lets model code (e.g. attention layers) discover the active
# mesh during a sharded trace and pick collective implementations (ring
# attention over "sp") without threading the mesh through every call.
# ---------------------------------------------------------------------------

import contextlib as _contextlib
import threading as _threading


class _MeshState(_threading.local):
    def __init__(self):
        self.mesh: Optional[Mesh] = None


_STATE = _MeshState()


def current_mesh() -> Optional[Mesh]:
    return _STATE.mesh


@_contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    prev, _STATE.mesh = _STATE.mesh, mesh
    try:
        yield mesh
    finally:
        _STATE.mesh = prev
