"""Persistent AOT program cache — O(deserialize) cold start.

The deployment story (bind once, serve many — PAPER.md's Module/Executor
contract) assumes program construction is cheap relative to serving. It is
not: every new serve replica pays a full XLA compilation per shape bucket
at ``warmup()``, and the fused update engine recompiles its one-program
step at every train start — the single biggest obstacle to spawning
replicas on demand (serve/autoscale.py) and to fast elastic rejoin
(kvstore/elastic.py). PR 8 already AOT-compiles every choke-point program
once (``jit.lower().compile()``) and keys it in the device-plane
(site,label) cost registry; this module turns that *identity* store into a
*persistent cross-process* cache:

- **One key derivation** (:func:`program_key`): ``serve/engine.py``,
  ``optimizer/fused.py``, and the ``obs/device.py`` registry all derive
  their program identity through this one function — a
  :class:`ProgramKey` carries the (site, label) the device registry files
  under plus a canonical SHA-256 ``digest`` over the program's statics
  (graph/optimizer fingerprint, avals, toggles). The same digest lands in
  ``compile_log`` entries, device cost records, and cache filenames, so
  the three surfaces can never key the same program differently.
- **Executable serialization** (:meth:`ProgramCache.put` / ``get``): a
  compiled ``jax.stages.Compiled`` is exported via
  ``jax.experimental.serialize_executable`` (XLA's own executable
  serialization — the deserialized program is the *same machine code*, so
  a cache hit is bitwise-identical to the compile it replaced). Backends
  that refuse executable export degrade to jax's persistent
  *compilation* cache (:func:`enable_jax_fallback_cache`) — slower than
  a deserialize but still skips XLA optimization on re-compiles.
- **Never a wrong program**: every entry embeds an environment
  fingerprint (backend platform + device kind + topology + jax/jaxlib
  versions + an ``mxnet_tpu`` source-tree content hash) checked before
  deserialization. A stale, foreign-platform, truncated, or CRC-corrupt
  entry is a *structured MISS/REJECT* — counted
  (``progcache.{hit,miss,reject,write}`` metrics + obs events) and
  degraded to a plain compile, never a crash, never a wrong program.
- **Crash-safe writes**: the ``checkpoint/`` idiom — temp + fsync +
  rename, per-entry CRC32, keep-last-N GC (``MXNET_PROGCACHE_KEEP``).

Activation: ``MXNET_PROGCACHE_DIR=<dir>`` (or ``MXNET_PROGCACHE=1`` with
the default ``~/.cache/mxnet_tpu/progcache``) arms the process-global
cache; ``MXNET_PROGCACHE=0`` vetoes it even with a dir set. Serving
artifacts can also ship their executables: ``serve.ship_programs`` writes
an engine's compiled buckets into a ``programs/`` payload next to the
artifact and ``serve.load`` warms from it (docs/PERFORMANCE.md "Program
cache and cold start").
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import struct
import threading
import time
from typing import Any, Dict, NamedTuple, Optional

from .checkpoint.atomic import atomic_write_bytes, crc32_bytes

__all__ = ["ProgramKey", "ProgramCache", "CacheEntry", "program_key",
           "env_fingerprint", "code_fingerprint", "active", "cache",
           "configure", "aot_compile", "serialize_compiled",
           "enable_jax_fallback_cache", "default_dir", "reset"]

# entry format version — bump on any layout/semantic change so old caches
# read as structured rejects, not parse errors
_MAGIC = b"MXPROG1\n"
_SCHEMA = 1

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


# ---------------------------------------------------------------------------
# key derivation — THE one place a program's identity is computed
# ---------------------------------------------------------------------------

class ProgramKey(NamedTuple):
    """A program's identity: the (site, label) the device-plane registry
    files cost records under, plus the canonical digest over its statics.
    Built only by :func:`program_key` so every surface derives identically.
    """
    site: str
    label: str
    digest: str


def _canon(obj) -> Any:
    """Canonicalize arbitrary static key parts into a deterministic,
    JSON-able structure. Types become qualified names, mappings sort by
    key, sets sort; anything else falls back to ``repr`` (tuples of
    primitives — the aval idiom — repr deterministically)."""
    if isinstance(obj, type):
        return f"{obj.__module__}.{obj.__qualname__}"
    if isinstance(obj, (str, int, bool)) or obj is None:
        return obj
    if isinstance(obj, float):
        return repr(obj)  # repr(f) roundtrips; json would re-round
    if isinstance(obj, bytes):
        return hashlib.sha256(obj).hexdigest()
    if isinstance(obj, dict):
        return {"__map__": sorted((str(k), _canon(v))
                                  for k, v in obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_canon(x) for x in obj]
    if isinstance(obj, (set, frozenset)):
        return {"__set__": sorted(repr(_canon(x)) for x in obj)}
    return repr(obj)


def program_key(site: str, label: str, statics: Any = ()) -> ProgramKey:
    """Derive a program's :class:`ProgramKey` from its compile statics.

    ``site``/``label`` follow the device-plane registry convention
    ("serve"/"bucket32", "update"/"Adam", ...); ``statics`` is everything
    that determines the traced program short of traced-argument *values*
    (graph fingerprint, avals, static hyperparameters, toggles). Two call
    sites passing equal statics get equal digests in any process."""
    blob = json.dumps([_SCHEMA, site, label, _canon(statics)],
                      sort_keys=True, separators=(",", ":"))
    return ProgramKey(site, label,
                      hashlib.sha256(blob.encode("utf-8")).hexdigest())


# ---------------------------------------------------------------------------
# environment fingerprint — when ANY of this drifts, entries MISS
# ---------------------------------------------------------------------------

_code_fp_cache: list = [None]
_env_fp_cache: list = [None]
# reentrant: env_fingerprint() computes code_fingerprint() under it
_fp_lock = threading.RLock()


def code_fingerprint() -> str:
    """Content hash over every ``mxnet_tpu/**/*.py`` source file. Programs
    are traced from this package's code, so a source change anywhere in it
    invalidates the cache — coarse, but the failure mode of a finer map
    (a stale program served after a lowering edit) is a silently wrong
    model. Computed once per process."""
    if _code_fp_cache[0] is not None:
        return _code_fp_cache[0]
    with _fp_lock:
        if _code_fp_cache[0] is not None:
            return _code_fp_cache[0]
        root = os.path.dirname(os.path.abspath(__file__))
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in sorted(os.walk(root)):
            dirnames.sort()
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                h.update(os.path.relpath(path, root).encode())
                try:
                    with open(path, "rb") as f:
                        h.update(f.read())
                except OSError:
                    h.update(b"<unreadable>")
        _code_fp_cache[0] = h.hexdigest()
        return _code_fp_cache[0]


def env_fingerprint() -> Dict[str, Any]:
    """The compatibility envelope of a serialized executable: backend
    platform, device kind, topology, jax/jaxlib versions, XLA topology
    flags, and the package source hash. Any mismatch on read is a
    structured reject — ``deserialize_and_load`` on a foreign platform
    would abort the process, and a version skew could execute stale HLO.
    """
    if _env_fp_cache[0] is not None:
        return dict(_env_fp_cache[0])
    with _fp_lock:
        if _env_fp_cache[0] is not None:
            return dict(_env_fp_cache[0])
        import jax
        import jaxlib

        try:
            devs = jax.devices()
            kind = devs[0].device_kind if devs else "?"
            ndev = len(devs)
        except Exception:  # lint-ok: fingerprint must never raise
            kind, ndev = "?", 0
        fp = {
            "schema": _SCHEMA,
            "platform": jax.default_backend(),
            "device_kind": str(kind),
            "num_devices": int(ndev),
            "process_count": int(getattr(jax, "process_count", lambda: 1)()),
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "xla_flags": os.environ.get("XLA_FLAGS", ""),
            # jax config knobs that shape compiled numerics: a writer with
            # x64 on or a different matmul precision would otherwise hand
            # a fingerprint-matching reader a numerically different
            # program than the one it would compile itself — breaking the
            # bitwise serve-vs-predict contract on hits
            "x64": bool(getattr(jax.config, "jax_enable_x64", False)),
            "matmul_precision": str(getattr(
                jax.config, "jax_default_matmul_precision", None)),
            "code": code_fingerprint(),
        }
        _env_fp_cache[0] = fp
        return dict(fp)


# ---------------------------------------------------------------------------
# serialization helpers
# ---------------------------------------------------------------------------

def aot_compile(jitted, args: tuple, kwargs: Optional[dict] = None):
    """``jitted.lower(*args).compile()`` or None — the capture-free AOT
    path for when the persistent cache is on but device-cost capture is
    vetoed (the two switches stay independent)."""
    try:
        return jitted.lower(*args, **(kwargs or {})).compile()
    except Exception:  # lint-ok: AOT refusal degrades to the jit path
        return None


def serialize_compiled(compiled) -> Optional[bytes]:
    """Export a ``jax.stages.Compiled`` to bytes (pickle of XLA's
    serialized executable + the call signature pytrees), or None when the
    backend refuses export — the caller then falls back to jax's
    persistent compilation cache."""
    try:
        from jax.experimental import serialize_executable as _se

        payload, in_tree, out_tree = _se.serialize(compiled)
        buf = io.BytesIO()
        pickle.dump((payload, in_tree, out_tree), buf,
                    protocol=pickle.HIGHEST_PROTOCOL)
        return buf.getvalue()
    except Exception:  # lint-ok: export support is backend-dependent
        return None


def _deserialize_compiled(blob: bytes):
    from jax.experimental import serialize_executable as _se

    payload, in_tree, out_tree = pickle.loads(blob)
    return _se.deserialize_and_load(payload, in_tree, out_tree)


_fallback_enabled = [False]
_fallback_lock = threading.Lock()


def enable_jax_fallback_cache(directory: str) -> bool:
    """Point jax's persistent *compilation* cache at ``<dir>/xla`` — the
    degraded mode for backends whose executables refuse serialization
    (``serialize_compiled`` → None): re-compiles skip XLA optimization by
    hitting the compiler-level cache instead. Idempotent; returns whether
    the config took. Serialized under a lock — concurrent warmup workers
    can hit export refusal together, and ``jax.config.update`` is a
    process-global mutation that must happen exactly once."""
    if _fallback_enabled[0]:
        return True
    with _fallback_lock:
        return _enable_jax_fallback_cache_locked(directory)


def _enable_jax_fallback_cache_locked(directory: str) -> bool:
    if _fallback_enabled[0]:
        return True
    try:
        import jax

        path = os.path.join(directory, "xla")
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache even fast compiles — cold start is dominated by many small
        # programs, each under the default 1s floor
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception:  # lint-ok: knob name varies across jax versions
            pass
        _fallback_enabled[0] = True
        return True
    except Exception:  # lint-ok: fallback is best-effort by contract
        return False


# ---------------------------------------------------------------------------
# the cache
# ---------------------------------------------------------------------------

class CacheEntry(NamedTuple):
    """A successful ``get``: the loaded executable + the entry's stored
    metadata (the compile-time cost record, bucket, timestamps...)."""
    executable: Any
    meta: Dict[str, Any]


def _obs_count(name: str, **attrs) -> None:
    # metrics/events only when telemetry records; the cache's own stats
    # dict counts unconditionally (serve_bench / tests read those)
    from . import obs

    if obs.enabled():
        obs.inc(f"progcache.{name}")
        if name in ("reject", "write", "export_refused"):
            obs.event(f"progcache.{name}", **attrs)


class ProgramCache:
    """One cache directory of serialized executables.

    Layout: ``<root>/<digest>.mxprog``, each file::

        MXPROG1\\n | u32 header_len | header json | u64 payload_len |
        payload (pickled serialized executable) | u32 crc32(all prior)

    The header carries the :class:`ProgramKey`, the writer's
    :func:`env_fingerprint`, and caller metadata (cost record, bucket).
    Writes are atomic (temp + fsync + rename); reads verify magic, CRC,
    digest, and fingerprint *before* unpickling — a mismatch on any is a
    counted reject, and the caller compiles as if the entry never existed.
    """

    def __init__(self, root: str, keep: Optional[int] = None,
                 durable: bool = True):
        self.root = str(root)
        if keep is None:
            from .obs._env import env_int

            keep = env_int("MXNET_PROGCACHE_KEEP", 128)
        self.keep = int(keep)
        self.durable = bool(durable)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self.stats: Dict[str, int] = {"hit": 0, "miss": 0, "reject": 0,
                                      "write": 0, "export_refused": 0}

    def _count(self, name: str, **attrs) -> None:
        with self._lock:
            self.stats[name] = self.stats.get(name, 0) + 1
        _obs_count(name, **attrs)

    def _path(self, digest: str) -> str:
        return os.path.join(self.root, f"{digest}.mxprog")

    # -- read ----------------------------------------------------------
    def _read_entry(self, path: str, digest: str):
        """Parse + verify one entry file. Returns (header, payload) or a
        string reject reason."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return "unreadable"
        if len(raw) < len(_MAGIC) + 4 + 8 + 4 \
                or not raw.startswith(_MAGIC):
            return "bad_magic"
        body, crc_bytes = raw[:-4], raw[-4:]
        if crc32_bytes(body) != struct.unpack("<I", crc_bytes)[0]:
            return "crc_mismatch"
        off = len(_MAGIC)
        (hlen,) = struct.unpack_from("<I", body, off)
        off += 4
        try:
            header = json.loads(body[off:off + hlen].decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return "bad_header"
        off += hlen
        (plen,) = struct.unpack_from("<Q", body, off)
        off += 8
        payload = body[off:off + plen]
        if len(payload) != plen:
            return "truncated"
        if header.get("key", {}).get("digest") != digest:
            return "digest_mismatch"
        if header.get("env") != env_fingerprint():
            return "env_mismatch"
        return header, payload

    def get(self, key: ProgramKey) -> Optional[CacheEntry]:
        """Load the executable for ``key``. A missing file is a counted
        miss; a present-but-unusable one (corrupt, truncated, foreign
        platform, stale code, deserialize failure) is a counted reject —
        both return None and the caller compiles normally."""
        path = self._path(key.digest)
        if not os.path.exists(path):
            self._count("miss")
            return None
        res = self._read_entry(path, key.digest)
        if isinstance(res, str):
            self._count("reject", reason=res, site=key.site,
                        label=key.label)
            return None
        header, payload = res
        try:
            executable = _deserialize_compiled(payload)
        except Exception as e:  # lint-ok: a bad blob degrades to compile
            self._count("reject", reason=f"deserialize:{type(e).__name__}",
                        site=key.site, label=key.label)
            return None
        self._count("hit")
        # touch so keep-last-N GC ranks by USE recency, not write time
        try:
            os.utime(path, None)
        except OSError:
            pass
        return CacheEntry(executable, header.get("meta") or {})

    # -- write ---------------------------------------------------------
    def put(self, key: ProgramKey, compiled,
            meta: Optional[dict] = None) -> bool:
        """Serialize + commit one executable. Returns False (after
        arming the jax fallback cache) when the backend refuses export.
        Concurrent writers of the same key are safe: rename is atomic and
        both wrote identical content.

        Every blob is round-trip verified (``deserialize_and_load``)
        before it is published: XLA:CPU's JIT dedupes identical kernels
        process-wide, so an executable compiled after a kernel-hash twin
        can REFERENCE kernels it does not embed — its serialization loads
        nowhere, not even in the writer process. Deserialization builds a
        fresh function library from the blob alone, so the verify catches
        exactly the entries a cold reader would have to reject; a
        non-self-contained export counts as ``export_refused`` and arms
        the compiler-level fallback cache instead of poisoning the dir."""
        blob = serialize_compiled(compiled)
        if blob is not None:
            try:
                _deserialize_compiled(blob)
            except Exception:  # lint-ok: unloadable export = refused export
                blob = None
        if blob is None:
            self._count("export_refused", site=key.site, label=key.label)
            enable_jax_fallback_cache(self.root)
            return False
        header = json.dumps(
            {"key": key._asdict(), "env": env_fingerprint(),
             "meta": meta or {}, "created": time.time()},
            sort_keys=True).encode("utf-8")
        body = b"".join([_MAGIC, struct.pack("<I", len(header)), header,
                         struct.pack("<Q", len(blob)), blob])
        data = body + struct.pack("<I", crc32_bytes(body))
        try:
            atomic_write_bytes(self._path(key.digest), data,
                               durable=self.durable)
        except OSError:
            return False
        self._count("write", site=key.site, label=key.label,
                    bytes=len(data))
        self.gc()
        return True

    # -- GC ------------------------------------------------------------
    def gc(self) -> int:
        """Keep the ``keep`` most recently used entries (by mtime — reads
        touch); drop the rest. Returns how many were removed."""
        if self.keep <= 0:
            return 0
        try:
            entries = [e for e in os.listdir(self.root)
                       if e.endswith(".mxprog")]
        except OSError:
            return 0
        if len(entries) <= self.keep:
            return 0
        stamped = []
        for e in entries:
            try:
                stamped.append((os.path.getmtime(
                    os.path.join(self.root, e)), e))
            except OSError:
                continue  # a concurrent GC got it first
        stamped.sort(reverse=True)
        removed = 0
        for _, e in stamped[self.keep:]:
            try:
                os.unlink(os.path.join(self.root, e))
                removed += 1
            except OSError:
                pass
        return removed

    def entries(self) -> int:
        try:
            return sum(1 for e in os.listdir(self.root)
                       if e.endswith(".mxprog"))
        except OSError:
            return 0


# ---------------------------------------------------------------------------
# process-global activation (env-driven; engines default to this)
# ---------------------------------------------------------------------------

_global: list = [None, False]  # [ProgramCache|None, resolved?]
_global_lock = threading.Lock()


def default_dir() -> str:
    return os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.join(os.path.expanduser("~"), ".cache")),
        "mxnet_tpu", "progcache")


def active() -> bool:
    """Is the process-global persistent cache armed?
    ``MXNET_PROGCACHE=0`` vetoes; ``MXNET_PROGCACHE_DIR`` (or
    ``MXNET_PROGCACHE=1`` with the default dir) arms."""
    env = os.environ.get("MXNET_PROGCACHE", "").lower()
    if env in _FALSE:
        return False
    return env in _TRUE or bool(os.environ.get("MXNET_PROGCACHE_DIR"))


def cache() -> Optional[ProgramCache]:
    """The process-global :class:`ProgramCache`, or None when inactive.
    Resolved from the environment on first use; :func:`configure`
    overrides programmatically."""
    if not active():
        return None
    if _global[1]:
        return _global[0]
    with _global_lock:
        if not _global[1]:
            root = os.environ.get("MXNET_PROGCACHE_DIR") or default_dir()
            try:
                _global[0] = ProgramCache(root)
            except OSError:
                _global[0] = None  # unwritable dir: run uncached
            _global[1] = True
    return _global[0]


def configure(directory: Optional[str], keep: Optional[int] = None
              ) -> Optional[ProgramCache]:
    """Arm (or disarm with None) the process-global cache in code — the
    env-free path tools and tests use."""
    with _global_lock:
        if directory is None:
            _global[0], _global[1] = None, True
            os.environ["MXNET_PROGCACHE"] = "0"
            return None
        os.environ.pop("MXNET_PROGCACHE", None)
        os.environ["MXNET_PROGCACHE_DIR"] = str(directory)
        _global[0] = ProgramCache(str(directory), keep=keep)
        _global[1] = True
        return _global[0]


def reset() -> None:
    """Forget the resolved global cache (tests; the next :func:`cache`
    re-reads the environment)."""
    with _global_lock:
        _global[0], _global[1] = None, False
