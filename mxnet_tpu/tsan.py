"""Runtime lock-order sanitizer + deadlock watchdog (``MXNET_TSAN=1``).

The static half of the concurrency plane (``analysis/concurrency.py``)
reads the source; this is the dynamic half: drop-in instrumented
``Lock``/``RLock``/``Condition`` wrappers that record per-thread lock
acquisition order into one process-global order graph and detect cycles
*live* — the moment a thread acquires B while holding A after any thread
ever acquired A while holding B, before the interleaving that would
actually deadlock ever happens (the classic happens-before lock-order
discipline, TSan/lockdep style).

The serve and PS planes create every lock through the factories below
(:func:`lock` / :func:`rlock` / :func:`condition`), which return plain
``threading`` primitives when the sanitizer is off — zero overhead, zero
behavior change — and instrumented ones under ``MXNET_TSAN=1``. Because
``ProcReplica`` / elastic workers inherit the environment, every chaos
subprocess is sanitized too: ``make tsan`` re-runs the fleet-SIGKILL and
elastic-rejoin chaos suites with the sanitizer on and the watchdog armed.

Violations are recorded (``violations()``), counted
(``tsan.lock_order_violations``), surfaced as obs events, and raised as
:class:`LockOrderViolation` under ``MXNET_TSAN_RAISE=1`` (or
:func:`set_strict`) — tests use strict mode to make a seeded inversion a
deterministic failure.

The **watchdog** (armed automatically when the sanitizer is enabled;
stall threshold ``MXNET_TSAN_STALL_S``, default 20s) scans for threads
that have been (a) blocked acquiring a tracked lock, (b) parked in a
``Condition.wait``, or (c) *holding* a tracked lock — e.g. blocked in a
socket ``recv`` under it — for longer than the threshold, and dumps every
thread's stack with held-lock attribution (which thread holds which named
lock, and for how long), so a wedged fleet leaves a diagnosis instead of
a hung CI job.
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
from _thread import allocate_lock as _raw_lock
from typing import Callable, Dict, List, Optional, Tuple

from .base import get_env

__all__ = ["enabled", "lock", "rlock", "condition", "SanLock", "SanRLock",
           "SanCondition", "LockOrderViolation", "violations", "reset",
           "set_strict", "arm_watchdog", "disarm_watchdog", "dump_stacks",
           "Watchdog"]


def enabled() -> bool:
    return bool(get_env("MXNET_TSAN", False, bool))


class LockOrderViolation(RuntimeError):
    """Acquiring this lock closes a cycle in the global lock-order graph:
    some interleaving of the participating threads can deadlock."""


# ---------------------------------------------------------------------------
# global sanitizer state (its own RAW lock: the bookkeeping must never
# participate in the graph it maintains)
# ---------------------------------------------------------------------------

_mu = _raw_lock()
_edges: Dict[str, Dict[str, dict]] = {}     # name -> {succ: first-edge info}
_violations: List[dict] = []
_violation_pairs: set = set()   # (holding, acquiring) pairs that cycled
_warned_pairs: set = set()
_strict = [bool(get_env("MXNET_TSAN_RAISE", False, bool))]
# watchdog-visible tables, keyed by thread ident
_holds: Dict[int, List[Tuple["SanLock", float]]] = {}   # held (lock, since)
_waiting: Dict[int, Tuple[str, float]] = {}             # acquiring (name, t)
_cv_waits: Dict[int, Tuple[str, float, Optional[float]]] = {}
_tls = threading.local()

_watchdog: Optional["Watchdog"] = None


def set_strict(flag: bool) -> None:
    """Raise :class:`LockOrderViolation` on cycle detection (tests; also
    ``MXNET_TSAN_RAISE=1``) instead of record-and-continue."""
    _strict[0] = bool(flag)


def violations() -> List[dict]:
    with _mu:
        return list(_violations)


def reset() -> None:
    """Drop the order graph, violation log, and watchdog tables (tests)."""
    with _mu:
        _edges.clear()
        _violations.clear()
        _violation_pairs.clear()
        _warned_pairs.clear()
        _holds.clear()
        _waiting.clear()
        _cv_waits.clear()
    held = getattr(_tls, "held", None)
    if held:
        held.clear()


def _held() -> List["SanLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _path_exists(src: str, dst: str) -> Optional[List[str]]:
    """BFS over the order graph; returns the node path src..dst or None.
    Caller holds ``_mu``."""
    if src == dst:
        return [src]
    frontier = [[src]]
    seen = {src}
    while frontier:
        path = frontier.pop(0)
        for succ in _edges.get(path[-1], ()):
            if succ == dst:
                return path + [dst]
            if succ not in seen:
                seen.add(succ)
                frontier.append(path + [succ])
    return None


def _record_acquired(lk: "SanLock") -> None:
    tid = threading.get_ident()
    held = _held()
    now = time.monotonic()
    new_cycle = None
    with _mu:
        _waiting.pop(tid, None)
        for h, _depth in held:
            if h.name == lk.name:
                continue  # reentrancy / same-named peer: not an order edge
            succs = _edges.setdefault(h.name, {})
            if lk.name in succs:
                succs[lk.name]["count"] += 1
                # a REPEAT of a known-bad ordering must keep reporting
                # (and keep raising under strict) — the first offender may
                # have been a daemon thread whose raise nobody saw
                if (h.name, lk.name) in _violation_pairs:
                    back = _path_exists(lk.name, h.name)
                    if back is not None and new_cycle is None:
                        new_cycle = {
                            "cycle": back + [lk.name],
                            "thread": threading.current_thread().name,
                            "holding": h.name, "acquiring": lk.name,
                            "stack": "".join(
                                traceback.format_stack(limit=12))}
                continue
            # NEW edge h -> lk: a cycle exists iff lk already reaches h
            back = _path_exists(lk.name, h.name)
            succs[lk.name] = {"count": 1,
                              "stack": traceback.format_stack(limit=8)}
            if back is not None:
                cycle = back + [lk.name]
                info = {"cycle": cycle, "thread": threading.current_thread().name,
                        "holding": h.name, "acquiring": lk.name,
                        "stack": "".join(traceback.format_stack(limit=12))}
                _violations.append(info)
                _violation_pairs.add((h.name, lk.name))
                if new_cycle is None:
                    new_cycle = info
        _holds.setdefault(tid, []).append((lk, now))
    held.append((lk, 1))
    if new_cycle is not None:
        _report_violation(new_cycle)


def _report_violation(info: dict) -> None:
    pair = (info["holding"], info["acquiring"])
    first = False
    with _mu:
        if pair not in _warned_pairs:
            _warned_pairs.add(pair)
            first = True
    msg = ("lock-order violation: acquiring %r while holding %r closes the "
           "cycle %s (thread %s)" % (info["acquiring"], info["holding"],
                                     " -> ".join(info["cycle"]),
                                     info["thread"]))
    try:  # lazy: obs pulls in the full runtime; the sanitizer must not
        from . import obs

        obs.inc("tsan.lock_order_violations")
        obs.event("tsan.lock_order_violation", cycle=info["cycle"],
                  thread=info["thread"])
    except Exception:  # noqa: BLE001 — reporting must never deadlock/raise
        pass
    if first:
        sys.stderr.write("[tsan] " + msg + "\n")
    if _strict[0]:
        raise LockOrderViolation(msg + "\n" + info["stack"])


def _record_released(lk: "SanLock") -> None:
    tid = threading.get_ident()
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is lk:
            del held[i]
            break
    with _mu:
        stack = _holds.get(tid)
        if stack:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] is lk:
                    del stack[i]
                    break
            if not stack:
                _holds.pop(tid, None)


def _record_waiting(name: str) -> None:
    tid = threading.get_ident()
    with _mu:
        _waiting[tid] = (name, time.monotonic())


def _clear_waiting() -> None:
    tid = threading.get_ident()
    with _mu:
        _waiting.pop(tid, None)


# ---------------------------------------------------------------------------
# instrumented primitives
# ---------------------------------------------------------------------------

class SanLock:
    """Instrumented non-reentrant lock (wraps a raw ``_thread`` lock)."""

    _reentrant = False

    def __init__(self, name: Optional[str] = None):
        self.name = name or f"anon-lock@{id(self):x}"
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _record_waiting(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                _record_acquired(self)
            except LockOrderViolation:
                # strict mode: leave the world as if the acquire never
                # happened, or the raise would leak a held lock
                _record_released(self)
                self._inner.release()
                raise
        else:
            _clear_waiting()
        return got

    def release(self) -> None:
        _record_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


class SanRLock(SanLock):
    """Instrumented reentrant lock. Re-acquisition by the owner bumps a
    depth counter and adds no order edges (not a hazard)."""

    _reentrant = True

    def __init__(self, name: Optional[str] = None):
        self.name = name or f"anon-rlock@{id(self):x}"
        self._inner = threading.RLock()
        self._owner: Optional[int] = None
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._owner == me:
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._depth += 1
            return got
        _record_waiting(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._depth = 1
            try:
                _record_acquired(self)
            except LockOrderViolation:
                self._owner = None
                self._depth = 0
                _record_released(self)
                self._inner.release()
                raise
        else:
            _clear_waiting()
        return got

    def release(self) -> None:
        if self._owner == threading.get_ident() and self._depth > 1:
            self._depth -= 1
            self._inner.release()
            return
        self._owner = None
        self._depth = 0
        _record_released(self)
        self._inner.release()

    # Condition integration: full release/restore across a wait, with the
    # sanitizer's held-bookkeeping kept in sync
    def _release_save(self):
        depth, self._depth, self._owner = self._depth, 0, None
        _record_released(self)
        state = self._inner._release_save()  # type: ignore[attr-defined]
        return (state, depth)

    def _acquire_restore(self, saved):
        state, depth = saved
        _record_waiting(self.name)
        self._inner._acquire_restore(state)  # type: ignore[attr-defined]
        self._owner = threading.get_ident()
        self._depth = depth
        _record_acquired(self)

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()


class SanCondition(threading.Condition):
    """Instrumented condition variable: its underlying lock participates
    in the order graph, and every ``wait`` registers with the watchdog so
    a stalled waiter shows up in the stack dump with its held locks."""

    def __init__(self, name: Optional[str] = None, lock=None):
        self.name = name or f"anon-cv@{id(self):x}"
        super().__init__(lock if lock is not None
                         else SanRLock(self.name))

    def wait(self, timeout: Optional[float] = None) -> bool:
        tid = threading.get_ident()
        with _mu:
            _cv_waits[tid] = (self.name, time.monotonic(), timeout)
        try:
            return super().wait(timeout)
        finally:
            with _mu:
                _cv_waits.pop(tid, None)


# ---------------------------------------------------------------------------
# factories — what the serve/kvstore planes actually call
# ---------------------------------------------------------------------------

def lock(name: Optional[str] = None):
    """A mutex: plain ``threading.Lock`` normally, :class:`SanLock` under
    ``MXNET_TSAN=1`` (the watchdog is armed on first creation)."""
    if enabled():
        _auto_arm()
        return SanLock(name)
    return threading.Lock()


def rlock(name: Optional[str] = None):
    if enabled():
        _auto_arm()
        return SanRLock(name)
    return threading.RLock()


def condition(name: Optional[str] = None, lock=None):
    if enabled():
        _auto_arm()
        return SanCondition(name, lock=lock)
    return threading.Condition(lock)


# ---------------------------------------------------------------------------
# deadlock watchdog
# ---------------------------------------------------------------------------

def dump_stacks(reason: str = "manual") -> str:
    """Every thread's stack with held-lock attribution. Written to stderr
    and returned (tests and the watchdog's sinks consume the text)."""
    now = time.monotonic()
    names = {t.ident: t.name for t in threading.enumerate()}
    with _mu:
        holds = {tid: [(lk.name, round(now - t0, 3)) for lk, t0 in stack]
                 for tid, stack in _holds.items() if stack}
        waits = dict(_waiting)
        cvw = dict(_cv_waits)
    lines = [f"[tsan] watchdog stack dump ({reason})"]
    frames = sys._current_frames()
    for tid, frame in frames.items():
        header = f"-- thread {names.get(tid, '?')} (ident {tid})"
        attribution = []
        for lname, age in holds.get(tid, ()):
            attribution.append(f"HOLDS {lname} for {age}s")
        if tid in waits:
            lname, t0 = waits[tid]
            attribution.append(
                f"BLOCKED acquiring {lname} for {round(now - t0, 3)}s")
        if tid in cvw:
            cname, t0, tmo = cvw[tid]
            attribution.append(
                f"WAITING on condition {cname} for {round(now - t0, 3)}s"
                + (" (no timeout)" if tmo is None else f" (timeout {tmo})"))
        if attribution:
            header += " [" + "; ".join(attribution) + "]"
        lines.append(header)
        lines.extend(line.rstrip("\n")
                     for line in traceback.format_stack(frame, limit=12))
    text = "\n".join(lines)
    sys.stderr.write(text + "\n")
    try:
        from . import obs

        obs.inc("tsan.watchdog_dumps")
        obs.event("tsan.watchdog_dump", reason=reason,
                  threads=len(frames))
        # a stalled fleet is exactly the "last seconds" question the
        # flight recorder answers: snapshot the telemetry ring + profiler
        # + these stacks as a bundle (throttled; no-op when disarmed)
        obs.blackbox.trigger(f"watchdog:{reason}"[:120])
    except Exception:  # noqa: BLE001 — diagnosis must never crash the host
        pass
    return text


class Watchdog:
    """Scans the sanitizer tables every ``interval`` and dumps all-thread
    stacks (once per offender) when any thread has been blocked acquiring
    a lock, parked in a Condition.wait, or holding a lock for longer than
    ``stall_s`` — the "fleet stalled" tripwire."""

    def __init__(self, stall_s: float = 20.0, interval: Optional[float] = None,
                 sink: Optional[Callable[[str], None]] = None):
        self.stall_s = float(stall_s)
        self.interval = float(interval if interval is not None
                              else max(self.stall_s / 4, 0.05))
        self.sink = sink
        self.dumps = 0
        self._reported: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxtpu-tsan-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            if self._thread.is_alive():  # scan wedged: nothing left to do
                sys.stderr.write("[tsan] watchdog thread did not stop\n")
            self._thread = None

    def check(self) -> Optional[str]:
        """One scan (callable from tests without the thread)."""
        now = time.monotonic()
        offenders = []
        with _mu:
            for tid, (lname, t0) in _waiting.items():
                if now - t0 > self.stall_s:
                    offenders.append(("acquire", tid, lname))
            for tid, (cname, t0, _tmo) in _cv_waits.items():
                if now - t0 > self.stall_s:
                    offenders.append(("cv-wait", tid, cname))
            for tid, stack in _holds.items():
                for lk, t0 in stack:
                    if now - t0 > self.stall_s:
                        offenders.append(("hold", tid, lk.name))
        # forget offenders that recovered: a future stall on the same
        # (thread, lock) key — or a reused thread ident — must dump again
        self._reported &= set(offenders)
        fresh = [o for o in offenders if o not in self._reported]
        if not fresh:
            return None
        self._reported.update(fresh)
        reason = "; ".join(f"{kind} {name} (thread {tid})"
                           for kind, tid, name in fresh)
        text = dump_stacks(f"stall: {reason}")
        self.dumps += 1
        if self.sink is not None:
            try:
                self.sink(text)
            except Exception:  # noqa: BLE001 — sink is observer-only
                pass
        return text

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the watchdog outlives scans
                pass


def arm_watchdog(stall_s: Optional[float] = None,
                 interval: Optional[float] = None,
                 sink: Optional[Callable[[str], None]] = None) -> Watchdog:
    """Start (or replace) the process watchdog. Default threshold:
    ``MXNET_TSAN_STALL_S`` (20s)."""
    global _watchdog
    if _watchdog is not None:
        _watchdog.stop()
    if stall_s is None:
        stall_s = float(get_env("MXNET_TSAN_STALL_S", 20.0, float))
    _watchdog = Watchdog(stall_s, interval=interval, sink=sink).start()
    return _watchdog


def disarm_watchdog() -> None:
    global _watchdog
    if _watchdog is not None:
        _watchdog.stop()
        _watchdog = None


_armed = [False]


def _auto_arm() -> None:
    if not _armed[0]:
        _armed[0] = True
        if float(get_env("MXNET_TSAN_STALL_S", 20.0, float)) > 0:
            arm_watchdog()
