"""Declarative wire-opcode registry for the PS (training) and serve planes.

Both planes share one length-prefixed binary framing (see
``kvstore/ps_server.py``), and historically each declared its opcodes as a
bare ``range(...)`` tuple in its own module — collisions between planes
(or a stale handler for a renumbered op) were only caught by runtime
breakage. This module is the single source of truth: every opcode is an
:class:`OpSpec` row (name, code, plane, direction, mutating?, dedup
discipline, WAL coverage, traced?) and the registries raise at import on
any duplicate code or name — collisions are impossible by construction.

Consumers:

- ``kvstore/ps_server.py`` / ``kvstore/elastic.py`` / ``serve/server.py``
  derive their ``OP_*`` constants and name tables from here, so the wire
  modules and the registry cannot drift;
- ``analysis/concurrency.py``'s protocol pass cross-checks the registries
  against the handler ASTs (every op has exactly one handler branch, every
  handler branch maps to a registered op, mutating ops carry their
  declared exactly-once machinery) — it reads *data*, not greps;
- the chaos rule table (``chaos/rpc.py``) keeps addressing ops by the
  names registered here.

This module is deliberately stdlib-only (no jax, no numpy): the static
analyzer imports it without pulling in the runtime.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Sequence, Tuple

__all__ = ["OpSpec", "WireRegistry", "PS_WIRE", "SERVE_WIRE",
           "check_disjoint", "DEDUP_KINDS"]

# Exactly-once disciplines a mutating op may declare:
#   "seq"        (client_id, seq) dedup table + (usually) WAL coverage
#   "token"      commit-token LRU (retried frame re-acks, never re-applies)
#   "idempotent" re-applying the frame is harmless by construction
#   "legacy"     documented at-least-once (plain PUSH; superseded by _seq)
DEDUP_KINDS = ("seq", "token", "idempotent", "legacy")


@dataclass(frozen=True)
class OpSpec:
    """One wire opcode, declaratively.

    ``direction`` is ``"request"`` for every current op (the reply rides
    the same opcode — request/reply pairing is checked by the protocol
    linter against the handler's reply sends). ``mutating`` means the op
    changes served/durable state; every mutating op must name its
    exactly-once discipline in ``dedup``. ``wal`` marks ops whose applied
    effect must survive a server SIGKILL (fsynced WAL record before the
    ack). ``traced`` means the handler loop is expected to extract and
    activate wire trace context for this op (true plane-wide since PR 7).
    """

    name: str
    code: int
    plane: str
    direction: str = "request"
    mutating: bool = False
    dedup: Optional[str] = None
    wal: bool = False
    traced: bool = True
    const: Optional[str] = None  # python constant name, default OP_<NAME>

    @property
    def const_name(self) -> str:
        return self.const or ("OP_" + self.name.upper())


class WireRegistry:
    """An immutable opcode table for one handler loop.

    ``handler`` is ``(relpath, loop_fn, dispatch_fn)`` — where the plane's
    framed receive loop and its per-opcode dispatch live, for the protocol
    linter. Raises ``ValueError`` on any duplicate code, name, or
    constant name at construction time.
    """

    def __init__(self, plane: str, handler: Tuple[str, str, str],
                 ops: Sequence[OpSpec]):
        self.plane = plane
        self.handler_path, self.loop_fn, self.dispatch_fn = handler
        self._by_code: Dict[int, OpSpec] = {}
        self._by_name: Dict[str, OpSpec] = {}
        self._by_const: Dict[str, OpSpec] = {}
        for op in ops:
            if op.code in self._by_code:
                raise ValueError(
                    f"{plane}: opcode collision: {op.name!r} and "
                    f"{self._by_code[op.code].name!r} both claim code "
                    f"{op.code}")
            if op.name in self._by_name:
                raise ValueError(
                    f"{plane}: duplicate op name {op.name!r}")
            if op.const_name in self._by_const:
                raise ValueError(
                    f"{plane}: duplicate constant {op.const_name!r}")
            self._by_code[op.code] = op
            self._by_name[op.name] = op
            self._by_const[op.const_name] = op

    def __iter__(self) -> Iterator[OpSpec]:
        return iter(sorted(self._by_code.values(), key=lambda o: o.code))

    def __len__(self) -> int:
        return len(self._by_code)

    def code(self, name: str) -> int:
        return self._by_name[name].code

    def spec(self, name: str) -> OpSpec:
        return self._by_name[name]

    def codes(self, *names: str) -> Tuple[int, ...]:
        return tuple(self._by_name[n].code for n in names)

    def names(self) -> Dict[int, str]:
        """``{code: name}`` — the telemetry/chaos label table."""
        return {c: o.name for c, o in self._by_code.items()}

    def by_const(self) -> Dict[str, OpSpec]:
        return dict(self._by_const)


def check_disjoint(*registries: WireRegistry) -> None:
    """Raise ``ValueError`` if any two registries share an opcode."""
    seen: Dict[int, str] = {}
    for reg in registries:
        for op in reg:
            if op.code in seen:
                raise ValueError(
                    f"cross-plane opcode collision: code {op.code} claimed "
                    f"by {seen[op.code]} and {reg.plane}:{op.name}")
            seen[op.code] = f"{reg.plane}:{op.name}"


# ---------------------------------------------------------------------------
# the PS (training) plane: kvstore ops 0-9 + the elastic range 16-26,
# all dispatched by kvstore/ps_server.py
# ---------------------------------------------------------------------------

PS_WIRE = WireRegistry(
    "kvstore", ("mxnet_tpu/kvstore/ps_server.py", "_handle_loop",
                "_handle_one"),
    [
        # key birth is idempotent (first-wins) but must survive a restart,
        # so it rides the WAL as a kind-2 record
        OpSpec("init", 0, "kvstore", mutating=True, dedup="idempotent",
               wal=True),
        # plain push is the documented at-least-once legacy path; the
        # retry-safe transport is push_seq
        OpSpec("push", 1, "kvstore", mutating=True, dedup="legacy"),
        OpSpec("pull", 2, "kvstore"),
        OpSpec("set_opt", 3, "kvstore", mutating=True, dedup="idempotent",
               wal=True),
        OpSpec("barrier", 4, "kvstore"),
        OpSpec("shutdown", 5, "kvstore"),
        OpSpec("push_sparse", 6, "kvstore", mutating=True, dedup="legacy"),
        OpSpec("pull_sparse", 7, "kvstore"),
        OpSpec("push_seq", 8, "kvstore", mutating=True, dedup="seq",
               wal=True),
        OpSpec("push_sparse_seq", 9, "kvstore", mutating=True, dedup="seq",
               wal=True),
        # elastic membership plane (kvstore/elastic.py state machine;
        # contributions deduped by cid, completed rounds LRU-cached)
        OpSpec("heartbeat", 16, "elastic", const="OP_HB"),
        OpSpec("join", 17, "elastic", mutating=True, dedup="idempotent"),
        OpSpec("reduce", 18, "elastic", mutating=True, dedup="idempotent"),
        OpSpec("epoch", 19, "elastic", mutating=True, dedup="idempotent"),
        OpSpec("leave", 20, "elastic", mutating=True, dedup="idempotent"),
        # training-fleet telemetry pull (obs/fleetstats.py): draining the
        # server's span ring + cached per-worker parts is destructive, so
        # retried collections re-serve the cached reply from the token LRU
        # (the serve-plane OP_TELEMETRY=42 idiom on the PS wire)
        OpSpec("telemetry", 21, "elastic", mutating=True, dedup="token"),
        # server stats snapshot (membership liveness, straggler verdicts,
        # hot keys, metrics under "metrics") — read-only, retries harmless
        OpSpec("stats", 22, "elastic"),
        # bounded-staleness async training (docs/ROBUSTNESS.md
        # "Asynchronous training"). clock: a worker commits "rank r
        # finished step t" — max-merge, so a retried frame is harmless
        # (idempotent), and the table must survive a server SIGKILL
        # mid-async-storm (kind-4 WAL record before the ack)
        OpSpec("clock", 23, "elastic", mutating=True, dedup="idempotent",
               wal=True),
        # read-only committed-clock table dump (floor + per-rank clocks):
        # tests and operators assert exactly-once clock recovery with it
        OpSpec("clock_pull", 24, "elastic"),
        # staleness-gated pull: blocks (wait bound rides IN the request —
        # the OP_REDUCE discipline) while the puller would run more than
        # `s` steps ahead of the fleet's committed clock floor
        OpSpec("pull_stale", 25, "elastic"),
        # scoped reduce: like "reduce" but the round completes at an
        # explicit contributor count instead of the full live membership —
        # the transport under hierarchical (group-tree) reduction
        OpSpec("reduce_scoped", 26, "elastic", mutating=True,
               dedup="idempotent"),
    ])


# ---------------------------------------------------------------------------
# the serve plane: opcodes 32-42, dispatched by serve/server.py
# ---------------------------------------------------------------------------

SERVE_WIRE = WireRegistry(
    "serve", ("mxnet_tpu/serve/server.py", "_handle_loop", "_handle_one"),
    [
        OpSpec("infer", 32, "serve"),
        OpSpec("health", 33, "serve"),
        OpSpec("ready", 34, "serve"),
        # single-replica hot reload; the fleet path is prepare+commit
        OpSpec("reload", 35, "serve", mutating=True, dedup="legacy"),
        OpSpec("stats", 36, "serve"),
        OpSpec("drain", 37, "serve", mutating=True, dedup="idempotent"),
        OpSpec("serve_shutdown", 38, "serve", const="OP_SHUTDOWN"),
        OpSpec("prepare_reload", 39, "serve", mutating=True,
               dedup="idempotent"),
        OpSpec("commit_reload", 40, "serve", mutating=True, dedup="token"),
        OpSpec("abort_reload", 41, "serve", mutating=True,
               dedup="idempotent"),
        # draining the span ring is destructive: retried collections
        # re-serve the cached reply from the token LRU
        OpSpec("telemetry", 42, "serve", mutating=True, dedup="token"),
        # flight-recorder snapshot (obs/blackbox.py): read-only — the
        # bundle is built from the always-on ring without draining
        # anything, so retries are harmless by construction
        OpSpec("dump", 43, "serve"),
        # autoregressive streaming lane (serve/decode.py): one
        # infer_stream request fans into a chunked token/end/error reply
        # sequence on the same connection. Generation mutates no served
        # state (KV pages are scoped to the stream and reclaimed on any
        # exit), so the request op stays non-mutating; a duplicated
        # request (chaos dup) just streams the same tokens twice and the
        # client drains the echo.
        OpSpec("infer_stream", 44, "serve"),
        # chunk frames: direction="reply" — many frames answer ONE
        # infer_stream request, so the protocol linter's one-handler-
        # branch-per-request rule must not expect dispatch branches for
        # them. Chaos drop/dup address them by these names.
        OpSpec("stream_token", 45, "serve", direction="reply"),
        OpSpec("stream_end", 46, "serve", direction="reply"),
        OpSpec("stream_error", 47, "serve", direction="reply"),
    ])


check_disjoint(PS_WIRE, SERVE_WIRE)
