"""``mx.nd`` — the imperative array API.

Reference: ``python/mxnet/ndarray/`` where op wrappers are generated at import
from the C++ registry (register.py, TBV — SURVEY.md §2.2). Here the same idea
is PEP-562 ``__getattr__``: any registered op name resolves to an eager
dispatcher, so ``nd.relu``, ``nd.FullyConnected``, ``nd.broadcast_add`` … all
exist without codegen.
"""
from __future__ import annotations

from typing import Sequence

from ..ops import get_op, has_op, list_ops
from ..ops.registry import OpDef
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange, save, load,
                      concat, stack, waitall, invoke, from_jax)
from .. import random

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange", "save",
           "load", "concat", "stack", "waitall", "random"]


def _make_dispatcher(name: str):
    opdef = get_op(name)

    def op_fn(*args, **kwargs):
        inputs = []
        rest = list(args)
        while rest and (isinstance(rest[0], NDArray)):
            inputs.append(rest.pop(0))
        if rest:
            raise TypeError(f"{name}: positional args after tensor inputs must be kwargs")
        return invoke(opdef, inputs, kwargs)

    op_fn.__name__ = name
    op_fn.__doc__ = (opdef.fn.__doc__ or "") + f"\n\n(registered op {name!r})"
    return op_fn


def __getattr__(name: str):
    if name in ("contrib", "sparse", "image", "linalg"):
        import importlib

        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    if has_op(name):
        fn = _make_dispatcher(name)
        globals()[name] = fn  # cache
        return fn
    raise AttributeError(f"module 'mxnet_tpu.ndarray' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list_ops()))


# A few wrappers whose python signatures differ from raw dispatch:

def split(data, num_outputs, axis=1, squeeze_axis=False):
    return invoke("SliceChannel", [data], {"num_outputs": num_outputs, "axis": axis,
                                           "squeeze_axis": squeeze_axis})


def dot(lhs, rhs, transpose_a=False, transpose_b=False, **kw):
    return invoke("dot", [lhs, rhs], {"transpose_a": transpose_a, "transpose_b": transpose_b})


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False, **kw):
    return invoke("batch_dot", [lhs, rhs],
                  {"transpose_a": transpose_a, "transpose_b": transpose_b})


def where(condition, x, y):
    return invoke("where", [condition, x, y], {})


def zeros_like(data):
    return invoke("zeros_like", [data], {})


def ones_like(data):
    return invoke("ones_like", [data], {})


def cast(data, dtype):
    return invoke("Cast", [data], {"dtype": dtype})


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return invoke("one_hot", [indices], {"depth": depth, "on_value": on_value,
                                         "off_value": off_value, "dtype": dtype})
