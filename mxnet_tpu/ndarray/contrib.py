"""``mx.nd.contrib`` — contrib op namespace (reference ndarray/contrib.py).

Resolves ``nd.contrib.box_nms`` → registered op ``_contrib_box_nms`` (or a
bare-name registration)."""
from __future__ import annotations

from ..ops import has_op
from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401
from . import _make_dispatcher


def __getattr__(name: str):
    for cand in (f"_contrib_{name}", name):
        if has_op(cand):
            fn = _make_dispatcher(cand)
            globals()[name] = fn
            return fn
    raise AttributeError(f"no contrib operator {name!r}")
