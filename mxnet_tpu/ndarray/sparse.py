"""Sparse NDArrays: CSR and row_sparse.

Reference: ``src/ndarray`` storage types + ``python/mxnet/ndarray/sparse.py``
(TBV — SURVEY.md §2.1 L3). XLA has no native sparse layout, so TPU sparse
arrays keep the reference's *metadata* (indices/indptr/data views, stype)
while backing compute with dense HLO (gather/scatter) — numerically exact
parity; the perf-relevant sparse path in the reference (distributed
row_sparse embedding pull) lives at the KVStore layer where the host-side
PS keeps true sparsity over the wire.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from .ndarray import NDArray, array as nd_array

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix", "row_sparse_array",
           "BaseSparseNDArray"]


class BaseSparseNDArray(NDArray):
    @property
    def stype(self):
        raise NotImplementedError

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data)
        if stype == self.stype:
            return self
        if stype == "row_sparse":
            return RowSparseNDArray.from_dense(NDArray(self._data))
        if stype == "csr":
            return CSRNDArray.from_dense(NDArray(self._data))
        raise ValueError(f"unknown stype {stype!r}")

    def todense(self) -> NDArray:
        return NDArray(self._data)

    def asscipy(self):
        raise NotImplementedError("scipy interchange not available")


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (2D)."""

    def __init__(self, dense_data, indptr, indices, sdata):
        super().__init__(dense_data)
        self._indptr = indptr
        self._indices = indices
        self._sdata = sdata

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self) -> NDArray:
        return nd_array(self._indptr)

    @property
    def indices(self) -> NDArray:
        return nd_array(self._indices)

    @property
    def data(self) -> NDArray:
        return nd_array(self._sdata)

    @staticmethod
    def from_dense(arr: NDArray) -> "CSRNDArray":
        d = np.asarray(arr.asnumpy())
        assert d.ndim == 2, "CSR requires 2D"
        indptr = [0]
        indices = []
        vals = []
        for row in d:
            nz = np.nonzero(row)[0]
            indices.extend(nz.tolist())
            vals.extend(row[nz].tolist())
            indptr.append(len(indices))
        return CSRNDArray(jnp.asarray(d), np.asarray(indptr, np.int64),
                          np.asarray(indices, np.int64),
                          np.asarray(vals, d.dtype))

    def __repr__(self):
        return (f"<CSRNDArray {self.shape} nnz={len(self._sdata)} "
                f"@{self.context}>")


class RowSparseNDArray(BaseSparseNDArray):
    """First-dim sparse tensor: (indices, data[rows]) — the embedding-gradient
    format the reference streams through KVStore row_sparse_pull."""

    def __init__(self, dense_data, indices, sdata):
        super().__init__(dense_data)
        self._indices = indices
        self._sdata = sdata

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return nd_array(self._indices)

    @property
    def data(self) -> NDArray:
        return nd_array(self._sdata)

    @staticmethod
    def from_dense(arr: NDArray) -> "RowSparseNDArray":
        d = np.asarray(arr.asnumpy())
        nz_rows = np.nonzero(d.reshape(d.shape[0], -1).any(axis=1))[0]
        return RowSparseNDArray(jnp.asarray(d), nz_rows.astype(np.int64),
                                d[nz_rows])

    def retain(self, rs_indices) -> "RowSparseNDArray":
        keep = set(np.asarray(
            rs_indices.asnumpy() if isinstance(rs_indices, NDArray)
            else rs_indices).astype(np.int64).tolist())
        d = np.array(self.asnumpy())
        mask = np.ones(d.shape[0], bool)
        for i in range(d.shape[0]):
            if i not in keep:
                d[i] = 0
        return RowSparseNDArray.from_dense(nd_array(d))

    def __repr__(self):
        return (f"<RowSparseNDArray {self.shape} rows={len(self._indices)} "
                f"@{self.context}>")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    """csr_matrix((data, indices, indptr), shape=...) or from dense source."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = np.asarray(data, dtype or np.float32)
        indices = np.asarray(indices, np.int64)
        indptr = np.asarray(indptr, np.int64)
        n_rows = len(indptr) - 1
        n_cols = shape[1] if shape else (int(indices.max()) + 1 if len(indices)
                                         else 0)
        dense = np.zeros((n_rows, n_cols), data.dtype)
        for r in range(n_rows):
            for k in range(indptr[r], indptr[r + 1]):
                dense[r, indices[k]] = data[k]
        return CSRNDArray(jnp.asarray(dense), indptr, indices, data)
    return CSRNDArray.from_dense(nd_array(arg1, ctx=ctx, dtype=dtype))


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    """row_sparse_array((data, indices), shape=...) or from dense source."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = np.asarray(data, dtype or np.float32)
        indices = np.asarray(indices, np.int64)
        n_rows = shape[0] if shape else int(indices.max()) + 1
        dense = np.zeros((n_rows,) + data.shape[1:], data.dtype)
        dense[indices] = data
        return RowSparseNDArray(jnp.asarray(dense), indices, data)
    return RowSparseNDArray.from_dense(nd_array(arg1, ctx=ctx, dtype=dtype))
