"""NDArray — the imperative tensor, TPU-native.

Reference: ``src/ndarray/ndarray.cc`` + ``include/mxnet/ndarray.h`` +
``python/mxnet/ndarray/ndarray.py`` (paths TBV — SURVEY.md §2.1 L3).

Redesign for PJRT/XLA (SURVEY.md §7 hard part #1):

- An NDArray **wraps an immutable ``jax.Array``** (a PJRT buffer). The
  reference's per-array engine variable + dependency queue is replaced by
  JAX's async dispatch: every op returns immediately with a future-backed
  buffer, and ``wait_to_read()`` ≡ ``block_until_ready()``.
- MXNet mutation semantics (``x[:] = v``, ``+=``, ``out=``) are kept by
  **rebinding**: the wrapper swaps in a new jax.Array and bumps a version
  counter. Autograd stays correct because tape closures capture the old
  immutable buffer — a mutated input cannot corrupt a recorded gradient
  (the reference needs engine write-locks for the same guarantee).
- Every operator call dispatches through one choke point, :func:`invoke`,
  which consults the op registry and the autograd tape. There are no
  per-backend kernels: the same pure function is executed eagerly here and
  traced under jit in CachedOp/Executor.
"""
from __future__ import annotations

import os
import struct
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# MX_SYNC=1: block after every op (reference MXNET_ENGINE_TYPE=NaiveEngine
# debug mode, SURVEY.md §5.2) — turns async-dispatch bugs and NaN origins
# into synchronous stack traces. Read once at import like the reference.
_MX_SYNC = (os.environ.get("MX_SYNC", "0") not in ("", "0")
            or os.environ.get("MXNET_ENGINE_TYPE") == "NaiveEngine")

from ..base import MXNetError, dtype_np
from ..context import Context, current_context
from ..ops import get_op
from ..ops.registry import OpDef

__all__ = ["NDArray", "invoke", "array", "zeros", "ones", "full", "empty", "arange",
           "save", "load", "concat", "stack", "waitall", "from_jax"]


class NDArray:
    """An n-dimensional array on a device, with async semantics."""

    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_ag_node", "_version", "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(np.asarray(data), dtype=dtype_np(dtype) if dtype else None)
            if data.dtype == jnp.float64:
                data = data.astype(jnp.float32)
            elif data.dtype == jnp.int64:
                data = data.astype(jnp.int32)
        elif dtype is not None:
            data = data.astype(dtype_np(dtype))
        if ctx is not None:
            dev = Context(ctx).jax_device() if not isinstance(ctx, Context) else ctx.jax_device()
            if not isinstance(data, jax.core.Tracer) and not _on_device(data, dev):
                data = jax.device_put(data, dev)
            self._ctx = Context(ctx) if not isinstance(ctx, Context) else ctx
        else:
            self._ctx = None
        self._data = data
        self._grad = None
        self._grad_req = "null"
        self._ag_node = None
        self._version = 0

    # ------------------------------------------------------------------ core
    def asjax(self) -> jax.Array:
        return self._data

    def _set_data(self, new) -> "NDArray":
        if isinstance(new, NDArray):
            # In-place mutation while recording: adopt the source's tape node so
            # the mutating op stays in the gradient chain (x *= 2 then y = x*x
            # differentiates through the *=). The reference raises on in-place
            # under recording; immutable buffers let us support it correctly.
            from .. import autograd

            if autograd.is_recording():
                self._ag_node = new._ag_node
            new = new._data
        self._data = new
        self._version += 1
        return self

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(self._data.size)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        if isinstance(self._data, jax.core.Tracer):
            # Under jit tracing there is no physical placement; report the
            # current default context (placement is the compiler's job).
            return current_context()
        dev = next(iter(self._data.devices()))
        if dev.platform == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    # ------------------------------------------------------------- transfer
    def asnumpy(self) -> np.ndarray:
        """Blocking device→host copy (reference NDArray::SyncCopyToCPU)."""
        from .. import profiler

        if profiler.counting_dispatches() and \
                not isinstance(self._data, jax.core.Tracer):
            profiler.count_dispatch("d2h")
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        """≡ reference WaitToRead; PJRT: block until the buffer is ready."""
        self._data.block_until_ready()
        return self

    def as_in_context(self, ctx) -> "NDArray":
        ctx = Context(ctx) if not isinstance(ctx, Context) else ctx
        if isinstance(self._data, jax.core.Tracer) or ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device()), ctx=ctx)

    as_in_ctx = as_in_context

    def copyto(self, other) -> "NDArray":
        if isinstance(other, Context):
            return self.as_in_context(other)
        other._set_data(jax.device_put(self._data, other.context.jax_device()))
        return other

    def copy(self) -> "NDArray":
        return NDArray(self._data, ctx=self._ctx)

    def astype(self, dtype, copy=True) -> "NDArray":
        d = dtype_np(dtype)
        if not copy and self.dtype == d:
            return self
        # float->float casts are differentiable and must stay on the tape
        # (reference: Cast has a registered backward); raw _wrap would
        # silently detach anything computed through e.g. .astype("float32").
        # jnp.issubdtype, not dtype.kind: ml_dtypes bfloat16 reports kind
        # 'V', which a kind=='f' test would silently detach again.
        if (_recording_this([self])
                and jnp.issubdtype(jnp.dtype(d), jnp.floating)
                and jnp.issubdtype(self._data.dtype, jnp.floating)):
            return invoke_fn(lambda x: x.astype(d), [self])
        return _wrap(self._data.astype(d), self)

    def detach(self) -> "NDArray":
        out = NDArray(self._data, ctx=self._ctx)
        return out

    # ------------------------------------------------------------- autograd
    def attach_grad(self, grad_req: str = "write", stype=None):
        """Mark for gradient computation (reference mx.autograd)."""
        from .. import autograd

        self._grad_req = grad_req
        if grad_req != "null":
            self._grad = NDArray(jnp.zeros_like(self._data), ctx=self._ctx)
            autograd._mark_variable(self)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------- indexing
    def __getitem__(self, key):
        key = _unwrap_key(key)
        if _recording_this([self]):
            return invoke_fn(lambda d: d[key], [self])
        return _wrap(self._data[key], self)

    def __setitem__(self, key, value):
        key = _unwrap_key(key)
        from .. import autograd

        if autograd.is_recording() and isinstance(value, NDArray):
            self._set_data(invoke_fn(lambda d, v: d.at[key].set(v), [self, value]))
            return
        if isinstance(value, NDArray):
            value = value._data
        if not isinstance(value, jax.Array):
            value = jnp.asarray(value, dtype=self._data.dtype)
        self._set_data(self._data.at[key].set(value))

    # ---------------------------------------------------------- arithmetic
    def __add__(self, o):
        return _binary("broadcast_add", "_plus_scalar", self, o)

    def __radd__(self, o):
        return _binary("broadcast_add", "_plus_scalar", self, o)

    def __sub__(self, o):
        return _binary("broadcast_sub", "_minus_scalar", self, o)

    def __rsub__(self, o):
        return invoke("_rminus_scalar", [self], {"scalar": o})

    def __mul__(self, o):
        return _binary("broadcast_mul", "_mul_scalar", self, o)

    def __rmul__(self, o):
        return _binary("broadcast_mul", "_mul_scalar", self, o)

    def __truediv__(self, o):
        return _binary("broadcast_div", "_div_scalar", self, o)

    def __rtruediv__(self, o):
        return invoke("_rdiv_scalar", [self], {"scalar": o})

    def __mod__(self, o):
        return _binary("broadcast_mod", "_mod_scalar", self, o)

    def __pow__(self, o):
        return _binary("broadcast_power", "_power_scalar", self, o)

    def __rpow__(self, o):
        return invoke("_rpower_scalar", [self], {"scalar": o})

    def __neg__(self):
        return invoke("negative", [self], {})

    def __abs__(self):
        return invoke("abs", [self], {})

    def __iadd__(self, o):
        return self._set_data(_binary("broadcast_add", "_plus_scalar", self, o))

    def __isub__(self, o):
        return self._set_data(_binary("broadcast_sub", "_minus_scalar", self, o))

    def __imul__(self, o):
        return self._set_data(_binary("broadcast_mul", "_mul_scalar", self, o))

    def __itruediv__(self, o):
        return self._set_data(_binary("broadcast_div", "_div_scalar", self, o))

    def __eq__(self, o):
        if o is None:
            return False
        return _binary("broadcast_equal", "_equal_scalar", self, o)

    def __ne__(self, o):
        if o is None:
            return True
        return _binary("broadcast_not_equal", "_not_equal_scalar", self, o)

    def __gt__(self, o):
        return _binary("broadcast_greater", "_greater_scalar", self, o)

    def __ge__(self, o):
        return _binary("broadcast_greater_equal", "_greater_equal_scalar", self, o)

    def __lt__(self, o):
        return _binary("broadcast_lesser", "_lesser_scalar", self, o)

    def __le__(self, o):
        return _binary("broadcast_lesser_equal", "_lesser_equal_scalar", self, o)

    def __hash__(self):
        return id(self)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("ambiguous truth value of multi-element NDArray")
        return bool(self.asscalar())

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        return f"\n{self.asnumpy()}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context}>"

    # ------------------------------------------------------- method aliases
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return invoke("reshape", [self], {"shape": shape, **kwargs})

    def reshape_like(self, other):
        return invoke("reshape", [self], {"shape": other.shape})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return invoke("transpose", [self], {"axes": axes or None})

    @property
    def T(self):
        return self.transpose()

    def _op_method(name):  # noqa: N805 — helper to declare forwarding methods
        def m(self, *args, **kwargs):
            return invoke(name, [self], kwargs)

        m.__name__ = name
        return m

    def sum(self, axis=None, keepdims=False, **kw):
        return invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False, **kw):
        return invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False, **kw):
        return invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False, **kw):
        return invoke("min", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False, **kw):
        return invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False, **kw):
        return invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False, **kw):
        return invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def norm(self, **kw):
        return invoke("norm", [self], kw)

    def abs(self):
        return invoke("abs", [self], {})

    def sqrt(self):
        return invoke("sqrt", [self], {})

    def square(self):
        return invoke("square", [self], {})

    def exp(self):
        return invoke("exp", [self], {})

    def log(self):
        return invoke("log", [self], {})

    def relu(self):
        return invoke("relu", [self], {})

    def sigmoid(self):
        return invoke("sigmoid", [self], {})

    def tanh(self):
        return invoke("tanh", [self], {})

    def clip(self, a_min=None, a_max=None):
        return invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def expand_dims(self, axis):
        return invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return invoke("squeeze", [self], {"axis": axis})

    def flatten(self):
        return invoke("Flatten", [self], {})

    def flip(self, axis):
        return invoke("flip", [self], {"axis": axis})

    def tile(self, reps):
        return invoke("tile", [self], {"reps": reps})

    def repeat(self, repeats, axis=None):
        return invoke("repeat", [self], {"repeats": repeats, "axis": axis})

    def broadcast_to(self, shape):
        return invoke("broadcast_to", [self], {"shape": shape})

    def broadcast_like(self, other):
        return invoke("broadcast_like", [self, other], {})

    def slice_axis(self, axis, begin, end):
        return invoke("slice_axis", [self], {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return invoke("take", [self, indices], {"axis": axis, "mode": mode})

    def one_hot(self, depth, **kw):
        return invoke("one_hot", [self], {"depth": depth, **kw})

    def topk(self, **kw):
        return invoke("topk", [self], kw)

    def sort(self, **kw):
        return invoke("sort", [self], kw)

    def argsort(self, **kw):
        return invoke("argsort", [self], kw)

    def dot(self, other, **kw):
        return invoke("dot", [self, other], kw)

    def softmax(self, axis=-1):
        return invoke("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return invoke("log_softmax", [self], {"axis": axis})

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse

        return sparse.cast_storage(self, stype)

    def zeros_like(self):
        return invoke("zeros_like", [self], {})

    def ones_like(self):
        return invoke("ones_like", [self], {})


def _on_device(arr: jax.Array, dev) -> bool:
    try:
        return set(arr.devices()) == {dev}
    except Exception:
        return False


def _unwrap_key(key):
    if isinstance(key, NDArray):
        return key._data.astype(jnp.int32)
    if isinstance(key, tuple):
        return tuple(_unwrap_key(k) for k in key)
    return key


def _wrap(data: jax.Array, like: Optional[NDArray] = None) -> NDArray:
    return NDArray(data, ctx=like._ctx if like is not None else None)


def _recording_this(inputs) -> bool:
    from .. import autograd

    return autograd.is_recording()


def _binary(op_name, scalar_op, lhs, rhs):
    if isinstance(rhs, NDArray):
        return invoke(op_name, [lhs, rhs], {})
    return invoke(scalar_op, [lhs], {"scalar": rhs})


# ---------------------------------------------------------------------------
# The dispatch choke point
# ---------------------------------------------------------------------------

def invoke(op: Any, inputs: Sequence[NDArray], kwargs: dict):
    """Execute a registered op eagerly, recording on the autograd tape if active.

    Analog of reference ``MXImperativeInvokeEx`` → ``Imperative::Invoke``
    (src/c_api/c_api_ndarray.cc, src/imperative/imperative.cc — TBV).
    """
    opdef = op if isinstance(op, OpDef) else get_op(op)
    out = kwargs.pop("out", None)
    from .. import autograd, profiler

    datas = [x._data if isinstance(x, NDArray) else x for x in inputs]
    # CachedOp dispatches count as "compiled" at their own call site
    if profiler.counting_dispatches() and not any(
            isinstance(d, jax.core.Tracer) for d in datas) \
            and not opdef.name.startswith("CachedOp_"):
        profiler.count_dispatch("eager_ops")
    # skip timing under trace: block_until_ready is a no-op on tracers, so
    # the "duration" would be trace-construction overhead, not execution
    timing = profiler.aggregate_active() and not any(
        isinstance(d, jax.core.Tracer) for d in datas)
    if timing:
        import time as _time

        t0 = _time.perf_counter()
    if autograd.is_recording() and opdef.differentiable:
        result = autograd._record_op(opdef, inputs, datas, kwargs)
    else:
        result = opdef.fn(*datas, **kwargs)
        result = _wrap_result(result, inputs)
    if timing:
        jax.block_until_ready([r._data for r in
                               (result if isinstance(result, (list, tuple))
                                else [result]) if isinstance(r, NDArray)])
        profiler.record_op(opdef.name, _time.perf_counter() - t0)
    if out is not None:
        if isinstance(result, (list, tuple)):
            for o, r in zip(out if isinstance(out, (list, tuple)) else [out], result):
                o._set_data(r._data)
        else:
            out._set_data(result._data)
        result = out
    if _MX_SYNC:
        for r in result if isinstance(result, (list, tuple)) else [result]:
            if isinstance(r, NDArray):
                r.wait_to_read()
    return result


def invoke_fn(fn, inputs: Sequence[NDArray], kwargs=None):
    """Invoke an ad-hoc pure function as if it were an op (used by __getitem__
    and contrib paths). Dispatches on input type: with Symbol inputs the
    function is spliced into the graph as one inline-OpDef node
    (symbol.invoke_fn), so F-generic hybrid_forward code using this escape
    hatch stays symbolically traceable."""
    from ..symbol.symbol import Symbol, invoke_fn as _sym_invoke_fn

    if any(isinstance(x, Symbol) for x in inputs):
        return _sym_invoke_fn(fn, inputs, kwargs)
    opdef = OpDef("<lambda>", fn, num_outputs=1)
    return invoke(opdef, inputs, kwargs or {})


def _wrap_result(result, inputs):
    like = next((x for x in inputs if isinstance(x, NDArray)), None)
    if isinstance(result, (list, tuple)):
        return tuple(_wrap(r, like) for r in result)
    return _wrap(result, like)


# ---------------------------------------------------------------------------
# Creation / io
# ---------------------------------------------------------------------------

def array(source_array, ctx=None, dtype=None) -> NDArray:
    """Create an NDArray. Reference dtype rule: np.ndarray sources keep their
    dtype; python lists/scalars default to float32."""
    if dtype is None and not isinstance(source_array, (np.ndarray, jax.Array, NDArray)):
        dtype = np.float32
    return NDArray(source_array, ctx=ctx or current_context(), dtype=dtype)


def from_jax(arr: jax.Array) -> NDArray:
    return NDArray(arr)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kw) -> NDArray:
    from ..base import dtype_name

    return invoke("_zeros", [], {"shape": _tup(shape), "dtype": dtype_name(dtype or "float32"),
                                 "ctx": None}) .as_in_context(ctx or current_context())


def ones(shape, ctx=None, dtype=None, **kw) -> NDArray:
    from ..base import dtype_name

    return invoke("_ones", [], {"shape": _tup(shape), "dtype": dtype_name(dtype or "float32"),
                                "ctx": None}).as_in_context(ctx or current_context())


def full(shape, val, ctx=None, dtype=None, **kw) -> NDArray:
    from ..base import dtype_name

    return invoke("_full", [], {"shape": _tup(shape), "value": val,
                                "dtype": dtype_name(dtype or "float32"),
                                "ctx": None}).as_in_context(ctx or current_context())


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32") -> NDArray:
    return invoke("_arange", [], {"start": start, "stop": stop, "step": step,
                                  "repeat": repeat, "dtype": dtype,
                                  "ctx": None}).as_in_context(ctx or current_context())


def _tup(shape):
    return (shape,) if isinstance(shape, int) else tuple(shape)


def concat(*data, dim=1):
    return invoke("Concat", list(data), {"dim": dim})


def stack(*data, axis=0):
    return invoke("stack", list(data), {"axis": axis})


def waitall():
    """Block until all launched work is done (reference MXNDArrayWaitAll)."""
    (jax.effects_barrier if hasattr(jax, "effects_barrier") else lambda: None)()


# ---------------------------------------------------------------------------
# save / load — reference NDArray serialization API (MXNDArraySave/Load).
# Format: the reference binary list container (see ndarray/serialization.py);
# load() also accepts the npz container earlier TPU builds wrote.
# ---------------------------------------------------------------------------

def save(fname: str, data) -> None:
    from .serialization import save_nd

    if isinstance(data, NDArray):
        keys, arrays = [], [data]
    elif isinstance(data, dict):
        keys = list(data.keys())
        arrays = [data[k] for k in keys]
    elif isinstance(data, (list, tuple)):
        keys, arrays = [], list(data)
    else:
        raise TypeError(f"cannot save {type(data)}")
    # ONE batched device→host gather for the whole set (not a blocking
    # asnumpy per array) — checkpoints of many-parameter models sync once
    host = jax.device_get([a._data for a in arrays])
    save_nd(fname, [np.asarray(h) for h in host], keys)


def load(fname: str):
    from .serialization import is_binary_nd, load_nd

    path = _npz_path(fname)
    with open(path, "rb") as f:
        head = f.read(8)
    if is_binary_nd(head):
        out = load_nd(path)
        if isinstance(out, dict):
            return {k: NDArray(v) for k, v in out.items()}
        return [NDArray(v) for v in out]
    with np.load(path, allow_pickle=False) as z:  # legacy npz container
        keys = list(z.keys())
        if keys == ["__single__"]:
            return [NDArray(z["__single__"])]
        if all(k.startswith("__list_") for k in keys):
            return [NDArray(z[f"__list_{i}__"]) for i in range(len(keys))]
        return {k: NDArray(z[k]) for k in keys}


def _npz_path(fname):
    import os

    if os.path.exists(fname):
        return fname
    if os.path.exists(fname + ".npz"):
        return fname + ".npz"
    return fname
