"""Reference-compatible binary NDArray serialization.

Implements the upstream ``MXNDArraySave``/``MXNDArrayLoad`` container format
(reference ``src/ndarray/ndarray.cc`` NDArray::Save/Load and
``src/c_api/c_api.cc`` — expected paths per SURVEY.md §5.4; the reference
mount was empty this round so byte layout is reconstructed from the public
Apache MXNet 1.x format, TBV against a real ``.params`` file when available):

    file   := u64 list_magic(0x112) | u64 reserved(0)
              | u64 n_arrays | array*  | u64 n_names | dmlc_str*
              | [u64 crc_magic | u32 crc32]          (optional footer)
    array  := u32 nd_magic | i32 stype | u32 ndim | i64*ndim shape
              | i32 dev_type | i32 dev_id | i32 type_flag | raw data
    dmlc_str := u64 len | bytes

Dense arrays only (stype 0); sparse NDArrays are densified on save with a
warning. ndim==0 encodes a "none" array (no context/dtype/data follow).

Robustness extensions (docs/ROBUSTNESS.md): ``save_nd`` commits via
temp-file + fsync + rename (a crashed save never leaves a half-written
.params file) and appends a CRC32 footer over the whole container;
``load_nd`` verifies the footer when present and rejects any other trailing
bytes, so truncation and bit flips surface as a clean ``ValueError`` rather
than silently corrupt weights. Reference-format files written by upstream
MXNet (no footer) still load.
"""
from __future__ import annotations

import struct
import warnings
from typing import Dict, List, Union

import numpy as np

from ..checkpoint.atomic import atomic_write_bytes, crc32_bytes

_LIST_MAGIC = 0x112
_CRC_MAGIC = 0x314352435F544B43  # "CKT_CRC1" little-endian
_CRC_FOOTER_LEN = 12  # u64 magic + u32 crc32
# reference ndarray.cc: V1 = int64 TShape, V2 = +storage type, V3 = np-shape
_ND_V1 = 0xF993FAC8
_ND_V2 = 0xF993FAC9
_ND_V3 = 0xF993FACA

# reference mshadow type flags (mshadow/base.h)
_TYPE_FLAG_TO_DTYPE = {
    0: np.dtype(np.float32),
    1: np.dtype(np.float64),
    2: np.dtype(np.float16),
    3: np.dtype(np.uint8),
    4: np.dtype(np.int32),
    5: np.dtype(np.int8),
    6: np.dtype(np.int64),
    7: np.dtype(np.bool_),
    8: np.dtype(np.int16),
    9: np.dtype(np.uint16),
    10: np.dtype(np.uint32),
    11: np.dtype(np.uint64),
}
_DTYPE_TO_TYPE_FLAG = {v: k for k, v in _TYPE_FLAG_TO_DTYPE.items()}
try:  # TPU-build extension: bfloat16 uses the 1.x kBfloat16 slot
    import ml_dtypes

    _TYPE_FLAG_TO_DTYPE[12] = np.dtype(ml_dtypes.bfloat16)
    _DTYPE_TO_TYPE_FLAG[np.dtype(ml_dtypes.bfloat16)] = 12
except ImportError:  # pragma: no cover
    pass

_CPU_DEV_TYPE = 1  # Context::kCPU — loads are device-agnostic anyway


def _write_array(out: List[bytes], arr: np.ndarray) -> None:
    if arr.ndim == 0:
        # The reference's ndim==0 record means "none" and carries NO
        # ctx/dtype/data (1.x NDArrays are never 0-d; legacy scalars are
        # shape (1,)). Writing trailing bytes after ndim=0 would desync any
        # reader — promote genuine 0-d saves to shape (1,) instead.
        warnings.warn("0-d NDArray saved as shape (1,) for reference "
                      "format compatibility")
        arr = arr.reshape(1)
    out.append(struct.pack("<Ii", _ND_V2, 0))  # magic, stype=default(dense)
    out.append(struct.pack("<I", arr.ndim))
    out.append(struct.pack(f"<{arr.ndim}q", *arr.shape))
    out.append(struct.pack("<ii", _CPU_DEV_TYPE, 0))
    flag = _DTYPE_TO_TYPE_FLAG.get(arr.dtype)
    if flag is None:
        raise TypeError(f"dtype {arr.dtype} has no reference type flag")
    out.append(struct.pack("<i", flag))
    out.append(np.ascontiguousarray(arr).tobytes())


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise ValueError("truncated NDArray file")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def unpack(self, fmt: str):
        return struct.unpack(fmt, self.take(struct.calcsize(fmt)))


def _read_array(r: _Reader) -> np.ndarray:
    (magic,) = r.unpack("<I")
    if magic not in (_ND_V1, _ND_V2, _ND_V3):
        raise ValueError(f"bad NDArray record magic {magic:#x}")
    if magic in (_ND_V2, _ND_V3):
        (stype,) = r.unpack("<i")
        if stype != 0:
            raise ValueError(f"sparse storage type {stype} not supported on load")
    (ndim,) = r.unpack("<I")
    if ndim == 0:
        return np.zeros((), np.float32)  # reference "none" placeholder
    if ndim > 32:
        raise ValueError(f"implausible ndim {ndim}")
    shape = r.unpack(f"<{ndim}q")
    r.unpack("<ii")  # dev_type, dev_id — ignored, loads land on default ctx
    (flag,) = r.unpack("<i")
    dtype = _TYPE_FLAG_TO_DTYPE.get(flag)
    if dtype is None:
        raise ValueError(f"unknown type flag {flag}")
    count = int(np.prod(shape)) if ndim else 1
    data = r.take(count * dtype.itemsize)
    return np.frombuffer(data, dtype=dtype).reshape(shape).copy()


def save_nd(fname: str, arrays: List[np.ndarray], names: List[str],
            crc: bool = True, durable: bool = True) -> None:
    """Write the reference list container. ``names`` may be empty (list save).

    Crash-safe by default: the bytes are committed via temp-file + fsync +
    rename, and a CRC32 footer covers the whole container (``crc=False``
    reproduces the plain upstream byte layout for cross-version tests).
    """
    out: List[bytes] = [struct.pack("<QQ", _LIST_MAGIC, 0),
                        struct.pack("<Q", len(arrays))]
    for a in arrays:
        _write_array(out, a)
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode("utf-8")
        out.append(struct.pack("<Q", len(b)))
        out.append(b)
    body = b"".join(out)
    if crc:
        body += struct.pack("<QI", _CRC_MAGIC, crc32_bytes(body))
    atomic_write_bytes(fname, body, durable=durable)


def is_binary_nd(head: bytes) -> bool:
    return len(head) >= 8 and struct.unpack("<Q", head[:8])[0] == _LIST_MAGIC


def load_nd(fname: str) -> Union[List[np.ndarray], Dict[str, np.ndarray]]:
    with open(fname, "rb") as f:
        buf = f.read()
    r = _Reader(buf)
    magic, _reserved = r.unpack("<QQ")
    if magic != _LIST_MAGIC:
        raise ValueError(f"not an NDArray file (magic {magic:#x})")
    (n,) = r.unpack("<Q")
    if n > 1_000_000:
        raise ValueError(f"implausible array count {n}")
    arrays = [_read_array(r) for _ in range(n)]
    (n_names,) = r.unpack("<Q")
    if n_names not in (0, n):
        raise ValueError(f"{n} arrays but {n_names} names")
    names = [r.take(r.unpack("<Q")[0]).decode("utf-8") for _ in range(n_names)]
    _verify_footer(buf, r.pos)
    if n_names == 0:
        return arrays
    return dict(zip(names, arrays))


def _verify_footer(buf: bytes, end: int) -> None:
    """Verify the optional CRC32 footer. Zero trailing bytes = legacy
    (upstream) file, accepted; a valid footer must match; anything else is
    truncation or corruption and is rejected."""
    remaining = len(buf) - end
    if remaining == 0:
        return
    if remaining != _CRC_FOOTER_LEN:
        raise ValueError(
            f"{remaining} unexpected trailing bytes (truncated file or "
            "damaged CRC footer)")
    magic, crc = struct.unpack_from("<QI", buf, end)
    if magic != _CRC_MAGIC:
        raise ValueError(f"bad CRC footer magic {magic:#x}")
    actual = crc32_bytes(buf[:end])
    if actual != crc:
        raise ValueError(
            f"CRC mismatch: footer {crc:#010x} != computed {actual:#010x} "
            "(file is corrupt)")
