"""``mx.nd.linalg`` — linear-algebra op namespace (reference
python/mxnet/ndarray/linalg.py: ``nd.linalg.gemm2`` etc. resolve to the
``_linalg_*`` registrations the flat ``nd.linalg_gemm2`` aliases expose)."""
from __future__ import annotations

from ..ops import has_op
from . import _make_dispatcher


def __getattr__(name: str):
    for cand in (f"_linalg_{name}", f"linalg_{name}", name):
        if has_op(cand):
            fn = _make_dispatcher(cand)
            globals()[name] = fn
            return fn
    raise AttributeError(f"no linalg operator {name!r}")
