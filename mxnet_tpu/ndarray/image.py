"""``mx.nd.image`` — image op namespace (reference python/mxnet/ndarray/
image.py, generated from the ``_image_*`` registry names — TBV).

Resolves ``nd.image.to_tensor`` → registered op ``_image_to_tensor``.
"""
from __future__ import annotations

from ..ops import has_op
from . import _make_dispatcher


def __getattr__(name: str):
    cand = f"_image_{name}"
    if has_op(cand):
        fn = _make_dispatcher(cand)
        globals()[name] = fn
        return fn
    raise AttributeError(f"no image operator {name!r}")
