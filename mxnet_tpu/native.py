"""Lazy builder/loader for the native (C++) components.

The reference ships its native core as libmxnet.so built ahead of time
(SURVEY.md §1); here the native pieces are small and build on demand with
g++ (seconds), with pure-Python fallbacks when a toolchain is absent:

- ``io_lib()``  → ctypes handle to libmxtpu_io.so (RecordIO+JPEG batch
  decode pipeline — C++ counterpart of src/io/iter_image_recordio_2.cc).
- ``ps_server_binary()`` → path to mxtpu_ps_server (ps-lite analog).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

__all__ = ["io_lib", "ps_server_binary", "native_dir", "build"]

_lock = threading.Lock()
_cache: dict = {}


def native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "native")


def _build_target(target: str) -> str | None:
    nd = native_dir()
    out = os.path.join(nd, "build", target)
    if os.path.exists(out):
        return out
    if os.environ.get("MXNET_NO_NATIVE_BUILD"):
        return None
    try:
        subprocess.run(["make", "-C", nd, os.path.join("build", target)],
                       check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired):
        return None
    return out if os.path.exists(out) else None


def build() -> bool:
    """Build everything; returns True if all targets exist."""
    return all(_build_target(t) for t in ("libmxtpu_io.so", "mxtpu_ps_server"))


def io_lib():
    """ctypes CDLL of the IO pipeline, or None if unavailable."""
    with _lock:
        if "io" not in _cache:
            path = _build_target("libmxtpu_io.so")
            lib = None
            if path:
                try:
                    lib = ctypes.CDLL(path)
                    lib.mxtpu_decode_batch.restype = ctypes.c_int
                    lib.mxtpu_decode_batch.argtypes = [
                        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                        ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
                        ctypes.POINTER(ctypes.c_float),
                        ctypes.POINTER(ctypes.c_float),
                        ctypes.POINTER(ctypes.c_float),
                        ctypes.POINTER(ctypes.c_float), ctypes.c_int,
                        ctypes.c_int]
                    lib.mxtpu_decode_batch_u8.restype = ctypes.c_int
                    lib.mxtpu_decode_batch_u8.argtypes = [
                        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                        ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
                        ctypes.POINTER(ctypes.c_uint8),
                        ctypes.POINTER(ctypes.c_float), ctypes.c_int,
                        ctypes.c_int]
                    lib.mxtpu_scan_offsets.restype = ctypes.c_int64
                    lib.mxtpu_scan_offsets.argtypes = [
                        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                        ctypes.c_int64]
                except (OSError, AttributeError):
                    # OSError: unloadable .so; AttributeError: stale build
                    # missing a newer symbol — fall back to the PIL path
                    lib = None
            _cache["io"] = lib
        return _cache["io"]


def ps_server_binary() -> str | None:
    with _lock:
        if "ps" not in _cache:
            _cache["ps"] = _build_target("mxtpu_ps_server")
        return _cache["ps"]
