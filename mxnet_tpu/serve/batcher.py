"""Dynamic micro-batching scheduler — the concurrency half of
``mxnet_tpu.serve``.

Reference: the MXNet Model Server's dynamic batcher (max-batch-size +
max-batch-delay per model — TBV, SURVEY.md §1). Redesigned around SLOs:

- **Bounded queue + load shedding**: beyond ``max_queue`` queued requests
  the submitter gets an immediate :class:`RequestRejected` (fail-fast
  429), never an unbounded latency tail. Shedding is the *client's* signal
  to back off; a silently growing queue turns overload into timeouts for
  everyone.
- **Deadline propagation**: each request may carry a deadline. Expired
  requests are shed — at submit, while queued, and at batch assembly —
  instead of executed: work whose answer nobody is waiting for anymore
  only steals capacity from requests that can still meet their SLO.
- **Priority lanes**: lane 0 is the tight-SLO lane. Assembly always starts
  from the highest non-empty lane, and a batch never *waits* on lower-lane
  stragglers — so an interactive request is never head-of-line-blocked
  behind a bulk scan that happens to be in front of it.
- **Linger**: after the first request is picked, assembly tops the batch up
  with shape-compatible requests for at most ``max_linger_ms`` — capped by
  the earliest member deadline, so lingering can't itself blow an SLO.

Every phase is telemetered (docs/OBSERVABILITY.md): per-request
``serve.queue_wait`` spans (recorded retroactively with the real enqueue
timestamp), ``serve.batch_assembly`` spans, shed counters by cause, queue
depth gauge, end-to-end ``serve.latency_seconds`` histogram.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from .. import copytrack, obs, tsan
from ..obs import context as obs_context
from .engine import DeadlineExceeded, Draining, RequestRejected, ServeError

__all__ = ["DynamicBatcher", "Future"]


class Future:
    """Completion handle for a submitted request."""

    __slots__ = ("_event", "_result", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def _set_result(self, result) -> None:
        self._result = result
        self._event.set()

    def _set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        """Block for ``(outputs, param_version)``; raises the request's
        error (DeadlineExceeded on wait timeout)."""
        if not self._event.wait(timeout):
            raise DeadlineExceeded("timed out waiting for inference result")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("data", "n", "feat", "deadline", "priority", "t_enqueue",
                 "future", "ctx")

    def __init__(self, data: List[np.ndarray], deadline: Optional[float],
                 priority: int):
        self.data = data
        self.n = int(data[0].shape[0])
        # batchable iff per-row feature shapes and dtypes agree
        self.feat = tuple((a.shape[1:], str(a.dtype)) for a in data)
        self.deadline = deadline
        self.priority = priority
        self.t_enqueue = time.monotonic()
        self.future = Future()
        # the submitter's trace context crosses to the batcher thread WITH
        # the request: queue_wait/execute spans recorded over there still
        # hang off the serve.rpc span that enqueued it
        self.ctx = obs_context.current()


class DynamicBatcher:
    """Assemble concurrent requests into engine-sized batches.

    Parameters
    ----------
    engine : InferenceEngine
        The compiled executor batches are dispatched to.
    max_batch_size : int, optional
        Rows per assembled batch (default: the engine's top bucket).
    max_linger_ms : float
        How long assembly may wait to top up a non-full batch. 0 disables
        lingering (every request dispatches immediately).
    max_queue : int
        Queued-request watermark; submissions beyond it are shed with
        :class:`RequestRejected`.
    lanes : int
        Priority lanes; 0 is served first. Default 2 (interactive / bulk).
    """

    def __init__(self, engine, *, max_batch_size: Optional[int] = None,
                 max_linger_ms: float = 2.0, max_queue: int = 256,
                 lanes: int = 2):
        if lanes < 1:
            raise ValueError("need at least one priority lane")
        self.engine = engine
        self.max_batch_size = int(max_batch_size or engine.max_batch_size)
        self.max_linger = max(float(max_linger_ms), 0.0) / 1e3
        self.max_queue = int(max_queue)
        self._lanes: List[List[_Request]] = [[] for _ in range(lanes)]
        self._qsize = 0
        self._cv = tsan.condition("serve.batcher.cv")
        self._running = True
        self._draining = False
        self._inflight = 0
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        # batch-occupancy accounting (unconditional, like the counters
        # above — the autoscaler reads it through STATS with obs off):
        # rows-per-dispatched-batch over max_batch_size, EWMA'd so stats()
        # reports RECENT pressure, not a lifetime average that a quiet
        # hour would freeze high
        self.exec_batches = 0
        self.exec_rows = 0
        self._occ_ewma = 0.0
        # sheds counted by cause (queue_full / deadline / draining):
        # "the endpoint shed 40 requests" is an alert, "38 deadline-expired
        # vs 2 queue-overflow" is a diagnosis — and the fleet STATS endpoint
        # surfaces this per replica
        self.shed_by_reason = {"queue_full": 0, "deadline": 0, "draining": 0}
        # None until close(); then True iff the worker thread exited within
        # the join budget (a leaked batcher thread pins the engine and its
        # device buffers — the fleet's stop accounting reads this)
        self.stopped_clean: Optional[bool] = None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxnet-tpu-serve-batcher")
        self._thread.start()

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def submit(self, inputs, deadline_ms: Optional[float] = None,
               priority: int = 1) -> Future:
        """Enqueue one request (``inputs``: one array per engine data
        input). ``deadline_ms`` is a relative latency budget from now;
        ``priority`` 0 is the tight-SLO lane. Raises immediately when the
        request cannot be served (queue full / draining / dead on
        arrival) — fail fast, don't queue doomed work."""
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        arrays = [np.ascontiguousarray(np.asarray(x)) for x in inputs]
        if not arrays or arrays[0].ndim < 1:
            raise ServeError("request inputs must have a batch dimension")
        now = time.monotonic()
        deadline = now + deadline_ms / 1e3 if deadline_ms else None
        lane = min(max(int(priority), 0), len(self._lanes) - 1)
        req = _Request(arrays, deadline, lane)
        with self._cv:
            if not self._running:
                raise ServeError("batcher is closed")
            if self._draining:
                self.shed += 1  # the aggregate must equal sum(by_reason)
                self.shed_by_reason["draining"] += 1
                obs.inc("serve.shed_draining")
                raise Draining("endpoint is draining; request refused")
            if self._qsize >= self.max_queue:
                self.shed += 1
                self.shed_by_reason["queue_full"] += 1
                obs.inc("serve.shed_queue_full")
                raise RequestRejected(
                    f"queue over watermark ({self.max_queue} requests); "
                    "back off and retry")
            # fresh clock read: ``deadline`` was built from ``now``, so
            # comparing against ``now`` itself can never fire — a
            # sub-resolution budget must still be dead on arrival
            if deadline is not None and deadline <= time.monotonic():
                self.shed += 1
                self.shed_by_reason["deadline"] += 1
                obs.inc("serve.shed_deadline")
                raise DeadlineExceeded("deadline expired before enqueue")
            self._lanes[lane].append(req)
            self._qsize += 1
            self.submitted += 1
            depth = self._qsize
            self._cv.notify_all()
        obs.set_gauge("serve.queue_depth", depth)
        return req.future

    # ------------------------------------------------------------------
    # worker
    # ------------------------------------------------------------------
    def _shed_locked(self, req: _Request, why: str) -> None:
        self.shed += 1
        self.shed_by_reason[why] = self.shed_by_reason.get(why, 0) + 1
        obs.inc(f"serve.shed_{why}")
        req.future._set_error(DeadlineExceeded(
            f"deadline expired while queued ({why}); request shed, "
            "not executed"))

    def _pop_next_locked(self) -> Optional[_Request]:
        """First request of the highest-priority non-empty lane, shedding
        anything already past its deadline on the way."""
        now = time.monotonic()
        for lane in self._lanes:
            while lane:
                req = lane.pop(0)
                self._qsize -= 1
                if req.deadline is not None and req.deadline <= now:
                    self._shed_locked(req, "deadline")
                    continue
                return req
        return None

    def _top_up_locked(self, batch: List[_Request], rows: int) -> int:
        """Pull shape-compatible requests (priority order, FIFO in lane)
        into ``batch`` until the row budget is exhausted. Non-matching
        requests keep their queue position."""
        feat = batch[0].feat
        now = time.monotonic()
        for lane in self._lanes:
            i = 0
            while i < len(lane) and rows < self.max_batch_size:
                req = lane[i]
                if req.deadline is not None and req.deadline <= now:
                    lane.pop(i)
                    self._qsize -= 1
                    self._shed_locked(req, "deadline")
                    continue
                if req.feat == feat and rows + req.n <= self.max_batch_size:
                    lane.pop(i)
                    self._qsize -= 1
                    batch.append(req)
                    rows += req.n
                    continue
                i += 1
        return rows

    @staticmethod
    def _linger_end(batch: List[_Request], cap: float) -> float:
        """Lingering must not blow ANY member's SLO — recomputed after
        every top-up, since a tight-deadline request may join mid-linger."""
        for r in batch:
            if r.deadline is not None:
                cap = min(cap, r.deadline)
        return cap

    def _assemble(self) -> Optional[List[_Request]]:
        """Block for work, then gather one batch (linger included)."""
        with self._cv:
            while self._running and self._qsize == 0:
                # submit()/close() notify; the timeout is only a lost-wakeup
                # safety net, not a poll interval
                self._cv.wait(timeout=0.5)
            if not self._running and self._qsize == 0:
                return None
            first = self._pop_next_locked()
            if first is None:
                return None
            batch = [first]
            rows = self._top_up_locked(batch, first.n)
            if self.max_linger > 0 and rows < self.max_batch_size:
                cap = time.monotonic() + self.max_linger
                while rows < self.max_batch_size:
                    remaining = self._linger_end(batch, cap) - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
                    rows = self._top_up_locked(batch, rows)
                    if not self._running:
                        break
            # shed members whose deadline expired while the batch lingered
            # (the NEVER-executed-late contract; a member that joined with
            # a tight deadline may have run out of budget waiting)
            now = time.monotonic()
            live = []
            for r in batch:
                if r.deadline is not None and r.deadline <= now:
                    self._shed_locked(r, "deadline")
                else:
                    live.append(r)
            batch = live
            if batch:
                self._inflight += 1
            depth = self._qsize
        obs.set_gauge("serve.queue_depth", depth)
        return batch or None

    def _execute(self, batch: List[_Request]) -> None:
        t_exec = time.monotonic()
        rows = sum(r.n for r in batch)
        occ = rows / float(self.max_batch_size)
        self.exec_batches += 1
        self.exec_rows += rows
        self._occ_ewma = occ if self.exec_batches == 1 \
            else 0.7 * self._occ_ewma + 0.3 * occ
        obs.set_gauge("serve.batch_occupancy", occ)
        rec = obs.enabled()
        # batch-level spans pin to the first SAMPLED member's trace — a
        # batch serves many traces, and under head sampling the member
        # that happened to open it may be unsampled; a sampled request
        # must never lose its execute/assembly spans to an unsampled lead
        lead_ctx = batch[0].ctx
        for r in batch:
            if r.ctx is not None and r.ctx.sampled:
                lead_ctx = r.ctx
                break
        if rec:
            for r in batch:
                # retroactive span: the wait happened on the caller's
                # timeline, measured here where both endpoints are known;
                # pinned to the request's OWN trace context
                obs.trace.complete("serve.queue_wait", r.t_enqueue,
                                   t_exec - r.t_enqueue, ctx=r.ctx,
                                   priority=r.priority, rows=r.n)
            obs.trace.complete("serve.batch_assembly", batch[0].t_enqueue,
                               t_exec - batch[0].t_enqueue,
                               ctx=lead_ctx,
                               requests=len(batch), rows=rows)
            obs.observe("serve.batch_rows", rows)
            obs.observe("serve.batch_requests", len(batch))
        try:
            if len(batch) == 1:
                inputs = batch[0].data
            else:
                inputs = [np.concatenate([r.data[i] for r in batch], axis=0)
                          for i in range(len(batch[0].data))]
                # per-batch assembly copy, counted for the wire_hop bench
                copytrack.TRACKER.copied(sum(a.nbytes for a in inputs))
            with obs_context.use(lead_ctx):
                outs, version = self.engine.infer(inputs, n_valid=rows)
            lo = 0
            done_t = time.monotonic()
            for r in batch:
                r.future._set_result(
                    ([o[lo:lo + r.n] for o in outs], version))
                lo += r.n
                if rec:
                    obs.observe("serve.latency_seconds",
                                done_t - r.t_enqueue)
            self.completed += len(batch)
        except BaseException as e:  # noqa: BLE001 — forwarded to waiters
            obs.inc("serve.execute_errors")
            err = e if isinstance(e, ServeError) else ServeError(
                f"inference execution failed: {type(e).__name__}: {e}")
            for r in batch:
                r.future._set_error(err)
        finally:
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()

    def _loop(self) -> None:
        while True:
            batch = self._assemble()
            if batch is None:
                if not self._running:
                    return
                continue
            self._execute(batch)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def queue_depth(self) -> int:
        return self._qsize

    def stats(self) -> dict:
        return {"submitted": self.submitted, "completed": self.completed,
                "shed": self.shed, "shed_by_reason": dict(self.shed_by_reason),
                "stopped_clean": self.stopped_clean,
                "queue_depth": self._qsize,
                "occupancy": round(self._occ_ewma, 4),
                "batches_executed": self.exec_batches,
                "rows_executed": self.exec_rows,
                "inflight": self._inflight, "lanes": len(self._lanes),
                "max_batch_size": self.max_batch_size,
                "max_linger_ms": self.max_linger * 1e3,
                "max_queue": self.max_queue}

    def drain(self, timeout: float = 30.0) -> bool:
        """Refuse new work, then wait for queued + in-flight requests to
        finish. True when fully drained."""
        deadline = time.monotonic() + timeout
        with self._cv:
            self._draining = True
            self._cv.notify_all()
            while self._qsize > 0 or self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=remaining)
        return True

    def close(self, timeout: float = 30.0) -> None:
        self.drain(timeout)
        with self._cv:
            self._running = False
            self._cv.notify_all()
        self._thread.join(timeout=5)
        # a timed-out join silently LEAKS the worker (join returns None
        # either way): surface it as a structured warning + flag instead
        # of pretending the shutdown was clean
        self.stopped_clean = not self._thread.is_alive()
        if not self.stopped_clean:
            obs.inc("serve.batcher_thread_leaked")
            obs.event("serve.batcher_thread_leaked", join_timeout_s=5,
                      inflight=self._inflight, queue_depth=self._qsize)
