"""Threaded socket front end for ``mxnet_tpu.serve``.

Reference: MXNet Model Server's HTTP front end over CachedOp workers (TBV,
SURVEY.md §1). This build reuses the parameter-server wire format
(``kvstore/ps_server.py``: length-prefixed binary framing, the same
``_pack_array`` array encoding) on a disjoint opcode range, so one set of
framing/chaos/telemetry tooling covers both the training and serving
planes.

Wire protocol (little-endian, see ``kvstore/ps_server.py`` for framing):

  INFER  request : f64 deadline_ms (0 = none) | u8 priority | packed arrays
  INFER  reply   : u8 status | (ok: u32 param_version | packed arrays)
                               (err: utf-8 message)
  HEALTH reply   : u8 0 — process liveness only
  READY  reply   : u8 status — 0 ready / DRAINING / NOT_READY
  RELOAD request : utf-8 json {"path": ..., "epoch": ..., "prefix": ...}
  RELOAD reply   : u8 status | (ok: u32 new_version; err: message)
  STATS  reply   : u8 0 | utf-8 json (engine + batcher + server stats)
  DRAIN  request : u8 stop_after (0/1)
  DRAIN  reply   : u8 0 once queued + in-flight work finished
  TELEMETRY request : utf-8 json {"drain": bool (default true),
                   "format": "json"|"prometheus",
                   "openmetrics": bool (default true; false = strict
                   text format 0.0.4, no exemplars/EOF — for textfile
                   collectors)} (empty = defaults).
  TELEMETRY reply: u8 status | utf-8 blob — json: {"parts": [telemetry
                   part, ...]} (obs.telemetry_part schema: pid, role,
                   wall_epoch clock anchor, drained span ring, metrics
                   snapshot; a FleetServer returns one part per live
                   replica plus its own). prometheus: text exposition
                   (obs/export.py), pid/role-labeled — the HTTP-free
                   scrape endpoint.

Distributed tracing (docs/OBSERVABILITY.md): every request frame's key
field may carry a ``\\x1f``-suffixed W3C traceparent (obs/context.py).
``_handle_loop`` strips it FIRST — old-format frames have no suffix and
parse unchanged; a bare INFER gets a fresh sampled-or-not root, so the
replica's spans are one timeline either way. Replies never carry context.
  PREPARE_RELOAD : utf-8 json {"path", "epoch", "prefix", "version",
                   "token": [cid, epoch]} — phase one of the fleet-atomic
                   reload (serve/fleet.py): load + validate + stage, do NOT
                   flip. reply u8 status | (ok: u32 staged_version)
  COMMIT_RELOAD  : u64 cid | u64 epoch (the prepare's token). Flips the
                   staged set — a pure pointer swap, infallible short of
                   process death. Exactly-once: a retried COMMIT whose ack
                   was lost re-acks from the token LRU without re-flipping
                   (the kvstore (client_id, seq) dedup idiom). reply
                   u8 status | (ok: u32 version)
  ABORT_RELOAD   : u64 cid | u64 epoch — discard the staged set (idempotent)

Graceful degradation contract (tested in tests/test_serve.py):

- a deadline-expired or shed request gets an explicit status, never a
  hang;
- ``drain()`` flips readiness, finishes in-flight work, then (optionally)
  stops the listener — a rolling restart loses zero accepted requests;
- hot reload swaps parameters atomically (engine contract): every reply
  carries the parameter version it was computed with;
- chaos (``MXNET_CHAOS_RPC`` on the client, ``MXNET_CHAOS_KILL`` at the
  ``serve:pre_reply`` / ``serve:post_recv`` kill points here) exercises
  the retry/failover paths deterministically.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from typing import Optional

import numpy as np

from .. import obs, tsan
from ..obs import context as obs_context
from ..chaos import rpc as _chaos_rpc
from ..chaos.proc import kill_point
from ..kvstore.ps_server import (_pack_arrays, _recv_msg, _send_msg,
                                 _unpack_arrays)
from .batcher import DynamicBatcher
from .engine import (DeadlineExceeded, Draining, InferenceEngine,
                     RequestRejected, ServeError)

__all__ = ["ServeServer", "OP_INFER", "OP_HEALTH", "OP_READY", "OP_RELOAD",
           "OP_STATS", "OP_DRAIN", "OP_SHUTDOWN", "OP_PREPARE_RELOAD",
           "OP_COMMIT_RELOAD", "OP_ABORT_RELOAD", "OP_TELEMETRY", "OP_DUMP",
           "OP_INFER_STREAM", "OP_STREAM_TOKEN", "OP_STREAM_END",
           "OP_STREAM_ERROR", "SERVE_OP_NAMES", "STATUS_OK",
           "STATUS_REJECTED", "STATUS_DEADLINE", "STATUS_BAD_REQUEST",
           "STATUS_DRAINING", "STATUS_INTERNAL", "STATUS_NOT_READY"]

# serve opcode range: disjoint from the kvstore PS opcodes by
# construction — both planes declare their rows in mxnet_tpu/wire.py and
# the registry raises on any collision at import; the protocol linter
# cross-checks this module's dispatch against the same table
from ..wire import SERVE_WIRE

(OP_INFER, OP_HEALTH, OP_READY, OP_RELOAD, OP_STATS, OP_DRAIN,
 OP_SHUTDOWN, OP_PREPARE_RELOAD, OP_COMMIT_RELOAD,
 OP_ABORT_RELOAD, OP_TELEMETRY, OP_DUMP, OP_INFER_STREAM,
 OP_STREAM_TOKEN, OP_STREAM_END, OP_STREAM_ERROR) = SERVE_WIRE.codes(
    "infer", "health", "ready", "reload", "stats", "drain",
    "serve_shutdown", "prepare_reload", "commit_reload", "abort_reload",
    "telemetry", "dump", "infer_stream", "stream_token", "stream_end",
    "stream_error")

SERVE_OP_NAMES = dict(SERVE_WIRE.names())

# single source of truth for chaos rule names: MXNET_CHAOS_RPC rules match
# these ops the moment the serving plane is imported (the client imports
# this module, so on_send always sees registered names)
_chaos_rpc.OP_NAMES.update(SERVE_OP_NAMES)

(STATUS_OK, STATUS_REJECTED, STATUS_DEADLINE, STATUS_BAD_REQUEST,
 STATUS_DRAINING, STATUS_INTERNAL, STATUS_NOT_READY) = range(7)

_INFER_HDR = struct.Struct("<dB")  # deadline_ms (0 = none), priority
# INFER_STREAM request: deadline_ms (0 = none), priority,
# max_new_tokens (0 = server default), temperature — then packed arrays
# (one 1-D int32 prompt). Reply is a chunk sequence on the same
# connection: STREAM_TOKEN (u32 token | u32 index) per token, closed by
# STREAM_END (u8 status | u32 n_tokens) or STREAM_ERROR (_err_payload).
_STREAM_HDR = struct.Struct("<dBIf")
_TOKEN_FRAME = struct.Struct("<II")


def _err_payload(status: int, msg: str) -> bytes:
    return struct.pack("<B", status) + msg.encode("utf-8", "replace")


class ServeServer:
    """A concurrent inference endpoint over an :class:`InferenceEngine`.

    One accept loop + one thread per connection (the PSServer pattern);
    every connection handler funnels INFERs into the shared
    :class:`DynamicBatcher`, so concurrency turns into batch occupancy
    instead of lock contention on the device.
    """

    def __init__(self, engine: Optional[InferenceEngine] = None,
                 host: str = "127.0.0.1", port: int = 0, *,
                 batcher: Optional[DynamicBatcher] = None,
                 decode=None,
                 max_linger_ms: float = 2.0, max_queue: int = 256,
                 lanes: int = 2, default_timeout: float = 30.0):
        self._engine = engine
        if batcher is not None:
            self._batcher = batcher
        elif engine is not None:
            self._batcher = DynamicBatcher(
                engine, max_linger_ms=max_linger_ms, max_queue=max_queue,
                lanes=lanes)
        else:
            self._batcher = None
        # streaming generation source (OP_INFER_STREAM): a
        # decode.DecodeScheduler, or — on a FleetServer — absent, in which
        # case the Router batcher's own generate() relays replica streams
        self._decode = decode
        self._default_timeout = float(default_timeout)
        self._draining = False
        self._started = time.monotonic()
        self._shed_draining = 0  # server-level sheds (pre-batcher)
        # two-phase reload bookkeeping: staged token + committed-token LRU
        # (the kvstore exactly-once idiom — a retried COMMIT re-acks, never
        # re-flips); one lock serializes prepare/commit/abort
        self._reload_lock = tsan.lock("serve.server.reload")
        self._staged_token = None
        from collections import OrderedDict
        self._committed_tokens: "OrderedDict" = OrderedDict()
        # exactly-once telemetry drains: draining the span ring is
        # destructive, and the client's RPC layer retries lost replies —
        # a retried collection token re-serves the cached reply instead
        # of draining again (the kvstore (client_id, seq) idiom; without
        # this, every retry would silently lose the first drain's spans)
        self._telemetry_tokens: "OrderedDict" = OrderedDict()
        self._telemetry_lock = tsan.lock("serve.server.telemetry")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._conns = []

    # ------------------------------------------------------------------
    # lifecycle (PSServer idiom)
    # ------------------------------------------------------------------
    def serve_forever(self):
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.5)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._conns.append(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads = [th for th in self._threads if th.is_alive()]
            self._threads.append(t)

    def start(self):
        t = threading.Thread(target=self.serve_forever, daemon=True,
                             name="mxnet-tpu-serve-accept")
        t.start()
        return t

    def stop(self):
        self._stop.set()
        self._close_listener()
        # snapshot: handler threads concurrently .remove() from _conns, and
        # iterating the live list would skip (and leave open) neighbors of
        # a removed entry — a stopped server must look dead to EVERY client
        for c in list(self._conns):
            try:
                c.close()
            except OSError:
                pass
        # reap handler threads (they exit once their sockets are severed);
        # OP_SHUTDOWN stops from inside a handler — never join yourself
        me = threading.current_thread()
        deadline = time.monotonic() + 1.0  # ONE budget for the whole reap
        leaked = 0
        for t in [t for t in self._threads if t is not me]:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                leaked += 1
        if leaked:
            obs.inc("serve.handler_threads_leaked", leaked)
            obs.event("serve.handler_threads_leaked", count=leaked)
        if self._batcher is not None:
            self._batcher.close(timeout=5)
        if self._decode is not None:
            self._decode.close(timeout=5)

    def abort(self):
        """Crash-style stop: sever the listener and every live connection
        WITHOUT draining queued or in-flight work — to a client this is
        indistinguishable from the process being SIGKILLed, which is
        exactly what the fleet tests need from an in-process replica
        (serve/fleet.py LocalReplica.kill)."""
        self._stop.set()
        self._close_listener()
        for c in list(self._conns):
            try:
                c.close()
            except OSError:
                pass

    def _close_listener(self):
        # shutdown() before close(): close alone does NOT wake the accept
        # loop blocked inside its 0.5s poll, and while that thread holds
        # the fd the kernel keeps the listener ALIVE — new connects land
        # in a zombie backlog and only see RST when the poll tick fires,
        # so "this port is dead" took up to half a second to become true
        # (the fleet router's dead-replica attempts randomly lost their
        # 250ms hedge window to it). shutdown resets the backlog and
        # raises the blocked accept immediately.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def drain(self, stop: bool = False, timeout: float = 30.0) -> bool:
        """Graceful shutdown, phase one: flip readiness off, let queued and
        in-flight requests finish, refuse new ones. ``stop=True`` closes
        the listener afterwards (phase two)."""
        self._draining = True
        obs.event("serve.drain", stop=stop)
        ok = True
        if self._batcher is not None:
            ok = self._batcher.drain(timeout=timeout)
        if self._decode is not None:
            ok = self._decode.drain(timeout=timeout) and ok
        if stop:
            self.stop()
        return ok

    def reload(self, path: str, epoch: Optional[int] = None,
               prefix: str = "ckpt") -> int:
        """Hot-swap parameters from a newer on-disk artifact (same graph).
        In-flight requests keep the generation they started with.
        Serialized against the two-phase prepare/commit path so a legacy
        RELOAD can't interleave with a fleet flip."""
        if self._engine is None:
            raise ServeError("no engine loaded")
        from . import load_params

        arg, aux = load_params(path, epoch=epoch, prefix=prefix)
        with self._reload_lock:
            return self._engine.reload(arg, aux)

    def prepare_reload(self, path: str, epoch: Optional[int] = None,
                       prefix: str = "ckpt", *,
                       version: Optional[int] = None, token=None) -> int:
        """Phase one of the fleet-atomic reload: load, validate, and stage
        the new generation without flipping (all fallible work happens
        here; the commit left is a pure pointer swap)."""
        if self._engine is None:
            raise ServeError("no engine loaded")
        from . import load_params

        arg, aux = load_params(path, epoch=epoch, prefix=prefix)
        with self._reload_lock:
            staged = self._engine.prepare_reload(arg, aux, version=version)
            self._staged_token = tuple(token) if token is not None else None
        return staged

    def commit_reload(self, token=None) -> int:
        """Phase two: flip the staged generation. Exactly-once under
        retries — a token seen in the committed LRU re-acks with the
        version it flipped to, without flipping again."""
        if self._engine is None:
            raise ServeError("no engine loaded")
        tok = tuple(token) if token is not None else None
        with self._reload_lock:
            if tok is not None and tok in self._committed_tokens:
                return self._committed_tokens[tok]  # retried frame: re-ack
            if tok is not None and self._staged_token not in (None, tok):
                raise ServeError(
                    f"commit token {tok} does not match staged "
                    f"{self._staged_token}")
            version = self._engine.commit_reload()
            self._staged_token = None
            if tok is not None:
                self._committed_tokens[tok] = version
                while len(self._committed_tokens) > 4096:
                    self._committed_tokens.popitem(last=False)
        return version

    def abort_reload(self, token=None) -> None:
        """Discard a staged generation (idempotent rollback)."""
        if self._engine is None:
            return
        tok = tuple(token) if token is not None else None
        with self._reload_lock:
            if tok is None or self._staged_token in (None, tok):
                self._engine.abort_reload()
                self._staged_token = None

    def stats(self, include_metrics: bool = True) -> dict:
        out = {"uptime_seconds": round(time.monotonic() - self._started, 3),
               "draining": self._draining,
               "connections": len(self._conns),
               "sheds": {"draining": self._shed_draining},
               "pid": os.getpid()}
        if include_metrics:
            # ONE schema for every numeric runtime signal: the full
            # registry snapshot rides STATS, so serve_bench /
            # fleet_report / the SLO monitor read the same counters the
            # process records — no ad-hoc parallel bookkeeping. (The
            # telemetry path passes False: its part already carries the
            # snapshot, a second copy would just double the payload.)
            out["metrics"] = obs.metrics.snapshot()
        if self._engine is not None:
            out["engine"] = self._engine.stats()
        if self._batcher is not None:
            out["batcher"] = self._batcher.stats()
        if self._decode is not None:
            out["decode"] = self._decode.stats()
        return out

    def telemetry(self, drain: bool = True,
                  retained: Optional[list] = None) -> dict:
        """This process's telemetry contribution (``OP_TELEMETRY``): span
        ring (drained by default — repeated collections are increments),
        metrics snapshot, clock anchor. A FleetServer overrides this to
        pull and append every live replica's parts.

        ``retained`` is the tail-retention verdict list riding the
        request (obs/tail.py): pending traces named in it promote into
        the ring BEFORE the drain, so a downstream hop's held spans leave
        with the collection that carried their verdict; everything past
        the hold window expires in the same pass."""
        if retained:
            obs.tail.resolve(retained)
        # stats first: anything stats() mirrors into gauges must land in
        # the snapshot telemetry_part() takes
        st = self.stats(include_metrics=False)
        part = obs.telemetry_part(drain=drain, role="server")
        part["stats"] = st
        return {"parts": [part]}

    # ------------------------------------------------------------------
    # connection handling
    # ------------------------------------------------------------------
    def _handle(self, conn: socket.socket):
        try:
            self._handle_loop(conn)
        finally:
            try:
                conn.close()
            except OSError:
                pass
            try:
                self._conns.remove(conn)
            except ValueError:
                pass

    def _handle_loop(self, conn: socket.socket):
        try:
            while True:
                opcode, key, payload = _recv_msg(conn)
                kill_point("serve:post_recv")  # chaos: die with work read
                # strip wire trace context BEFORE anything looks at the
                # key (old-format frames: no separator, no context); a
                # context-less INFER becomes a new sampled-or-not root, so
                # replica spans trace either way ("absent = new root")
                key, wctx = obs_context.extract_key(key)
                rec = obs.enabled()
                root_here = False
                if wctx is None and rec and opcode in (OP_INFER,
                                                       OP_INFER_STREAM):
                    wctx = obs_context.new_root()
                    root_here = True
                t0 = time.monotonic() if rec else 0.0
                opname = SERVE_OP_NAMES.get(opcode, str(opcode))
                try:
                    with obs_context.use(wctx), \
                            obs.trace.span("serve.rpc", op=opname):
                        alive = self._handle_one(conn, opcode, key, payload)
                finally:
                    if rec:
                        obs.observe(f"serve.rpc.{opname}_seconds",
                                    time.monotonic() - t0)
                    # tail retention: a server-side root's verdict
                    # happens HERE — latency + the outcome _do_infer
                    # noted (shed/deadline/error rode the reply to
                    # the client; the same verdict decides whether
                    # the trace survives). When the CLIENT owns the
                    # root, the reply status byte carries the outcome —
                    # but hedge/breaker flags noted by the router on
                    # THIS thread never reach the client, so
                    # finish_remote applies the policy to the flags
                    # locally (retaining the fleet-side spans) and, like
                    # finish_root, always clears this thread's notes so
                    # they cannot leak into the next request on this
                    # connection — even ones taken while telemetry was
                    # off.
                    if root_here:
                        obs.tail.finish_root(wctx, time.monotonic() - t0)
                    else:
                        obs.tail.finish_remote(wctx,
                                               time.monotonic() - t0)
                if not alive:
                    return
        except (ConnectionError, OSError):
            return

    def _reply(self, conn, opcode: int, payload):
        kill_point("serve:pre_reply")  # chaos: server dies before the ack
        _send_msg(conn, opcode, "", payload)

    def _handle_one(self, conn, opcode: int, key: str, payload) -> bool:
        if opcode == OP_INFER:
            self._reply(conn, OP_INFER, self._do_infer(payload))
        elif opcode == OP_INFER_STREAM:
            return self._do_infer_stream(conn, payload)
        elif opcode == OP_HEALTH:
            # liveness only: answering at all is the signal
            self._reply(conn, OP_HEALTH, struct.pack("<B", STATUS_OK))
        elif opcode == OP_READY:
            # the fleet front (serve/fleet.py FleetServer) has no engine:
            # the Router IS the batcher, and its ready() gates on live
            # replicas instead of a loaded model
            # a decode-only replica (no batch engine) is ready while its
            # scheduler accepts work
            src = self._batcher if self._batcher is not None \
                else self._decode
            if src is None or (self._engine is None
                               and not hasattr(src, "ready")):
                status = STATUS_NOT_READY
            elif self._draining:
                status = STATUS_DRAINING
            elif self._engine is None and not src.ready():
                status = STATUS_NOT_READY
            else:
                status = STATUS_OK
            # the serving param version rides along (u32 appended — old
            # clients read byte 0 only), so a fleet router can gate a
            # replica on version coherence from one probe
            if self._engine is not None:
                version = self._engine.version
            else:
                version = int(getattr(src, "version", 0) or 0)
            self._reply(conn, OP_READY,
                        struct.pack("<BI", status, version))
        elif opcode == OP_RELOAD:
            try:
                spec = json.loads(bytes(payload).decode("utf-8"))
                version = self.reload(spec["path"],
                                      epoch=spec.get("epoch"),
                                      prefix=spec.get("prefix", "ckpt"))
                self._reply(conn, OP_RELOAD,
                            struct.pack("<BI", STATUS_OK, version))
            except Exception as e:  # noqa: BLE001 — wire-reported
                obs.inc("serve.reload_errors")
                self._reply(conn, OP_RELOAD, _err_payload(
                    STATUS_INTERNAL, f"{type(e).__name__}: {e}"))
        elif opcode == OP_PREPARE_RELOAD:
            try:
                spec = json.loads(bytes(payload).decode("utf-8"))
                staged = self.prepare_reload(
                    spec["path"], epoch=spec.get("epoch"),
                    prefix=spec.get("prefix", "ckpt"),
                    version=spec.get("version"), token=spec.get("token"))
                self._reply(conn, OP_PREPARE_RELOAD,
                            struct.pack("<BI", STATUS_OK, staged))
            except Exception as e:  # noqa: BLE001 — wire-reported
                obs.inc("serve.reload_errors")
                self._reply(conn, OP_PREPARE_RELOAD, _err_payload(
                    STATUS_INTERNAL, f"{type(e).__name__}: {e}"))
        elif opcode == OP_COMMIT_RELOAD:
            try:
                token = struct.unpack_from("<QQ", payload, 0) \
                    if len(payload) >= 16 else None
                kill_point("serve:pre_commit")  # chaos: die mid-phase-2
                version = self.commit_reload(token)
                self._reply(conn, OP_COMMIT_RELOAD,
                            struct.pack("<BI", STATUS_OK, version))
            except Exception as e:  # noqa: BLE001 — wire-reported
                obs.inc("serve.reload_errors")
                self._reply(conn, OP_COMMIT_RELOAD, _err_payload(
                    STATUS_INTERNAL, f"{type(e).__name__}: {e}"))
        elif opcode == OP_ABORT_RELOAD:
            token = struct.unpack_from("<QQ", payload, 0) \
                if len(payload) >= 16 else None
            self.abort_reload(token)
            self._reply(conn, OP_ABORT_RELOAD, struct.pack("<B", STATUS_OK))
        elif opcode == OP_STATS:
            # optional json payload {"metrics": false} skips the registry
            # snapshot — the fleet supervisor polls replica queue-depth/
            # occupancy every probe interval and must not pay a full
            # snapshot per poll (empty payload = legacy full stats)
            include = True
            if len(payload):
                try:
                    spec = json.loads(bytes(payload).decode("utf-8"))
                    include = bool(spec.get("metrics", True))
                except ValueError:
                    pass
            blob = json.dumps(self.stats(include_metrics=include),
                              default=str).encode("utf-8")
            self._reply(conn, OP_STATS, struct.pack("<B", STATUS_OK) + blob)
        elif opcode == OP_TELEMETRY:
            try:
                spec = json.loads(bytes(payload).decode("utf-8")) \
                    if len(payload) else {}
                token = spec.get("token")
                blob = None
                if token is not None:
                    with self._telemetry_lock:
                        blob = self._telemetry_tokens.get(token)
                if blob is None:
                    tel = self.telemetry(drain=bool(spec.get("drain", True)),
                                         retained=spec.get("retained"))
                    if spec.get("format") == "prometheus":
                        from ..obs.export import parts_to_prometheus

                        blob = parts_to_prometheus(
                            tel["parts"],
                            openmetrics=bool(spec.get("openmetrics", True)),
                        ).encode("utf-8")
                    else:
                        blob = json.dumps(tel, default=float).encode("utf-8")
                    if token is not None:
                        with self._telemetry_lock:
                            self._telemetry_tokens[token] = blob
                            while len(self._telemetry_tokens) > 4:
                                self._telemetry_tokens.popitem(last=False)
                self._reply(conn, OP_TELEMETRY,
                            struct.pack("<B", STATUS_OK) + blob)
            except Exception as e:  # noqa: BLE001 — wire-reported
                obs.inc("serve.telemetry_errors")
                self._reply(conn, OP_TELEMETRY, _err_payload(
                    STATUS_INTERNAL, f"{type(e).__name__}: {e}"))
        elif opcode == OP_DUMP:
            # flight-recorder snapshot (obs/blackbox.py): the bundle is
            # built from the always-on ring — nothing drains, so retries
            # are harmless and no dedup token is needed
            try:
                spec = json.loads(bytes(payload).decode("utf-8")) \
                    if len(payload) else {}
                from ..obs import blackbox

                reason = str(spec.get("reason", "wire"))
                doc = blackbox.bundle(reason=reason)
                if spec.get("write") and blackbox.enabled():
                    # persist the SAME document the reply carries (a
                    # second bundle_dict here would snapshot a later,
                    # different ring)
                    doc["path"] = blackbox.dump(reason=reason, doc=doc)
                blob = json.dumps(doc, default=str).encode("utf-8")
                self._reply(conn, OP_DUMP,
                            struct.pack("<B", STATUS_OK) + blob)
            except Exception as e:  # noqa: BLE001 — wire-reported
                obs.inc("serve.dump_errors")
                self._reply(conn, OP_DUMP, _err_payload(
                    STATUS_INTERNAL, f"{type(e).__name__}: {e}"))
        elif opcode == OP_DRAIN:
            stop = bool(payload and payload[0])
            drained = self.drain(stop=False)
            self._reply(conn, OP_DRAIN, struct.pack(
                "<B", STATUS_OK if drained else STATUS_INTERNAL))
            if stop:
                self.stop()
                return False
        elif opcode == OP_SHUTDOWN:
            self._reply(conn, OP_SHUTDOWN, struct.pack("<B", STATUS_OK))
            self.stop()
            return False
        else:
            self._reply(conn, opcode,
                        _err_payload(STATUS_BAD_REQUEST,
                                     f"unknown opcode {opcode}"))
        return True

    def _do_infer_stream(self, conn, payload) -> bool:
        """Relay one generation as a chunked reply sequence. The token
        source is uniform: ``DecodeScheduler.generate`` on a replica,
        ``Router.generate`` on a fleet front — both yield ints and raise
        the typed serve errors, possibly mid-stream. Returns False (drop
        the connection) only when the CLIENT died mid-stream — the
        generator's close() cancels the generation so its KV pages are
        reclaimed at the next step boundary."""
        src = self._decode if self._decode is not None else self._batcher
        gen_fn = getattr(src, "generate", None)
        if gen_fn is None:
            self._reply(conn, OP_STREAM_ERROR, _err_payload(
                STATUS_NOT_READY, "no decode path loaded"))
            return True
        if self._draining:
            self._shed_draining += 1
            obs.inc("serve.shed_draining")
            obs.tail.note("shed")
            self._reply(conn, OP_STREAM_ERROR, _err_payload(
                STATUS_DRAINING, "endpoint draining"))
            return True
        try:
            deadline_ms, priority, max_new, temp = \
                _STREAM_HDR.unpack_from(payload, 0)
            arrays, _ = _unpack_arrays(payload[_STREAM_HDR.size:])
            tokens = np.asarray(arrays[0]).reshape(-1)
        except (struct.error, IndexError, KeyError, ValueError) as e:
            self._reply(conn, OP_STREAM_ERROR, _err_payload(
                STATUS_BAD_REQUEST, f"malformed INFER_STREAM frame: {e}"))
            return True
        gen = gen_fn(tokens,
                     max_new_tokens=int(max_new) or None,
                     deadline_ms=deadline_ms or None,
                     priority=int(priority),
                     temperature=float(temp))
        n = 0
        try:
            try:
                for tok in gen:
                    n += 1
                    # chaos: die with tokens streamed but the generation
                    # still resident — the page-reclaim proof's kill point
                    kill_point("serve:mid_stream")
                    _send_msg(conn, OP_STREAM_TOKEN, "",
                              _TOKEN_FRAME.pack(int(tok) & 0xFFFFFFFF, n))
                _send_msg(conn, OP_STREAM_END, "",
                          struct.pack("<BI", STATUS_OK, n))
            except RequestRejected as e:
                obs.tail.note("shed")
                _send_msg(conn, OP_STREAM_ERROR, "",
                          _err_payload(STATUS_REJECTED, str(e)))
            except DeadlineExceeded as e:
                obs.tail.note("deadline")
                _send_msg(conn, OP_STREAM_ERROR, "",
                          _err_payload(STATUS_DEADLINE, str(e)))
            except Draining as e:
                obs.tail.note("shed")
                _send_msg(conn, OP_STREAM_ERROR, "",
                          _err_payload(STATUS_DRAINING, str(e)))
            except ServeError as e:
                obs.tail.note("error")
                _send_msg(conn, OP_STREAM_ERROR, "",
                          _err_payload(STATUS_INTERNAL, str(e)))
        except (ConnectionError, OSError):
            # the CLIENT vanished mid-stream: nothing to reply to — just
            # make sure the generation leaves the batch
            obs.inc("serve.stream_client_lost")
            return False
        finally:
            gen.close()
            if n:
                obs.inc("serve.stream_tokens", n)
        return True

    def _do_infer(self, payload):
        if self._batcher is None:
            return _err_payload(STATUS_NOT_READY, "no model loaded")
        if self._draining:
            self._shed_draining += 1
            obs.inc("serve.shed_draining")
            return _err_payload(STATUS_DRAINING, "endpoint draining")
        try:
            deadline_ms, priority = _INFER_HDR.unpack_from(payload, 0)
            arrays, _ = _unpack_arrays(payload[_INFER_HDR.size:])
        except (struct.error, IndexError, KeyError, ValueError) as e:
            return _err_payload(STATUS_BAD_REQUEST,
                                f"malformed INFER frame: {e}")
        try:
            fut = self._batcher.submit(arrays,
                                       deadline_ms=deadline_ms or None,
                                       priority=int(priority))
            wait = (deadline_ms / 1e3) if deadline_ms \
                else self._default_timeout
            outs, version = fut.result(timeout=wait + 1.0)
        except RequestRejected as e:
            obs.tail.note("shed")
            return _err_payload(STATUS_REJECTED, str(e))
        except DeadlineExceeded as e:
            obs.tail.note("deadline")
            # DEADLINE means "your deadline passed, the work was shed"; a
            # deadline-LESS request timing out the server-side wait is an
            # internal condition (the work may still execute), not an SLO
            # miss the client never asked for
            if not deadline_ms:
                return _err_payload(
                    STATUS_INTERNAL,
                    f"server wait exceeded {self._default_timeout}s: {e}")
            return _err_payload(STATUS_DEADLINE, str(e))
        except Draining as e:
            obs.tail.note("shed")
            return _err_payload(STATUS_DRAINING, str(e))
        except ServeError as e:
            obs.tail.note("error")
            return _err_payload(STATUS_INTERNAL, str(e))
        with obs.trace.span("serve.serialize", outputs=len(outs)):
            # status header and packed arrays travel as separate parts:
            # _send_msg scatter-gathers them, so the reply is never
            # re-copied into one contiguous buffer (data-plane lint)
            reply = [struct.pack("<BI", STATUS_OK, version),
                     _pack_arrays([np.ascontiguousarray(o) for o in outs])]
        # chaos: die with the answer computed but unsent — the INFER-specific
        # twin of serve:pre_reply (which also fires on probe replies, so a
        # fleet test could never target "kill mid-INFER-reply" with it)
        kill_point("serve:infer_pre_reply")
        return reply


def main():  # pragma: no cover - CLI shim
    import argparse

    import jax

    # serving may legitimately target the accelerator; MXNET_SERVE_PLATFORM
    # pins it (the PS server's MXNET_PS_PLATFORM idiom)
    plat = os.environ.get("MXNET_SERVE_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    ap = argparse.ArgumentParser(description="mxnet_tpu serving endpoint")
    ap.add_argument("model", help="artifact path (Module checkpoint prefix, "
                    "gluon export path, or checkpoint directory)")
    ap.add_argument("--epoch", type=int, default=None)
    ap.add_argument("--port", type=int, default=9191)
    ap.add_argument("--max-batch-size", type=int, default=32)
    ap.add_argument("--max-linger-ms", type=float, default=2.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--warmup-shape", type=str, default=None,
                    help="comma-separated per-row feature shape to "
                         "pre-compile every bucket for, e.g. 3,224,224")
    ap.add_argument("--progcache-dir", type=str, default=None,
                    help="persistent AOT program-cache directory "
                         "(mxnet_tpu/progcache.py); overrides "
                         "MXNET_PROGCACHE_DIR — warmup deserializes "
                         "previously compiled bucket programs instead of "
                         "recompiling them")
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel shard the engine over the first "
                         "N local devices (mesh axis 'tp'; sharding specs "
                         "come from the model's rule table when serving "
                         "in-process — the CLI path replicates params)")
    args = ap.parse_args()

    from . import load

    if args.progcache_dir:
        from .. import progcache

        progcache.configure(args.progcache_dir)

    engine_kw = {}
    if args.tp:
        from ..parallel import make_mesh

        engine_kw["mesh"] = make_mesh({"tp": args.tp})
    engine = load(args.model, epoch=args.epoch,
                  max_batch_size=args.max_batch_size, **engine_kw)
    if args.warmup_shape:
        feat = tuple(int(d) for d in args.warmup_shape.split(",") if d)
        engine.warmup(feat)
    srv = ServeServer(engine, port=args.port,
                      max_linger_ms=args.max_linger_ms,
                      max_queue=args.max_queue)
    print(f"ServeServer listening on :{srv.port}", flush=True)
    srv.serve_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
