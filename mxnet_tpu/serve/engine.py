"""Compiled inference executor — the device half of ``mxnet_tpu.serve``.

Reference: the MXNet Model Server ran inference through threaded CachedOp
executors (``python/mxnet/gluon/block.py`` CachedOp + mms's batching handler
— TBV, SURVEY.md §1). TPU redesign: one **donation-free ``jax.jit``
program per bucketed input shape**, parameters device-resident and passed
as *traced arguments* — so a hot parameter reload swaps arrays without a
single retrace, and the compiled-program count is bounded by construction:

- **Shape bucketing**: a request batch of ``n`` rows is padded up to the
  smallest configured bucket ≥ n (pad rows are zeros; outputs are sliced
  back to ``n`` — rows are independent in eval mode, BatchNorm uses its
  moving stats, so the valid rows are bitwise what an unpadded run with the
  same program would produce). Ragged traffic therefore compiles at most
  ``len(buckets) × distinct feature signatures`` programs, ever.
- **Cache-key accounting** mirrors ``optimizer/fused.py``: every program is
  keyed explicitly (input avals), ``compile_log`` records one entry per
  compilation, and the TraceLinter's ``serve-retrace-churn`` rule
  (``analysis/trace.py``) turns that log into a *proof* that the bound
  holds — a key compiled twice, or more programs than buckets admit, is a
  linted defect, not a hunch.
- **Hot reload**: ``reload()`` validates the new parameter set against the
  current avals (a shape/dtype drift would silently double the program
  count) and swaps the whole device-resident set atomically under a lock.
  In-flight executions hold the snapshot they started with — a request sees
  *old or new* parameters, never a mix.

Telemetry (docs/OBSERVABILITY.md): ``serve.execute`` spans per batch with
bucket/compile attribution, ``serve.compile_seconds`` vs
``serve.execute_seconds`` histograms, ``dispatch.*`` counters feeding
``profiler.count_dispatches()`` so tests can assert the program bound.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import copytrack, obs
from ..base import MXNetError

__all__ = ["InferenceEngine", "ServeError", "RequestRejected",
           "DeadlineExceeded", "Draining", "default_buckets"]


class ServeError(MXNetError):
    """Base error of the serving subsystem."""


class RequestRejected(ServeError):
    """Load shed: the request was refused before execution (HTTP-429
    analog) — queue over watermark, or the server is not accepting."""


class DeadlineExceeded(ServeError):
    """The request's deadline expired before (or while) it could run; it
    was shed, not executed."""


class Draining(ServeError):
    """The endpoint is draining for shutdown and refuses new work."""


def _to_device(v, sharding=None):
    """NDArray/numpy → device array (load-time AND reload-time parameter
    placement share this one helper so they can never diverge). With a
    ``sharding`` the value is committed to the engine's mesh slice —
    tensor-parallel params land shard-resident per device, never gathered
    on one."""
    import jax

    from ..ndarray import NDArray

    if isinstance(v, NDArray) and v._data is not None:
        if sharding is None:
            return v._data
        return jax.device_put(v._data, sharding)
    arr = np.ascontiguousarray(np.asarray(v))
    return jax.device_put(arr) if sharding is None \
        else jax.device_put(arr, sharding)


def _shape_of(v) -> tuple:
    s = getattr(v, "shape", None)
    if s is None:
        s = np.asarray(v).shape
    return tuple(int(d) for d in s)


def _sig_of(arrays) -> tuple:
    """THE program signature of a (padded) batch — ``infer``'s accounting
    key and ``warmup``'s already-compiled filter both derive through this
    one function, so the two can never silently drift apart (a mismatch
    would make every warmup re-run full inferences instead of returning
    0 on the second call)."""
    return tuple((tuple(a.shape), str(a.dtype)) for a in arrays)


def default_buckets(max_batch_size: int) -> List[int]:
    """Power-of-two batch buckets up to ``max_batch_size`` (which is always
    included, power of two or not): 32 → [1, 2, 4, 8, 16, 32]."""
    max_batch_size = int(max_batch_size)
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1")
    out = []
    b = 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return out


class _ParamSet:
    """One immutable generation of device-resident parameters. Executions
    snapshot the reference once, so a concurrent reload can never hand a
    program half-old half-new arrays."""

    __slots__ = ("version", "arg_vals", "aux_vals")

    def __init__(self, version: int, arg_vals: tuple, aux_vals: tuple):
        self.version = version
        self.arg_vals = arg_vals
        self.aux_vals = aux_vals


class InferenceEngine:
    """Serve a trained symbolic graph as compiled, bucketed inference.

    Parameters
    ----------
    symbol : Symbol
        The inference graph (a trained Module's symbol, a gluon export's
        embedded trace, or a ``quantize_model`` int8 rewrite).
    arg_params / aux_params : dict[str, array]
        Trained parameters (NDArray or numpy). Graph arguments that are
        neither data nor parameters (e.g. ``softmax_label`` on a training
        head) are bound to zeros per bucket — they don't affect eval-mode
        outputs.
    data_names : sequence of str
        Which graph arguments are request inputs, in request order.
    max_batch_size : int
        Largest bucket; requests bigger than this are chunked.
    buckets : sequence of int, optional
        Explicit batch buckets (sorted, deduped). Default:
        ``default_buckets(max_batch_size)``.
    lint : "off" | "warn" | "error"
        Pre-flight ``Symbol.lint`` at load time; "error" refuses to serve a
        graph with error-severity findings (a bad graph should fail at
        deploy, not on the first customer request).
    progcache_dir : str, optional
        Directory of a persistent AOT program cache for THIS engine
        (``mxnet_tpu/progcache.py``) — e.g. an artifact's shipped
        ``programs/`` payload. Default: the process-global cache
        (``MXNET_PROGCACHE_DIR`` / ``MXNET_PROGCACHE=1``), or no
        persistence. With a cache, a bucket whose program was compiled by
        ANY earlier process (same graph, avals, platform, code) warms by
        deserializing the stored executable — the ``compile_log`` entry
        records ``cache_hit: True`` and zero fresh XLA compilation
        happens; the loaded program is the same machine code, so the
        bitwise serve-vs-predict contract is untouched.
    mesh : jax.sharding.Mesh, optional
        Shard the engine over a device mesh (typically one replica group's
        slice — ``parallel.mesh_slices``): parameters are committed
        shard-resident per device by the ``rules`` table, every bucket's
        program compiles over the mesh (XLA inserts the tensor-parallel
        collectives), and batches shard over a ``dp`` axis when the mesh
        has one (``data_spec``). The compiled-program bound, the
        compile_log accounting, atomic hot reload, and the
        bitwise-vs-``predict`` contract *per shard config* are all
        unchanged — the mesh only changes where arrays live.
    rules : parallel.ShardingRules, optional
        Parameter-name → PartitionSpec table (default: everything
        replicated). Specs naming axes the mesh lacks, or not dividing a
        dim, prune to replicated — one table serves every mesh shape.
    data_spec : PartitionSpec, optional
        Spec for request batches (default ``P("dp")``, pruned per bucket
        shape; a pure-``tp`` slice replicates the batch).
    """

    def __init__(self, symbol, arg_params, aux_params=None, *,
                 data_names: Sequence[str] = ("data",),
                 max_batch_size: int = 32,
                 buckets: Optional[Sequence[int]] = None,
                 lint: str = "warn",
                 pad_value: float = 0.0,
                 mesh=None, rules=None, data_spec=None,
                 progcache_dir: Optional[str] = None):
        import jax

        from ..executor import _build_graph_fn

        self.symbol = symbol
        self._data_names = list(data_names)
        if buckets is None:
            buckets = default_buckets(max_batch_size)
        self.buckets: List[int] = sorted(set(int(b) for b in buckets))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"invalid buckets {buckets!r}")
        self.max_batch_size = self.buckets[-1]
        self._pad_value = float(pad_value)

        arg_params = dict(arg_params or {})
        aux_params = dict(aux_params or {})
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        missing_data = [n for n in self._data_names if n not in arg_names]
        if missing_data:
            raise ServeError(
                f"data_names {missing_data} are not arguments of the graph "
                f"(arguments: {arg_names})")
        self._param_names = [n for n in arg_names
                             if n not in self._data_names and n in arg_params]
        # training-head leftovers (labels): zero-filled per bucket — they
        # must not force the client to ship dummy tensors over the wire.
        # ONLY label-like names qualify: zero-filling an arbitrary missing
        # weight (a name-mismatched or truncated checkpoint) would serve
        # garbage silently, the exact bug class the aux check below rejects
        self._free_names = [n for n in arg_names
                            if n not in self._data_names
                            and n not in arg_params]
        not_label = [n for n in self._free_names if "label" not in n]
        if not_label:
            raise ServeError(
                f"graph arguments {not_label} are neither inputs nor in "
                "arg_params — a zero-filled weight would serve wrong "
                "predictions silently; fix the checkpoint/param_map, or "
                "list them in data_names if they are real inputs")
        self._aux_names = list(aux_names)
        missing_aux = [n for n in aux_names if n not in aux_params]
        if missing_aux:
            raise ServeError(
                f"aux states {missing_aux} missing from aux_params — an "
                "untrained BatchNorm served with default stats is a silent "
                "accuracy bug; export the full checkpoint")

        # -- pre-flight static analysis (model-load, not first-request) ----
        self.lint_report = None
        if lint not in ("off", "warn", "error"):
            raise ValueError(f"lint must be 'off'|'warn'|'error', got {lint!r}")
        if lint != "off":
            self.lint_report = symbol.lint()
            if lint == "error":
                self.lint_report.raise_if_errors()
            elif self.lint_report:
                import warnings

                warnings.warn("serve model-load lint: "
                              + self.lint_report.format(), stacklevel=2)

        # -- mesh sharding (tensor-parallel serving) ----------------------
        # the mesh-dependent placement is all resolved HERE, once: a dict
        # name → NamedSharding for params (rules table, pruned per shape),
        # replicated for aux/free/rng, batch spec per bucket at infer time.
        # reload goes through the same dict, so a new generation can never
        # land with a different layout than the programs compiled for.
        self.mesh = mesh
        self._param_sh: Dict[str, object] = {}
        self._replicated_sh = None
        self._data_spec = data_spec
        self._data_sh_cache: Dict[tuple, object] = {}
        if mesh is not None:
            from ..parallel.sharding import (ShardingRules, replicated)

            rules = rules or ShardingRules()
            self._rules = rules
            self._replicated_sh = replicated(mesh)
            for n in self._param_names:
                self._param_sh[n] = rules.sharding_for(
                    n, mesh, _shape_of(arg_params[n]))

        # -- device-resident parameters -----------------------------------
        self._lock = threading.Lock()
        self._staged: Optional[_ParamSet] = None  # prepared, not yet serving
        self._params = _ParamSet(
            0,
            tuple(_to_device(arg_params[n], self._param_sh.get(n))
                  for n in self._param_names),
            tuple(_to_device(aux_params[n], self._replicated_sh)
                  for n in self._aux_names))
        self._param_avals = tuple(
            (tuple(v.shape), str(v.dtype)) for v in self._params.arg_vals)
        self._aux_avals = tuple(
            (tuple(v.shape), str(v.dtype)) for v in self._params.aux_vals)

        # -- the compiled program (one jax.jit entry per input signature) --
        # The traced function mirrors Executor._get_fn's ``wrapped``
        # EXACTLY (same arg_vals/aux_vals list layout, same (outs, new_aux)
        # return): identical jaxpr → identical HLO → the engine's bucket-B
        # program is bit-for-bit the executable ``Module.predict`` runs at
        # batch B. That is what makes the flagship bitwise-equality
        # contract (serve output == direct predict output) hold by
        # construction instead of by luck — XLA does not promise identical
        # ulps across *different* programs, only across runs of the same
        # one.
        arg_order = {n: i for i, n in enumerate(arg_names)}
        _, _, fn, _ = _build_graph_fn(symbol, train=False)
        self._param_slots = [arg_order[n] for n in self._param_names]
        self._free_slots = [arg_order[n] for n in self._free_names]
        self._data_slots = [arg_order[n] for n in self._data_names]
        self._n_args = len(arg_names)

        def wrapped(rng_key, arg_vals, aux_vals):
            import jax.random as jr

            from .. import random as _random

            if hasattr(jr, "wrap_key_data") and \
                    getattr(rng_key, "dtype", None) == jax.numpy.uint32:
                rng_key = jr.wrap_key_data(rng_key)
            with _random.trace_key_scope(rng_key):
                return fn(arg_vals, aux_vals)

        self._jitted = jax.jit(wrapped)
        import jax.random as jr

        key = jr.PRNGKey(0)  # eval mode draws nothing; fixed = deterministic
        self._rng_data = jr.key_data(key) if hasattr(jr, "key_data") else key
        if self._replicated_sh is not None:
            # every program input must be COMMITTED to the engine's mesh
            # slice: an uncommitted array defaults to device 0, which may
            # not even be in this slice
            self._rng_data = jax.device_put(self._rng_data,
                                            self._replicated_sh)

        # explicit program accounting (the fused-update cache-key idiom):
        # one entry per distinct input signature ever compiled. The
        # TraceLinter serve-retrace-churn rule audits this log.
        self._programs: Dict[tuple, int] = {}   # sig -> execution count
        # counters mutate from concurrent warmup threads (+= is not atomic
        # once XLA releases the GIL mid-infer); compile_log appends are
        self._stat_lock = threading.Lock()
        self.compile_log: List[dict] = []
        self._free_cache: Dict[tuple, tuple] = {}
        self.exec_count = 0
        # device-plane accounting (obs/device.py): when capture is active a
        # signature's program is AOT-compiled ONCE — the same executable is
        # analyzed (flops/bytes/HBM into compile_log) and then executed
        self._aot: Dict[tuple, object] = {}      # sig -> compiled executable
        self._sig_cost: Dict[tuple, dict] = {}   # sig -> cost record

        # persistent AOT program cache (mxnet_tpu/progcache.py): explicit
        # dir (an artifact's programs/ payload) beats the process-global
        # env-armed cache. Key statics = everything that determines the
        # traced program short of the batch signature — the graph itself,
        # argument layout, pad value, and mesh placement; progcache adds
        # the platform/topology/version fingerprint per entry.
        from .. import progcache as _progcache

        self._progcache = (_progcache.ProgramCache(progcache_dir)
                           if progcache_dir else _progcache.cache())
        self._sig_key: Dict[tuple, object] = {}   # sig -> ProgramKey
        self._key_statics = None
        self.cache_hits = 0
        if self._progcache is not None:
            self._key_statics = self._compute_key_statics()

    # ------------------------------------------------------------------
    # properties / stats
    # ------------------------------------------------------------------
    @property
    def version(self) -> int:
        """Monotonic parameter generation (bumped by :meth:`reload`)."""
        return self._params.version

    @property
    def num_programs(self) -> int:
        """Distinct compiled programs so far (the bounded quantity)."""
        return len(self._programs)

    @property
    def data_names(self) -> List[str]:
        return list(self._data_names)

    def _mesh_ctx(self):
        """Trace-time scope: model code (ring attention etc.) discovers the
        engine's mesh slice via ``parallel.current_mesh()``. No-op when the
        engine is unsharded."""
        if self.mesh is None:
            return contextlib.nullcontext()
        from ..parallel.mesh import mesh_scope

        return mesh_scope(self.mesh)

    def _compute_key_statics(self):
        """The serve-program statics fed to ``progcache.program_key``:
        graph json (hashed), argument layout, avals, pad value, and — for
        a sharded engine — the mesh axes + concrete device ids (a program
        compiled for one slice must never load onto another)."""
        mesh_desc = None
        if self.mesh is not None:
            mesh_desc = (tuple(self.mesh.axis_names),
                         tuple(self.mesh.devices.shape),
                         tuple(int(d.id) for d in self.mesh.devices.flat),
                         repr(self._data_spec))
        return (self.symbol.tojson().encode("utf-8"),
                tuple(self._data_names), tuple(self._param_names),
                tuple(self._aux_names), tuple(self._free_names),
                self._param_avals, self._aux_avals, self._pad_value,
                mesh_desc)

    def _program_key(self, sig, bucket: int):
        """One :class:`~mxnet_tpu.progcache.ProgramKey` per signature —
        the SAME derivation the device-plane cost registry and the
        persistent cache file names use (progcache.program_key)."""
        pk = self._sig_key.get(sig)
        if pk is None:
            from .. import progcache as _progcache

            pk = _progcache.program_key("serve", f"bucket{bucket}",
                                        (self._key_statics, sig))
            self._sig_key[sig] = pk
        return pk

    def stats(self) -> dict:
        staged = self._staged
        out = {
            "version": self.version,
            "staged_version": staged.version if staged is not None else None,
            "buckets": list(self.buckets),
            "num_programs": self.num_programs,
            "executions": self.exec_count,
            "programs": {repr(k): v for k, v in self._programs.items()},
            "compiles": len(self.compile_log),
            "cache_hits": self.cache_hits,
        }
        if self._progcache is not None:
            out["progcache"] = dict(self._progcache.stats,
                                    dir=self._progcache.root)
        if self.mesh is not None:
            from ..parallel.mesh import mesh_axes

            out["mesh"] = mesh_axes(self.mesh)
            out["mesh_devices"] = int(self.mesh.devices.size)
            out["sharded_params"] = sum(
                1 for sh in self._param_sh.values()
                if getattr(sh, "spec", None) and any(
                    ax is not None for ax in sh.spec))
        return out

    # ------------------------------------------------------------------
    # bucketing
    # ------------------------------------------------------------------
    def bucket_for(self, n: int) -> Optional[int]:
        """Smallest bucket ≥ n, or None when n exceeds the largest (the
        caller chunks)."""
        for b in self.buckets:
            if b >= n:
                return b
        return None

    def _free_vals(self, batch: int, data_shapes) -> tuple:
        """Zero tensors for non-data, non-param graph arguments (labels),
        shaped by shape inference at this bucket. Cached per signature."""
        key = (batch, tuple(data_shapes))
        vals = self._free_cache.get(key)
        if vals is None:
            import jax.numpy as jnp

            if self._free_names:
                from ..symbol.symbol import infer_shapes

                shapes = dict(zip(self._data_names, data_shapes))
                inferred, _ = infer_shapes(self.symbol, shapes)
                missing = [n for n in self._free_names if n not in inferred]
                if missing:
                    raise ServeError(
                        f"cannot infer shapes for unbound arguments "
                        f"{missing}; pass them as arg_params or data_names")
                vals = tuple(jnp.zeros(inferred[n], jnp.float32)
                             for n in self._free_names)
                if self._replicated_sh is not None:
                    import jax

                    vals = tuple(jax.device_put(v, self._replicated_sh)
                                 for v in vals)
            else:
                vals = ()
            self._free_cache[key] = vals
        return vals

    def _data_sharding(self, shape):
        """Batch placement for one (padded) request array: the ``data_spec``
        pruned against this mesh and shape — sharded over ``dp`` when the
        bucket divides, replicated otherwise (a pure-``tp`` replica group
        always replicates the batch; the weights are what is sharded).
        Cached per shape (the _free_cache idiom): shapes are bounded by
        the bucket list, and rebuilding the pruned NamedSharding per
        request would be pure repeated work on the hot path."""
        sh = self._data_sh_cache.get(shape)
        if sh is None:
            from jax.sharding import PartitionSpec as P

            from ..parallel.sharding import batch_sharding

            spec = self._data_spec if self._data_spec is not None \
                else P("dp")
            sh = batch_sharding(self.mesh, spec, shape)
            self._data_sh_cache[shape] = sh
        return sh

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def infer(self, inputs, n_valid: Optional[int] = None
              ) -> Tuple[List[np.ndarray], int]:
        """Run one (possibly padded) batch. ``inputs``: one array per data
        name, equal leading dim. Returns ``(outputs, param_version)`` with
        outputs as host numpy sliced back to ``n_valid`` rows.

        Batches larger than the top bucket are chunked internally (each
        chunk still hits a bucketed program); the version is taken from the
        first chunk's snapshot — chunks of one oversized request could in
        principle straddle a reload, which is the documented cost of
        sending a request bigger than max_batch_size.
        """
        import jax

        from .. import profiler

        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if len(inputs) != len(self._data_names):
            raise ServeError(
                f"expected {len(self._data_names)} input(s) "
                f"({self._data_names}), got {len(inputs)}")
        arrays = [np.ascontiguousarray(np.asarray(x)) for x in inputs]
        n = int(arrays[0].shape[0]) if arrays[0].ndim else 1
        for a in arrays[1:]:
            if int(a.shape[0]) != n:
                raise ServeError("inputs disagree on batch dimension: "
                                 f"{[x.shape for x in arrays]}")
        if n == 0:
            raise ServeError("empty request (0 rows)")
        if n_valid is None:
            n_valid = n
        bucket = self.bucket_for(n)
        if bucket is None:
            # chunk an oversized batch through the top bucket
            top = self.max_batch_size
            pieces: List[List[np.ndarray]] = []
            version = None
            for lo in range(0, n, top):
                outs, v = self.infer([a[lo:lo + top] for a in arrays])
                version = v if version is None else version
                pieces.append(outs)
            merged = [np.concatenate([p[i] for p in pieces], axis=0)
                      for i in range(len(pieces[0]))]
            return [m[:n_valid] for m in merged], version

        pad = bucket - n
        if pad:
            arrays = [np.concatenate(
                [a, np.full((pad,) + a.shape[1:], self._pad_value, a.dtype)],
                axis=0) for a in arrays]
        sig = _sig_of(arrays)
        if self.mesh is not None:
            # commit the padded batch onto the mesh slice (dp-sharded when
            # the spec and bucket allow, replicated otherwise) — the sig is
            # taken from the host shapes above, so sharding never changes
            # the program-accounting key
            arrays = [jax.device_put(a, self._data_sharding(a.shape))
                      for a in arrays]
        free_vals = self._free_vals(bucket, [tuple(a.shape) for a in arrays])
        snapshot = self._params  # atomic: old-or-new, never mixed

        if profiler.counting_dispatches():
            profiler.count_dispatch("compiled")
            profiler.count_dispatch("h2d", len(arrays))
        arg_vals: List = [None] * self._n_args
        for slot, v in zip(self._param_slots, snapshot.arg_vals):
            arg_vals[slot] = v
        for slot, v in zip(self._free_slots, free_vals):
            arg_vals[slot] = v
        for slot, v in zip(self._data_slots, arrays):
            arg_vals[slot] = v
        rec = obs.enabled()
        t0 = time.monotonic() if rec else 0.0
        is_compile = sig not in self._programs
        cache_hit = False
        if is_compile:
            entry = {
                "sig": sig, "bucket": bucket,
                "param_avals": self._param_avals,
                "version_at_compile": snapshot.version,
            }
            pc = self._progcache
            pk = None
            if pc is not None:
                # persistent cache first: a hit deserializes the SAME
                # machine code an earlier process compiled — zero fresh
                # XLA work, bitwise-identical outputs
                pk = self._program_key(sig, bucket)
                entry["program_key"] = pk.digest
                cached = pc.get(pk)
                if cached is not None:
                    cache_hit = True
                    self._aot[sig] = cached.executable
                    cost = obs.device.adopt_cached_cost(pk, cached.meta)
                    if cost:
                        entry.update(cost)
                        self._sig_cost[sig] = cost
            entry["cache_hit"] = cache_hit
            if not cache_hit and (obs.device.active() or pc is not None):
                # one AOT compile per signature: cost/memory analysis into
                # the compile_log entry, the executable into the sig cache
                # (params stay traced arguments — reload still swaps arrays
                # without touching the program)
                with self._mesh_ctx():
                    if obs.device.active():
                        compiled, cost = obs.device.capture(
                            self._jitted,
                            (self._rng_data, arg_vals,
                             list(snapshot.aux_vals)),
                            site="serve", label=f"bucket{bucket}", key=pk)
                    else:  # cache armed, cost capture vetoed: plain AOT
                        from .. import progcache as _progcache

                        compiled = _progcache.aot_compile(
                            self._jitted,
                            (self._rng_data, arg_vals,
                             list(snapshot.aux_vals)))
                        cost = (obs.device.analyze_compiled(compiled)
                                if compiled is not None else None)
                if compiled is not None:
                    self._aot[sig] = compiled
                    if pc is not None:
                        pc.put(pk, compiled,
                               meta=dict(cost or {}, bucket=bucket))
                if cost:
                    entry.update(cost)
                    self._sig_cost[sig] = cost
            self.compile_log.append(entry)
            if cache_hit:
                with self._stat_lock:
                    self.cache_hits += 1
        fn = self._aot.get(sig, self._jitted)
        with obs.trace.span("serve.execute", bucket=bucket, rows=n_valid,
                            compile=is_compile, cache_hit=cache_hit,
                            version=snapshot.version) as sp:
            with self._mesh_ctx():
                outs, _new_aux = fn(self._rng_data, arg_vals,
                                    list(snapshot.aux_vals))
            cost = self._sig_cost.get(sig) if rec and not is_compile \
                else None
            if cost:
                # MFU over device work only (block, no D2H yet) so the
                # serve phase is comparable with forward/backward/update;
                # the span itself still covers the host materialization
                # (intentional sync: sampled timing boundary, not a stall)
                copytrack.TRACKER.host_sync("serve.engine.block_until_ready")
                jax.block_until_ready(outs)  # lint: disable=host-sync-on-hot-path
                obs.device.annotate_span(sp, "serve.execute",
                                         time.monotonic() - t0, cost)
            # materialize on host: the wire sends numpy, and an unwaited
            # future would let the execute span under-report real latency
            # (intentional sync: THE accounted d2h hop — copytrack counts
            # it so the wire_hop bench can subtract execute time)
            copytrack.TRACKER.host_sync("serve.engine.device_get")
            host = jax.device_get(list(outs))  # lint: disable=host-sync-on-hot-path
        if profiler.counting_dispatches():
            profiler.count_dispatch("d2h", len(host))
        if rec:
            dt = time.monotonic() - t0
            if is_compile and not cache_hit:
                obs.inc("serve.compile")
                obs.observe("serve.compile_seconds", dt)
            elif cache_hit:
                # a deserialize is not an XLA compile — count it apart so
                # "zero fresh compilations on warm start" is checkable;
                # and dt here includes the disk read + CRC + load, so it
                # stays out of the steady-state execute histogram too
                obs.inc("serve.cache_hit")
                obs.observe("serve.deserialize_seconds", dt)
            else:
                obs.observe("serve.execute_seconds", dt)
            obs.inc("serve.rows_executed", n_valid)
            obs.inc("serve.rows_padding", bucket - n_valid)
            obs.device.sample()  # live-HBM counter track, per batch
        with self._stat_lock:
            self._programs[sig] = self._programs.get(sig, 0) + 1
            self.exec_count += 1
        return ([np.asarray(o)[:n_valid] if np.ndim(o) else np.asarray(o)
                 for o in host], snapshot.version)

    def predict(self, *inputs):
        """Convenience single-call inference: numpy in, numpy out (one
        array, or a list when the graph has multiple outputs)."""
        outs, _version = self.infer(list(inputs))
        return outs[0] if len(outs) == 1 else outs

    def warmup(self, *feature_shapes, dtype=np.float32,
               concurrency: Optional[int] = None) -> int:
        """Pre-compile every bucket for the given per-row feature shape(s)
        (one tuple per data input; call once per distinct signature).
        Returns the number of programs compiled. Servers call this before
        flipping readiness so the first customer request never eats an XLA
        compile.

        Buckets warm **concurrently** (a thread pool over per-bucket
        compiles — XLA releases the GIL while it optimizes, so distinct
        buckets' compilations genuinely overlap; cache-hit deserialization
        runs at the same parallelism). ``concurrency`` caps the pool
        (``MXNET_SERVE_WARMUP_THREADS`` overrides the default of
        min(buckets, cores); 1 restores the serial path)."""
        shapes = list(feature_shapes) or [()]
        if len(shapes) != len(self._data_names):
            raise ServeError(
                f"warmup needs one feature shape per data input "
                f"({len(self._data_names)}), got {len(shapes)}")
        before = self.num_programs
        todo = [b for b in self.buckets
                if _sig_of([np.zeros((b,) + tuple(s), dtype)
                            for s in shapes]) not in self._programs]
        if concurrency is None:
            import os as _os

            from ..obs._env import env_int

            concurrency = env_int(
                "MXNET_SERVE_WARMUP_THREADS",
                min(len(todo) or 1, max(1, _os.cpu_count() or 2)))

        def _one(b):
            self.infer([np.zeros((b,) + tuple(s), dtype) for s in shapes])

        if concurrency <= 1 or len(todo) <= 1:
            for b in todo:
                _one(b)
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=min(concurrency, len(todo)),
                    thread_name_prefix="mxnet-serve-warmup") as pool:
                # list() re-raises the first worker's exception here,
                # matching the serial path's failure surface
                list(pool.map(_one, todo))
        return self.num_programs - before

    def save_programs(self, directory: str, keep: Optional[int] = None,
                      durable: bool = True) -> int:
        """Export this engine's compiled executables into ``directory`` as
        a persistent program-cache payload (the artifact ``programs/``
        convention ``serve.load`` auto-discovers — ``serve.ship_programs``
        wraps this with descriptor bookkeeping). Signatures compiled
        through the plain jit path (no cache/capture active) are
        AOT-recompiled from their recorded signature so every warmed
        bucket ships. Returns the number of entries written."""
        from .. import progcache as _progcache

        if self._key_statics is None:
            self._key_statics = self._compute_key_statics()
        pc = _progcache.ProgramCache(directory, keep=keep or 0,
                                     durable=durable)
        snapshot = self._params
        written = 0
        for sig in list(self._programs):
            bucket = int(sig[0][0][0])
            compiled = self._aot.get(sig)
            if compiled is None:
                # same trace scope as infer's compile sites: model code
                # (ring attention etc.) discovers the mesh slice at trace
                # time — an unscoped retrace would ship (and install) the
                # non-mesh variant of the program
                with self._mesh_ctx():
                    compiled = _progcache.aot_compile(
                        self._jitted, self._args_for_sig(sig, snapshot))
                if compiled is None:
                    continue
                self._aot[sig] = compiled
            pk = self._program_key(sig, bucket)
            meta = dict(self._sig_cost.get(sig) or {}, bucket=bucket)
            if pc.put(pk, compiled, meta=meta):
                written += 1
        return written

    def _args_for_sig(self, sig, snapshot) -> tuple:
        """Rebuild example program arguments from a recorded signature
        (zero-filled batches — only avals matter to ``lower``)."""
        import jax

        arrays = [np.zeros(shape, dtype) for shape, dtype in sig]
        if self.mesh is not None:
            arrays = [jax.device_put(a, self._data_sharding(a.shape))
                      for a in arrays]
        free_vals = self._free_vals(int(sig[0][0][0]),
                                    [tuple(a.shape) for a in arrays])
        arg_vals: List = [None] * self._n_args
        for slot, v in zip(self._param_slots, snapshot.arg_vals):
            arg_vals[slot] = v
        for slot, v in zip(self._free_slots, free_vals):
            arg_vals[slot] = v
        for slot, v in zip(self._data_slots, arrays):
            arg_vals[slot] = v
        return (self._rng_data, arg_vals, list(snapshot.aux_vals))

    # ------------------------------------------------------------------
    # hot reload
    # ------------------------------------------------------------------
    def _validated_param_set(self, arg_params, aux_params):
        """Shared reload validation: names, shapes, and dtypes must match
        the serving set — a drifted checkpoint would silently recompile
        every bucket (and is almost always a deploy mistake). Returns the
        device-resident ``(new_args, new_aux)`` tuples."""
        arg_params = dict(arg_params or {})
        aux_params = dict(aux_params or {})
        missing = [n for n in self._param_names if n not in arg_params]
        missing += [n for n in self._aux_names if n not in aux_params]
        if missing:
            raise ServeError(f"reload missing parameters: {missing}")
        # the new generation lands with the SAME shardings the serving set
        # was placed with (the dict resolved at construction): the compiled
        # programs' layouts are part of the engine contract, not of any one
        # parameter generation
        new_args = tuple(_to_device(arg_params[n], self._param_sh.get(n))
                         for n in self._param_names)
        new_aux = tuple(_to_device(aux_params[n], self._replicated_sh)
                        for n in self._aux_names)
        for names, vals, avals in (
                (self._param_names, new_args, self._param_avals),
                (self._aux_names, new_aux, self._aux_avals)):
            for name, v, (shape, dtype) in zip(names, vals, avals):
                got = (tuple(v.shape), str(v.dtype))
                if got != (shape, dtype):
                    raise ServeError(
                        f"reload aval mismatch for {name!r}: serving "
                        f"{(shape, dtype)}, new checkpoint {got} — this "
                        "would retrace every bucket; deploy a new engine "
                        "for a changed architecture")
        return new_args, new_aux

    def prepare_reload(self, arg_params, aux_params=None, *,
                       version: Optional[int] = None) -> int:
        """Phase one of a two-phase reload: do ALL fallible work now —
        validate against the serving avals, place the new generation on
        device — and stage it without flipping. :meth:`commit_reload` is
        then a pure pointer swap that only process death can stop, which is
        what makes a *fleet-wide* flip atomic (serve/fleet.py): every
        replica prepares, then every live replica's commit is infallible.

        ``version`` pins the staged generation number (the fleet stamps its
        own coherent version across replicas); default is current + 1.
        Returns the staged version."""
        new_args, new_aux = self._validated_param_set(arg_params, aux_params)
        with self._lock:
            v = int(version) if version is not None \
                else self._params.version + 1
            self._staged = _ParamSet(v, new_args, new_aux)
        obs.event("serve.reload_prepared", version=v)
        return v

    def commit_reload(self) -> int:
        """Phase two: flip the staged generation live (one reference swap;
        in-flight executions keep the snapshot they started with). Raises
        when nothing is staged. Returns the now-serving version."""
        with self._lock:
            if self._staged is None:
                raise ServeError("no prepared reload to commit")
            self._params, self._staged = self._staged, None
            version = self._params.version
        obs.inc("serve.reloads")
        obs.event("serve.reload", version=version)
        return version

    def abort_reload(self) -> None:
        """Discard a staged generation (two-phase rollback; idempotent)."""
        with self._lock:
            self._staged = None

    def reload(self, arg_params, aux_params=None, *,
               version: Optional[int] = None) -> int:
        """Swap in a new parameter generation without dropping in-flight
        work (single-replica path). One lock acquisition, and the staged
        slot is untouched — a legacy reload racing a two-phase fleet flip
        can neither clobber the staged generation nor be half-applied.
        Returns the new version."""
        new_args, new_aux = self._validated_param_set(arg_params, aux_params)
        with self._lock:
            v = int(version) if version is not None \
                else self._params.version + 1
            self._params = _ParamSet(v, new_args, new_aux)
        obs.inc("serve.reloads")
        obs.event("serve.reload", version=v)
        return v
