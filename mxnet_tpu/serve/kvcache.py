"""Paged KV-cache accounting — the memory half of the decode engine.

Reference: vLLM's PagedAttention block tables (TBV — PAPERS.md), rebuilt
on the engine.py pad-and-slice discipline: the device-resident KV pool is
ONE fixed-shape array (``(pages, layers, 2, page_size, heads, head_dim)``,
allocated once by ``serve/decode.py``), so no program ever sees a ragged
cache shape — growth is a *page-table edit on the host*, never a retrace.

This module owns the host half: a :class:`PagePool` free list with
per-sequence page tables, alloc/free at step granularity, and leak-checked
reclaim. The invariants are deliberately loud:

- every page is owned by exactly one sequence or the free list — a
  double free or a free of a foreign page raises :class:`PageLeakError`
  instead of silently corrupting a neighbour's cache;
- ``used()`` returning to its baseline after every finish/cancel/deadline/
  kill is the no-leak proof tests assert on, and the same number is
  exported live as the ``decode.kv_pages_used`` gauge;
- page 0 is a reserved scratch page: inactive decode slots point their
  page tables at it, so the fixed-shape decode-step program always has a
  legal write target and a masked-out read target. It is never handed out.

Sizing: a pool of ``P`` pages of ``page_size`` positions serves at most
``(P - 1) * page_size`` live KV positions across all concurrent
generations (page 0 is scratch). See docs/SERVING.md "Autoregressive
decode" for the sizing arithmetic.
"""
from __future__ import annotations

from typing import Dict, List

from .. import obs, tsan
from .engine import RequestRejected, ServeError

__all__ = ["PagePool", "PageLeakError", "PagesExhausted", "pages_for",
           "SCRATCH_PAGE"]

# page 0: the decode-step program's write/read target for inactive slots
SCRATCH_PAGE = 0


class PageLeakError(ServeError):
    """Page accounting corruption: double free, foreign free, or pages
    still owned at a point the caller asserted must be baseline."""


class PagesExhausted(RequestRejected):
    """The fixed page pool has no free page — shed semantics (429): the
    caller backs off or the scheduler sheds the newest generation."""


def pages_for(n_positions: int, page_size: int) -> int:
    """Pages needed to hold ``n_positions`` KV entries (ceil division)."""
    if n_positions <= 0:
        return 0
    return -(-int(n_positions) // int(page_size))


class PagePool:
    """Fixed pool of KV pages with per-sequence page tables.

    Allocation is at *step granularity*: a generation takes the pages its
    (padded) prompt needs at admission, then one page at a time as its
    position crosses a page boundary — so a short answer never reserves
    the worst-case footprint.
    """

    def __init__(self, num_pages: int, page_size: int):
        num_pages = int(num_pages)
        page_size = int(page_size)
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved scratch)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = num_pages
        self.page_size = page_size
        self._lock = tsan.lock("serve.kvcache.pool")
        # LIFO free list (page 0 excluded — reserved scratch): reusing the
        # most recently freed page keeps the working set of the device
        # pool compact
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._tables: Dict[object, List[int]] = {}
        self.alloc_count = 0
        self.free_count = 0
        self.exhausted = 0
        self._peak = 0

    # ------------------------------------------------------------------
    def capacity(self) -> int:
        """Allocatable pages (scratch excluded)."""
        return self.num_pages - 1

    def used(self) -> int:
        with self._lock:
            return self.capacity() - len(self._free)

    def available(self) -> int:
        with self._lock:
            return len(self._free)

    def table(self, seq) -> List[int]:
        """A copy of ``seq``'s page table, in position order."""
        with self._lock:
            t = self._tables.get(seq)
            if t is None:
                raise PageLeakError(f"unknown sequence {seq!r}")
            return list(t)

    def sequences(self) -> int:
        with self._lock:
            return len(self._tables)

    # ------------------------------------------------------------------
    def alloc(self, seq, n: int = 1) -> List[int]:
        """Append ``n`` pages to ``seq``'s table (created on first alloc).
        All-or-nothing: raises :class:`PagesExhausted` without taking any
        page when fewer than ``n`` are free."""
        n = int(n)
        if n < 0:
            raise ValueError("n must be >= 0")
        with self._lock:
            if len(self._free) < n:
                self.exhausted += 1
                obs.inc("decode.pages_exhausted")
                raise PagesExhausted(
                    f"kv page pool exhausted ({len(self._free)} free, "
                    f"{n} requested of {self.capacity()})")
            pages = [self._free.pop() for _ in range(n)]
            self._tables.setdefault(seq, []).extend(pages)
            self.alloc_count += n
            used = self.capacity() - len(self._free)
            self._peak = max(self._peak, used)
        obs.set_gauge("decode.kv_pages_used", used)
        return pages

    def free(self, seq) -> int:
        """Return ALL of ``seq``'s pages to the free list (finish, cancel,
        deadline, and dead-client reclaim all funnel here). Returns the
        page count; raises :class:`PageLeakError` for an unknown sequence
        (a double free is accounting corruption, not a no-op)."""
        with self._lock:
            pages = self._tables.pop(seq, None)
            if pages is None:
                raise PageLeakError(
                    f"free of unknown sequence {seq!r} (double free?)")
            for p in pages:
                if p == SCRATCH_PAGE or p >= self.num_pages:
                    raise PageLeakError(
                        f"sequence {seq!r} table held illegal page {p}")
            self._free.extend(reversed(pages))
            self.free_count += len(pages)
            used = self.capacity() - len(self._free)
        obs.set_gauge("decode.kv_pages_used", used)
        return len(pages)

    def assert_baseline(self, baseline: int = 0) -> None:
        """Raise :class:`PageLeakError` unless ``used() == baseline`` —
        the reclaim proof after a drain/chaos run."""
        used = self.used()
        if used != baseline:
            with self._lock:
                owners = {repr(k): len(v) for k, v in self._tables.items()}
            raise PageLeakError(
                f"kv page leak: {used} pages still owned "
                f"(baseline {baseline}); owners: {owners}")

    def stats(self) -> dict:
        with self._lock:
            return {"num_pages": self.num_pages,
                    "page_size": self.page_size,
                    "used": self.capacity() - len(self._free),
                    "free": len(self._free),
                    "peak_used": self._peak,
                    "sequences": len(self._tables),
                    "allocs": self.alloc_count,
                    "frees": self.free_count,
                    "exhausted": self.exhausted}
