"""Fault-tolerant serving fleet — supervised replicas behind a failover
router (docs/ROBUSTNESS.md "Serving fleet", docs/SERVING.md).

PR 5 made one serve process degrade gracefully; this layer makes the
*service* survive the process. The reference stack tolerates worker churn
by design (ps-lite retries RPCs past dead peers — PAPER.md §1); the
serving plane earns the same property here, plus the thing the reference
never had: a **fleet-atomic** model flip.

Layers (bottom up):

- **Replica handles** — :class:`LocalReplica` (an in-process
  :class:`~mxnet_tpu.serve.server.ServeServer`, "killed" by severing its
  sockets — crash-equivalent to a client) and :class:`ProcReplica` (a real
  subprocess, killed with SIGKILL). One supervision/routing code path
  covers both, so the fast tier-1 tests and the subprocess chaos flagship
  exercise the same logic.
- :class:`ReplicaPool` — supervision: liveness via the existing
  health/readiness probes, restart-with-capped-backoff+jitter on death
  (``base.capped_backoff`` — the PS client's curve), and **target
  tracking**: a replica restarted after a fleet reload is resynced to the
  committed ``(artifact, version)`` *before* it is marked ready, so a
  rejoin can never reintroduce a stale generation.
- :class:`Router` — spreads traffic over ready replicas (round-robin),
  with per-replica **circuit breakers** (trip on consecutive
  failures/timeouts, half-open probe recovery), client-side **failover**
  (INFER is read-only, so a retry on another replica is idempotent by
  construction), optional **tail-latency hedging** (duplicate a request on
  a second replica once it exceeds ``hedge_ms`` and the deadline still
  allows; first reply wins), and the **fleet-atomic two-phase reload**.
- :class:`FleetServer` — a :class:`ServeServer` whose "batcher" is the
  Router: same wire protocol, so ``ServeClient`` / ``serve_bench`` /
  chaos rules drive a fleet exactly like a single replica, and the STATS
  endpoint reports per-replica breaker/failover state.

Fleet-atomic reload (the two-phase flip)
----------------------------------------
``Router.reload`` reuses the PS plane's coordination idioms
(``kvstore/ps_server.py``): the prepare wave is a *barrier* — no commit is
sent until every ready replica has staged the new generation — and the
commit carries a ``(controller_id, epoch)`` token the replica dedups in an
LRU, so a retried commit whose ack was lost applies exactly once (the
``(client_id, seq)`` push idiom). Phase one does ALL fallible work
(disk load, device placement, aval validation); phase two is a pure
pointer swap that only process death can stop. The router then pauses
intake, drains in-flight work, commits everywhere, and stamps the fleet
version — so:

- a replica that dies during phase two serves *nothing* (not old params),
  and the pool restarts it onto the already-committed target;
- every reply carries its parameter version and the router rejects a
  stale one (failing over instead of returning it);
- ⇒ a mixed-version fleet is unreachable, asserted under chaos in
  tests/test_fleet.py.

Chaos hooks: ``MXNET_CHAOS_KILL_REPLICA<i>`` becomes replica *i*'s
``MXNET_CHAOS_KILL`` (SIGKILL at ``serve:post_recv`` / ``serve:pre_reply``
/ ``serve:pre_commit``); the router has ``fleet:post_prepare`` /
``fleet:pre_commit`` kill points of its own.

Telemetry: ``fleet.ready_replicas`` gauge, ``fleet.failovers`` /
``fleet.hedges`` / ``fleet.hedge_wins`` / ``fleet.breaker_trips`` /
``fleet.replica_deaths`` / ``fleet.replica_restarts`` counters,
``fleet.rpc.replica<i>_seconds`` histograms, ``fleet.route`` spans — all
in the same timeline as the serve spans (docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import contextlib
import os
import queue
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs, tsan
from ..base import capped_backoff
from ..chaos.proc import kill_point
from .batcher import Future
from .client import ServeClient
from .engine import (DeadlineExceeded, Draining, RequestRejected, ServeError)
from .server import ServeServer

__all__ = ["CircuitBreaker", "LocalReplica", "ProcReplica", "ReplicaPool",
           "Router", "FleetServer"]


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Per-replica circuit breaker: ``threshold`` consecutive hard failures
    trip it OPEN (requests skip the replica instead of queueing behind a
    corpse); after ``cooldown`` seconds it goes HALF-OPEN and admits one
    probe request — success closes it, failure re-opens it for another
    cooldown. Thread-safe; shed replies (429/draining) are *answers*, not
    failures, and reset the streak."""

    def __init__(self, threshold: int = 3, cooldown: float = 1.0):
        self.threshold = max(1, int(threshold))
        self.cooldown = float(cooldown)
        self.trips = 0
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_out = False
        # cumulative seconds spent NOT closed (open + half-open probing) —
        # the SLO monitor's "breaker open-time" signal: how long traffic
        # was being turned away from this replica
        self.open_seconds = 0.0
        self._not_closed_since: Optional[float] = None
        self._lock = tsan.lock("serve.breaker")

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a request go to this replica now? HALF-OPEN admits exactly
        one in-flight probe per cooldown window."""
        with self._lock:
            if self._state == "closed":
                return True
            if (self._state == "open"
                    and time.monotonic() - self._opened_at >= self.cooldown):
                self._state = "half_open"
                self._probe_out = False
            if self._state == "half_open" and not self._probe_out:
                self._probe_out = True
                return True
            return False

    def success(self) -> None:
        with self._lock:
            if self._not_closed_since is not None:
                self.open_seconds += time.monotonic() - self._not_closed_since
                self._not_closed_since = None
            self._state = "closed"
            self._consecutive = 0
            self._probe_out = False

    def release(self) -> None:
        """An admitted request ended with NO verdict on the replica's
        health (deadline expired client-side, dispatch never happened).
        Free the half-open probe slot so the next request can probe —
        without this, a deadline during half-open would blackhole the
        replica forever."""
        with self._lock:
            self._probe_out = False

    def failure(self) -> bool:
        """Record a hard failure; True when this call tripped the breaker
        open (the caller counts trips once, not per rejected request)."""
        with self._lock:
            self._consecutive += 1
            if self._state == "half_open" or (
                    self._state == "closed"
                    and self._consecutive >= self.threshold):
                was_closed = self._state == "closed"
                self._state = "open"
                self._opened_at = time.monotonic()
                if was_closed:  # open→half_open→open keeps the first stamp
                    self._not_closed_since = self._opened_at
                self._probe_out = False
                self.trips += 1
                return True
            return False

    def snapshot(self) -> dict:
        with self._lock:
            open_s = self.open_seconds
            if self._not_closed_since is not None:
                open_s += time.monotonic() - self._not_closed_since
            return {"state": self._state, "consecutive": self._consecutive,
                    "trips": self.trips, "threshold": self.threshold,
                    "cooldown_s": self.cooldown,
                    "open_seconds": round(open_s, 4)}


# ---------------------------------------------------------------------------
# replica handles
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class LocalReplica:
    """In-process replica: ``factory()`` must return a *started*
    :class:`ServeServer`. ``kill()`` severs its listener and every live
    connection without draining — to clients this is indistinguishable
    from SIGKILL, which makes the failover paths testable at tier-1
    speed."""

    def __init__(self, factory: Callable[[], ServeServer]):
        self._factory = factory
        self.server: Optional[ServeServer] = None
        self.idx = -1  # assigned by the pool

    def start(self) -> Tuple[str, int]:
        self.server = self._factory()
        return ("127.0.0.1", self.server.port)

    def alive(self) -> bool:
        return self.server is not None and not self.server._stop.is_set()

    def kill(self) -> None:
        if self.server is not None:
            self.server.abort()

    def stop(self) -> None:
        if self.server is not None:
            self.server.stop()
            self.server = None


class ProcReplica:
    """Subprocess replica: ``python -m mxnet_tpu.serve.server <model>`` on
    a pre-picked port. ``kill()`` is a real SIGKILL. Per-replica chaos:
    ``MXNET_CHAOS_KILL_REPLICA<idx>`` in the parent environment becomes the
    child's ``MXNET_CHAOS_KILL``, so one fleet member can be killed at a
    named code point while its peers stay healthy.

    Telemetry inheritance: when the parent has obs on (or ``MXNET_OBS`` is
    set) the child gets ``MXNET_OBS=1`` and the parent's sample rate; with
    an ``obs_dir`` (param or ``MXNET_OBS_DIR``) the child also streams
    flush-per-event JSONL to ``<obs_dir>/replica-<pid>.jsonl`` — so a
    SIGKILL'd replica still leaves its half of the timeline on disk, and
    ``tools/trace_report.py`` merges it back in by pid lane."""

    def __init__(self, model: str, *, args: Sequence[str] = (),
                 env: Optional[dict] = None, log_path: Optional[str] = None,
                 obs_dir: Optional[str] = None,
                 progcache_dir: Optional[str] = None):
        self.model = model
        self._args = list(args)
        self._env = dict(env or {})
        self._log_path = log_path
        self._obs_dir = obs_dir or os.environ.get("MXNET_OBS_DIR")
        # persistent AOT program cache (mxnet_tpu/progcache.py): an
        # explicit dir pins the child's cache; otherwise the parent's
        # MXNET_PROGCACHE* env rides the inherited environment, so
        # autoscale scale-out and restart-after-SIGKILL warm their bucket
        # programs from disk instead of recompiling
        self._progcache_dir = progcache_dir
        self.proc: Optional[subprocess.Popen] = None
        self.idx = -1  # assigned by the pool

    def start(self) -> Tuple[str, int]:
        port = _free_port()
        env = dict(os.environ)
        env.update(self._env)
        # the child must import mxnet_tpu regardless of the caller's cwd
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        chaos = env.pop(f"MXNET_CHAOS_KILL_REPLICA{self.idx}",
                        os.environ.get(f"MXNET_CHAOS_KILL_REPLICA{self.idx}"))
        if chaos:
            env["MXNET_CHAOS_KILL"] = chaos
        if self._progcache_dir:
            # explicit param beats an inherited dir; an inherited
            # MXNET_PROGCACHE=0 veto is deliberately NOT overridden
            env["MXNET_PROGCACHE_DIR"] = self._progcache_dir
        if obs.enabled():
            # the whole fleet observes or none of it does — a replica with
            # telemetry off would be a hole in every collected trace
            env.setdefault("MXNET_OBS", "1")
            env.setdefault("MXNET_OBS_SAMPLE",
                           repr(obs.context.sample_rate()))
            # the black-box plane inherits too: tail mode (replica-side
            # pending buffers), the continuous profiler, and the flight
            # recorder — whose bundle dir defaults to the same evidence
            # directory as the JSONL stream, so a SIGKILL'd replica
            # leaves BOTH its flushed spans and its last-seconds bundle
            if obs.tail.enabled():
                env.setdefault("MXNET_OBS_TAIL", "1")
            if obs.profile.enabled():
                env.setdefault("MXNET_OBS_PROF", "1")
            if self._obs_dir:
                env.setdefault("MXNET_OBS_BLACKBOX_DIR", self._obs_dir)
        if self._obs_dir and env.get("MXNET_OBS") \
                and "MXNET_OBS_JSONL" not in self._env:
            os.makedirs(self._obs_dir, exist_ok=True)
            # %p expands to the CHILD's pid at its obs import — per-pid
            # evidence files that survive SIGKILL. This OVERRIDES a
            # parent-inherited MXNET_OBS_JSONL (which would make every
            # replica append to one shared file with clashing clock
            # anchors); only an explicit per-replica env wins over it.
            env["MXNET_OBS_JSONL"] = os.path.join(
                self._obs_dir, "replica-%p.jsonl")
        out = open(self._log_path, "ab") if self._log_path \
            else subprocess.DEVNULL
        try:
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "mxnet_tpu.serve.server", self.model,
                 "--port", str(port)] + self._args,
                env=env, stdout=out, stderr=subprocess.STDOUT)
        finally:
            if out is not subprocess.DEVNULL:
                out.close()
        return ("127.0.0.1", port)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self) -> None:
        if self.alive():
            os.kill(self.proc.pid, signal.SIGKILL)

    def stop(self) -> None:
        if self.proc is not None:
            if self.proc.poll() is None:
                self.proc.terminate()
                try:
                    self.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    self.proc.kill()
            self.proc.wait()  # reap


# ---------------------------------------------------------------------------
# replica pool (supervision)
# ---------------------------------------------------------------------------

class _Member:
    __slots__ = ("idx", "handle", "state", "addr", "incarnation", "restarts",
                 "restart_at", "restarting", "version", "rpcs", "errors",
                 "sheds", "last_error", "queue_depth", "occupancy")

    def __init__(self, idx: int, handle):
        self.idx = idx
        self.handle = handle
        # new|starting|quarantined|ready|resync|dead|leaving|removed|stopped
        self.state = "new"
        self.addr: Optional[Tuple[str, int]] = None
        self.incarnation = 0
        self.restarts = 0
        self.restart_at = 0.0
        self.restarting = False
        self.version = 0
        self.rpcs = 0
        self.errors = 0
        self.sheds = 0
        self.last_error = ""
        # pulled from the replica's STATS by the supervisor each cycle and
        # mirrored into fleet.replica<i>.* gauges — the autoscaler and the
        # Prometheus exposition read the SAME numbers
        self.queue_depth = 0
        self.occupancy = 0.0


class ReplicaPool:
    """Supervise N serve replicas: bring-up, liveness probes, restart with
    capped backoff + jitter, and reload-target tracking so restarts rejoin
    at the committed fleet version (never a stale one).

    The pool is **elastic** (the ``kvstore/elastic.py`` membership protocol
    ported to the serve plane): :meth:`add_replica` brings a newcomer up
    **quarantined** — started, probed ready, warmed, resynced to the
    committed ``(artifact, version)`` target — and only then **activates**
    it at a **generation boundary** (one atomic flip under the pool lock;
    the Router's candidate set changes between requests, never mid-request).
    :meth:`remove_replica` is the leave half: deactivate at a boundary
    (routing stops instantly), drain the replica's queued + in-flight work,
    then stop it — scale-in sheds nothing. ``generation`` increments on
    every membership change, so observers can count scale events exactly.
    """

    def __init__(self, replicas: Sequence, *, probe_interval: float = 0.5,
                 backoff_base: float = 0.2, backoff_cap: float = 5.0,
                 ready_timeout: float = 120.0, probe_timeout: float = 3.0):
        if not replicas:
            raise ValueError("need at least one replica")
        self._members = [_Member(i, h) for i, h in enumerate(replicas)]
        for m in self._members:
            m.handle.idx = m.idx
        self.probe_interval = float(probe_interval)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.ready_timeout = float(ready_timeout)
        self.probe_timeout = float(probe_timeout)
        self._target: Optional[Tuple[str, Optional[int], str, int]] = None
        self._lock = tsan.rlock("serve.pool")
        self._pool_id = int.from_bytes(os.urandom(8), "little")
        self._resync_seq = 0
        self._stop_evt = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        # membership generation: bumped on every activate/leave (the
        # elastic-plane idiom) — autoscale events are generation deltas
        self.generation = 0
        # mesh-slice allocator (ReplicaPool.sharded): slices freed by
        # scale-in are reused by the next scale-out
        self._make_server: Optional[Callable] = None
        self._spare_slices: List = []

    @classmethod
    def local(cls, factory: Callable[[], ServeServer], n: int,
              **kw) -> "ReplicaPool":
        return cls([LocalReplica(factory) for _ in range(n)], **kw)

    @classmethod
    def spawn(cls, model: str, n: int, *, args: Sequence[str] = (),
              env: Optional[dict] = None, obs_dir: Optional[str] = None,
              **kw) -> "ReplicaPool":
        return cls([ProcReplica(model, args=args, env=env, obs_dir=obs_dir)
                    for _ in range(n)], **kw)

    @classmethod
    def sharded(cls, make_server: Callable, groups: Optional[int] = None, *,
                mesh=None, start: Optional[int] = None,
                **kw) -> "ReplicaPool":
        """Data-parallel replica groups on mesh slices: split the device
        mesh along its ``dp`` axis into ``groups`` tensor-parallel
        submeshes (``parallel.mesh_slices``) and supervise one in-process
        replica per slice. ``make_server(submesh)`` must return a *started*
        :class:`~mxnet_tpu.serve.server.ServeServer` whose engine was built
        with ``mesh=submesh`` (see ``InferenceEngine``).

        ``start`` (default: all ``groups``) brings up only the first
        ``start`` slices; the rest stay spare for elastic scale-out
        (:meth:`new_sharded_handle` / ``serve/autoscale.py``). Default mesh:
        ``make_mesh({"dp": groups, "tp": -1})`` over all local devices —
        every device serves from the first request."""
        import functools

        from ..parallel import make_mesh, mesh_slices

        if mesh is None:
            if not groups:
                raise ValueError("pass groups= or mesh=")
            mesh = make_mesh({"dp": int(groups), "tp": -1})
        slices = mesh_slices(mesh, "dp")
        if start is None:
            start = len(slices)
        start = max(1, min(int(start), len(slices)))
        replicas = []
        for sub in slices[:start]:
            r = LocalReplica(functools.partial(make_server, sub))
            r.mesh = sub
            replicas.append(r)
        pool = cls(replicas, **kw)
        pool._make_server = make_server
        pool._spare_slices = list(slices[start:])
        return pool

    def new_sharded_handle(self) -> LocalReplica:
        """Allocate a spare mesh slice and return a replica handle bound to
        it — the autoscaler's scale-out factory for sharded pools. Raises
        :class:`ServeError` when every slice is in use."""
        import functools

        if self._make_server is None:
            raise ServeError("not a sharded pool (use ReplicaPool.sharded)")
        with self._lock:
            if not self._spare_slices:
                raise ServeError("no spare mesh slices (fleet at capacity)")
            sub = self._spare_slices.pop(0)
        r = LocalReplica(functools.partial(self._make_server, sub))
        r.mesh = sub
        return r

    @property
    def spare_slices(self) -> int:
        with self._lock:
            return len(self._spare_slices)

    # -- lifecycle ------------------------------------------------------
    def start(self, wait_ready: bool = True) -> "ReplicaPool":
        threads = [threading.Thread(target=self._bring_up, args=(m,),
                                    daemon=True) for m in self._members]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.ready_timeout)
        if any(t.is_alive() for t in threads):
            # a wedged bring-up is not fatal (the member stays un-ready and
            # the supervisor owns it) but must not pass silently
            obs.inc("fleet.bringup_threads_stuck")
            obs.event("fleet.bringup_stuck",
                      stuck=sum(t.is_alive() for t in threads))
        self._stop_evt.clear()
        self._supervisor = threading.Thread(target=self._supervise,
                                            daemon=True,
                                            name="mxtpu-fleet-supervisor")
        self._supervisor.start()
        if wait_ready and not self.ready_members():
            self.stop()
            errs = {m.idx: m.last_error for m in self._members}
            raise ServeError(f"no replica became ready: {errs}")
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
            if self._supervisor.is_alive():
                obs.inc("fleet.supervisor_thread_leaked")
                obs.event("fleet.supervisor_thread_leaked", join_timeout_s=5)
        for m in self._members:
            try:
                m.handle.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
            m.state = "stopped"
        self._gauge()

    # -- views ----------------------------------------------------------
    def members(self) -> List[_Member]:
        return list(self._members)

    def ready_members(self) -> List[_Member]:
        return [m for m in self._members if m.state == "ready"]

    @property
    def target(self):
        with self._lock:
            return self._target

    def set_target(self, path: str, epoch: Optional[int], prefix: str,
                   version: int) -> None:
        """Record the committed reload target. Called by the router BEFORE
        phase-two commits begin, so a replica killed mid-flip restarts onto
        the new generation — the invariant that keeps a mixed-version fleet
        unreachable."""
        with self._lock:
            self._target = (path, epoch, prefix, int(version))

    def request_resync(self, idx: int) -> None:
        """Ask the supervisor to re-drive a live replica onto the committed
        target (a commit that errored on an alive replica)."""
        m = self._members[idx]
        if m.state == "ready":
            m.state = "resync"

    def kill(self, idx: int) -> None:
        """Chaos helper: hard-kill one replica (SIGKILL / socket sever).
        The supervisor detects and restarts it."""
        obs.event("fleet.chaos_kill", replica=idx)
        self._members[idx].handle.kill()

    # -- elastic membership (the kvstore/elastic.py join/leave protocol) -
    def add_replica(self, handle, *, wait_ready: bool = True) -> int:
        """Elastic scale-out: register ``handle`` as a new member and drive
        it through quarantine → resync-to-committed-target → activation at
        a generation boundary. ``wait_ready=False`` joins in the background
        (the autoscaler's mode — bring-up includes XLA warmup and must not
        block the control loop). Returns the member index."""
        with self._lock:
            idx = len(self._members)
            m = _Member(idx, handle)
            handle.idx = idx
            self._members.append(m)
        obs.inc("fleet.scale_out")
        obs.event("fleet.replica_join", replica=idx)
        if wait_ready:
            self._bring_up(m)
            if m.state != "ready":
                raise ServeError(
                    f"replica {idx} failed to join: {m.last_error}")
        else:
            # supervised fire-and-forget: the member's state machine (the
            # pool lock + leaving/removed terminal states) owns this
            # bring-up; remove_replica reaps a member whose thread wedged
            threading.Thread(target=self._bring_up, args=(m,),
                             daemon=True).start()  # lint: disable=thread-fire-and-forget
        return idx

    def remove_replica(self, idx: int, *, drain_timeout: float = 30.0
                       ) -> bool:
        """Elastic scale-in (the leave protocol): deactivate at a
        generation boundary — ``ready_members()`` stops listing the member
        the instant the state flips, so the Router routes nothing new to
        it — then DRAIN its queued + in-flight work and stop the handle.
        Zero requests are lost: anything racing the flip fails over through
        the Router. Returns True when the drain finished in time."""
        m = self._members[idx]
        with self._lock:
            if m.state in ("leaving", "removed", "stopped"):
                return True
            prev, m.state = m.state, "leaving"
            self.generation += 1
            gen = self.generation
        obs.inc("fleet.scale_in")
        obs.event("fleet.replica_leave", replica=idx, generation=gen)
        self._gauge()
        drained = True
        if prev == "ready" and m.handle.alive() and m.addr:
            try:
                cli = self._client(m, timeout=max(drain_timeout,
                                                  self.probe_timeout))
                try:
                    drained = cli.drain(stop=False)
                finally:
                    cli.close()
            except Exception as e:  # noqa: BLE001 — leave is best-effort
                m.last_error = f"drain: {type(e).__name__}: {e}"
                drained = False
        try:
            m.handle.stop()
        except Exception:  # noqa: BLE001 — it may already be dead
            pass
        m.state = "removed"
        # a removed member's exported gauges must not linger in the
        # Prometheus exposition as frozen last values
        for g in ("queue_depth", "occupancy", "breaker_state"):
            obs.metrics.remove(f"fleet.replica{idx}.{g}")
        # return the mesh slice (sharded pools) for the next scale-out
        sub = getattr(m.handle, "mesh", None)
        if sub is not None and self._make_server is not None:
            with self._lock:
                self._spare_slices.append(sub)
        self._gauge()
        return drained

    def _activate(self, m: _Member) -> bool:
        """Activate at a generation boundary: ONE atomic flip under the
        pool lock. Routing (ready_members) sees the member before or after
        the boundary, never a half-joined state."""
        with self._lock:
            if m.state in ("leaving", "removed", "stopped"):
                return False  # removed while joining: stay out
            m.state = "ready"
            self.generation += 1
            gen = self.generation
        obs.set_gauge("fleet.generation", gen)
        obs.event("fleet.replica_activated", replica=m.idx,
                  generation=gen, version=m.version)
        return True

    def stats(self) -> dict:
        members = {}
        for m in self._members:
            members[str(m.idx)] = {
                "state": m.state, "version": m.version,
                "restarts": m.restarts,
                "queue_depth": m.queue_depth,
                "occupancy": round(m.occupancy, 4)}
        return {"replicas": len(self._members),
                "ready": len(self.ready_members()),
                "generation": self.generation,
                "spare_slices": self.spare_slices,
                "target_version": self._target[3] if self._target else None,
                "restarts": sum(m.restarts for m in self._members),
                "members": members}

    # -- internals ------------------------------------------------------
    def _gauge(self) -> None:
        obs.set_gauge("fleet.ready_replicas", len(self.ready_members()))

    def _client(self, m: _Member, timeout: Optional[float] = None
                ) -> ServeClient:
        return ServeClient(m.addr[0], m.addr[1],
                           timeout=timeout or self.probe_timeout, retries=1)

    def _transition(self, m: _Member, state: str) -> bool:
        """Flip a member's state under the pool lock unless it has left
        (leaving/removed/stopped are terminal for joiners): an unlocked
        write here could overwrite a concurrent remove_replica's verdict
        and activate — and route — a replica whose mesh slice was already
        returned to the spare list."""
        with self._lock:
            if m.state in ("leaving", "removed", "stopped"):
                return False
            m.state = state
            return True

    def _bring_up(self, m: _Member) -> None:
        if not self._transition(m, "starting"):
            return  # scaled in while waiting for this bring-up
        try:
            m.addr = m.handle.start()
            m.incarnation += 1
            deadline = time.monotonic() + self.ready_timeout
            ready = False
            while time.monotonic() < deadline and not self._stop_evt.is_set():
                if not m.handle.alive():
                    raise ServeError("replica process died during bring-up")
                cli = self._client(m)
                try:
                    ready, m.version = cli.ready_version()
                finally:
                    cli.close()
                if ready:
                    break
                time.sleep(min(0.05 * (1 + m.restarts), 0.5))
            if not ready:
                raise ServeError(
                    f"replica {m.idx} not ready within {self.ready_timeout}s")
            # QUARANTINE: fully up but not routed — the committed-target
            # resync happens here, so activation can never introduce a
            # stale generation (the elastic-plane rejoin invariant)
            if not self._transition(m, "quarantined"):
                return  # removed mid-bring-up; the leaver stopped the handle
            self._resync_member(m)
            if not self._activate(m):
                return  # removed while quarantined
            obs.event("fleet.replica_ready", replica=m.idx,
                      incarnation=m.incarnation, version=m.version)
        except Exception as e:  # noqa: BLE001 — supervised: schedule retry
            m.last_error = f"{type(e).__name__}: {e}"
            self._schedule_restart(m)
        self._gauge()

    def _resync_member(self, m: _Member) -> None:
        tgt = self.target
        if tgt is None or m.version == tgt[3]:
            return
        path, epoch, prefix, version = tgt
        with self._lock:
            self._resync_seq += 1
            token = (self._pool_id, self._resync_seq)
        cli = self._client(m, timeout=max(self.probe_timeout, 10.0))
        try:
            cli.prepare_reload(path, epoch=epoch, prefix=prefix,
                               version=version, token=token, retries=3)
            cli.commit_reload(token, retries=3)
        finally:
            cli.close()
        m.version = version
        obs.event("fleet.replica_resynced", replica=m.idx, version=version)

    def _probe_ok(self, m: _Member) -> bool:
        cli = self._client(m)
        try:
            return cli.health()
        finally:
            cli.close()

    def _mark_dead(self, m: _Member) -> None:
        if not self._transition(m, "dead"):
            return  # already leaving/removed: no death accounting
        obs.inc("fleet.replica_deaths")
        obs.event("fleet.replica_dead", replica=m.idx,
                  incarnation=m.incarnation)
        self._schedule_restart(m)
        self._gauge()

    def _schedule_restart(self, m: _Member) -> None:
        if not self._transition(m, "dead"):
            return  # a leaver's death needs no resurrection
        delay = capped_backoff(m.restarts, self.backoff_base,
                               self.backoff_cap)
        m.restart_at = time.monotonic() + delay

    def _restart(self, m: _Member) -> None:
        try:
            m.restarts += 1
            obs.inc("fleet.replica_restarts")
            try:
                m.handle.stop()  # reap the corpse / release the old socket
            except Exception:  # noqa: BLE001 — it is already dead
                pass
            self._bring_up(m)
        finally:
            m.restarting = False

    def _probe_ready_members(self) -> None:
        """Probe every ready member CONCURRENTLY: a wedged replica blocks
        its own probe for probe_timeout, not the detection and restart of
        its dead peers (serial probing would head-of-line-block the whole
        supervision cycle behind one corpse)."""
        ready = [m for m in self._members if m.state == "ready"]
        if not ready:
            return
        verdicts = {}

        def probe(m):
            try:
                verdicts[m.idx] = m.handle.alive() and self._probe_ok(m)
            except Exception:  # noqa: BLE001 — a broken probe is a death
                verdicts[m.idx] = False

        threads = [threading.Thread(target=probe, args=(m,), daemon=True)
                   for m in ready]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.probe_timeout + 1.0)
        if any(t.is_alive() for t in threads):
            # a probe thread still stuck past its socket timeout means a
            # wedged replica: its member gets no verdict below and is
            # marked dead — count the stuck probe so a watchdog dump has
            # a metric to correlate with
            obs.inc("fleet.probe_threads_stuck")
        for m in ready:
            # no verdict (probe thread still stuck) = not answering = dead
            if m.state == "ready" and not verdicts.get(m.idx, False):
                self._mark_dead(m)

    def _collect_member_stats(self) -> None:
        """Pull each ready replica's batcher queue-depth/occupancy (one
        metrics-free STATS RPC) into the member record AND the registry, so
        the autoscaler and the Prometheus exposition read the same numbers
        the operator's dashboard does — pool stats stop being
        snapshot-on-demand only."""
        for m in [m for m in self._members if m.state == "ready"]:
            try:
                cli = self._client(m)
                try:
                    st = cli.stats(include_metrics=False)
                finally:
                    cli.close()
            except Exception:  # noqa: BLE001 — the probe's job, not ours
                continue
            b = st.get("batcher") or {}
            m.queue_depth = int(b.get("queue_depth", 0) or 0)
            m.occupancy = float(b.get("occupancy", 0.0) or 0.0)
            # re-check state + set gauges under the pool lock: a
            # remove_replica that ran during the stats RPC has already
            # deleted this member's gauges, and an unguarded set here
            # would resurrect them as permanent frozen values (removal
            # flips the state under the same lock first)
            with self._lock:
                if m.state != "ready":
                    continue
                obs.set_gauge(f"fleet.replica{m.idx}.queue_depth",
                              m.queue_depth)
                obs.set_gauge(f"fleet.replica{m.idx}.occupancy",
                              m.occupancy)
        obs.set_gauge("fleet.replicas_total", sum(
            1 for m in self._members
            if m.state not in ("removed", "stopped")))
        obs.set_gauge("fleet.generation", self.generation)

    def _supervise(self) -> None:
        while not self._stop_evt.wait(self.probe_interval):
            self._probe_ready_members()
            self._collect_member_stats()
            for m in self._members:
                if self._stop_evt.is_set():
                    return
                if m.state == "resync":
                    try:
                        self._resync_member(m)
                        m.state = "ready"
                    except Exception as e:  # noqa: BLE001 — degrade to dead
                        m.last_error = f"{type(e).__name__}: {e}"
                        self._mark_dead(m)
                elif (m.state == "dead" and not m.restarting
                        and time.monotonic() >= m.restart_at):
                    m.restarting = True
                    # supervised: m.restarting gates re-spawn and _restart
                    # clears it in a finally — the supervisor loop is the
                    # join point for this state machine
                    threading.Thread(target=self._restart, args=(m,),
                                     daemon=True).start()  # lint: disable=thread-fire-and-forget
            self._gauge()


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------

class _ConnPool:
    """Free-list of ServeClients for one replica incarnation (one socket
    per concurrent request, not one serialized socket per replica)."""

    def __init__(self, addr: Tuple[str, int], timeout: float):
        self._addr = addr
        self._timeout = timeout
        self._free: List[ServeClient] = []
        self._lock = tsan.lock("serve.connpool")

    def acquire(self) -> ServeClient:
        with self._lock:
            if self._free:
                return self._free.pop()
        return ServeClient(self._addr[0], self._addr[1],
                           timeout=self._timeout, retries=1)

    def release(self, cli: ServeClient) -> None:
        with self._lock:
            self._free.append(cli)

    def close(self) -> None:
        with self._lock:
            for cli in self._free:
                cli.close()
            self._free.clear()


class Router:
    """Spread INFER traffic across a :class:`ReplicaPool` with breakers,
    failover, hedging, and the fleet-atomic two-phase reload. Duck-types
    the :class:`DynamicBatcher` surface (``submit``/``stats``/``drain``/
    ``close`` + ``ready``/``version``), so a :class:`ServeServer` front
    can mount it directly as its batcher (:class:`FleetServer`)."""

    def __init__(self, pool: ReplicaPool, *, hedge_ms: Optional[float] = None,
                 breaker_threshold: int = 3, breaker_cooldown: float = 1.0,
                 client_timeout: float = 30.0, gate_timeout: float = 10.0,
                 flip_timeout: float = 30.0):
        self._pool = pool
        self.hedge_ms = hedge_ms
        self._client_timeout = float(client_timeout)
        self._gate_timeout = float(gate_timeout)
        self._flip_timeout = float(flip_timeout)
        self._breakers = {m.idx: CircuitBreaker(breaker_threshold,
                                                breaker_cooldown)
                          for m in pool.members()}
        self._pools: dict = {}
        self._lock = tsan.lock("serve.router")
        self._rr = 0
        # intake gate: cleared only for the phase-two flip window
        self._gate = threading.Event()
        self._gate.set()
        self._cv = tsan.condition("serve.router.inflight")
        self._inflight = 0
        tgt = pool.target
        self._fleet_version = tgt[3] if tgt else 0
        self._reload_lock = tsan.lock("serve.router.reload")
        self._controller_id = int.from_bytes(os.urandom(8), "little")
        self._reload_epoch = 0
        self._commit_hook: Optional[Callable] = None  # test injection point
        # unconditional counters (the STATS endpoint works with obs off)
        self.failovers = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.stale_rejected = 0

    # -- plumbing -------------------------------------------------------
    def _breaker(self, m: _Member) -> CircuitBreaker:
        br = self._breakers.get(m.idx)
        if br is None:
            br = self._breakers.setdefault(m.idx, CircuitBreaker())
        return br

    @contextlib.contextmanager
    def _conn(self, m: _Member):
        key = (m.idx, m.incarnation)
        pool = self._pools.get(key)
        if pool is None:
            with self._lock:
                pool = self._pools.get(key)
                if pool is None:
                    pool = _ConnPool(m.addr, self._client_timeout)
                    self._pools[key] = pool
                    for k in [k for k in self._pools
                              if k[0] == m.idx and k != key]:
                        self._pools.pop(k).close()  # stale incarnation
        cli = pool.acquire()
        try:
            yield cli
        except BaseException:
            cli.close()  # unknown wire state: never back into the pool
            raise
        else:
            pool.release(cli)

    def _candidates(self) -> List[_Member]:
        members = self._pool.ready_members()
        if not members:
            return []
        with self._lock:
            start = self._rr % len(members)
            self._rr += 1
        return members[start:] + members[:start]

    # -- the per-replica attempt ---------------------------------------
    def _attempt(self, m: _Member, arrays, deadline: Optional[float],
                 priority: int):
        """One replica, one try. Returns ``(True, (outs, version))`` or
        ``(False, exception)``. Hard failures feed the breaker; shed
        replies are answers (the replica is alive) and reset it."""
        rem = None if deadline is None else deadline - time.monotonic()
        if rem is not None and rem <= 0:
            return False, DeadlineExceeded("deadline expired before dispatch")
        br = self._breaker(m)
        if not br.allow():
            return False, RequestRejected(
                f"replica {m.idx} circuit breaker open")
        rpc_timeout = self._client_timeout if rem is None \
            else min(self._client_timeout, rem + 0.5)
        t0 = time.monotonic()
        try:
            with obs.trace.span("fleet.route", replica=m.idx,
                                priority=priority):
                with self._conn(m) as cli:
                    result, version = cli.infer(
                        *arrays,
                        deadline_ms=rem * 1e3 if rem is not None else None,
                        priority=priority, return_version=True,
                        rpc_timeout=rpc_timeout)
        except (RequestRejected, Draining) as e:
            br.success()  # an answering replica is a healthy replica
            m.sheds += 1
            return False, e
        except DeadlineExceeded as e:
            # no health verdict (the budget ran out, the replica may be
            # fine) — but the half-open probe slot must not leak
            br.release()
            return False, e
        except (ServeError, ConnectionError, OSError) as e:
            if br.failure():
                obs.inc("fleet.breaker_trips")
                obs.event("fleet.breaker_trip", replica=m.idx)
                # tail retention: a request that crossed a TRIPPING
                # breaker is interesting even if a failover later
                # succeeds (a lone failure that fails over cleanly is
                # not — "breaker" must mean a trip, or the retention
                # counters operators alert on lie)
                obs.tail.note(breaker=True)
            m.errors += 1
            m.last_error = f"{type(e).__name__}: {e}"
            return False, e
        br.success()
        m.rpcs += 1
        obs.observe(f"fleet.rpc.replica{m.idx}_seconds",
                    time.monotonic() - t0)
        outs = result if isinstance(result, list) else [result]
        return True, (outs, int(version))

    def _attempt_hedged(self, primary: _Member, secondary: _Member, arrays,
                        deadline: Optional[float], priority: int):
        """Race a slow primary against a hedge on a second replica: wait
        ``hedge_ms`` for the primary, then duplicate the request (INFER is
        read-only — the loser's work is wasted capacity, not corruption)
        and take the first success."""
        q: "queue.Queue" = queue.Queue()
        # the trace context is thread-local and the racing attempts run on
        # fresh threads — carry it over, or every hedged request would
        # re-root downstream (new trace_id, fresh sampling roll) and fall
        # out of the client's trace
        ctx = obs.context.current()

        def run(member):
            with obs.context.use(ctx):
                res = self._attempt(member, arrays, deadline, priority)
                # tail notes are thread-local too: a breaker trip noted
                # inside _attempt lands in THIS racer's TLS, which no
                # finish_root ever reads — ship the notes back with the
                # result so the request thread re-applies them to the
                # root's retention verdict
                q.put((member, res, obs.tail.take_notes()))

        def renote(notes):
            outcome, flags = notes
            if outcome:
                obs.tail.note(outcome=outcome)
            for f in flags:
                obs.tail.note(**{f: True})

        # deliberately unjoined racer: the reply comes back over q and
        # INFER is read-only — the losing attempt is wasted capacity, not
        # an orphaned mutation; a wedged racer dies with its socket timeout
        threading.Thread(target=run, args=(primary,), daemon=True).start()  # lint: disable=thread-fire-and-forget
        try:
            member, (ok, val), notes = q.get(timeout=self.hedge_ms / 1e3)
            renote(notes)
            if ok:
                return True, val
            # primary failed FAST (conn refused, shed): that is plain
            # failover to the secondary, not a hedge
            self.failovers += 1
            obs.inc("fleet.failovers")
            return self._attempt(secondary, arrays, deadline, priority)
        except queue.Empty:
            pass
        self.hedges += 1
        obs.inc("fleet.hedges")
        obs.event("fleet.hedge", primary=primary.idx,
                  secondary=secondary.idx)
        # a hedged request is a tail-retention signal: the primary was
        # slow enough to duplicate, whoever wins
        obs.tail.note(hedged=True)
        threading.Thread(target=run, args=(secondary,), daemon=True).start()  # lint: disable=thread-fire-and-forget
        budget = self._client_timeout if deadline is None \
            else max(deadline - time.monotonic(), 0.0)
        end = time.monotonic() + budget + 0.5
        last = None
        for _ in range(2):
            try:
                member, (ok, val), notes = q.get(
                    timeout=max(end - time.monotonic(), 0.01))
            except queue.Empty:
                break
            renote(notes)
            if ok:
                if member is secondary:
                    self.hedge_wins += 1
                    obs.inc("fleet.hedge_wins")
                return True, val
            last = val
        return False, (last if last is not None
                       else DeadlineExceeded("hedged attempts timed out"))

    # -- public API -----------------------------------------------------
    def infer(self, inputs, deadline_ms: Optional[float] = None,
              priority: int = 1) -> Tuple[List[np.ndarray], int]:
        """Route one request; failover across replicas within the deadline.
        Returns ``(outputs, param_version)`` like ``InferenceEngine.infer``.
        Raises the last shed error only when every replica shed; a hard
        failure on every replica raises :class:`ServeError`."""
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        arrays = [np.ascontiguousarray(np.asarray(x)) for x in inputs]
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms else None)
        # a Router driven directly (no FleetServer front) still roots the
        # trace here, so fleet.route → replica spans correlate; behind a
        # front the serve.rpc handler already activated the wire context
        rctx = None
        if obs.enabled() and obs.context.current() is None:
            rctx = obs.context.new_root()
        # gate-check and inflight-increment must be one atomic step from
        # the flip's point of view: check the gate again under _cv after
        # counting ourselves, so either the reload's drain sees us (and
        # waits) or we see the cleared gate (and back off) — a request can
        # never slip between the gate clearing and the commit wave
        gate_deadline = time.monotonic() + self._gate_timeout
        while True:
            budget = gate_deadline - time.monotonic()
            if deadline is not None:
                budget = min(budget, deadline - time.monotonic())
            if budget <= 0 or not self._gate.wait(timeout=budget):
                raise RequestRejected("fleet reload flip in progress; retry")
            with self._cv:
                if self._gate.is_set():
                    self._inflight += 1
                    break
        t0 = time.monotonic()
        outcome = "ok"
        try:
            with obs.context.use(rctx):
                result = self._infer_routed(arrays, deadline, priority)
            # ONE observation per REQUEST, front-side — the replica-side
            # serve.latency_seconds counts executions, which hedging
            # duplicates; SLO math prefers this histogram when present so
            # phantom hedge completions can't dilute attainment
            obs.observe("fleet.request_latency_seconds",
                        time.monotonic() - t0)
            return result
        except DeadlineExceeded:
            obs.inc("fleet.request_deadline_exceeded")
            outcome = "deadline"
            raise
        except (RequestRejected, Draining):
            outcome = "shed"
            raise
        except BaseException:
            outcome = "error"
            raise
        finally:
            # tail retention for a directly-driven Router (rctx is the
            # root): verdict here. Behind a FleetServer front the wire
            # handler owns the root — and this thread's hedge/breaker
            # notes, which finish_root must NOT consume (rctx None skips
            # the call entirely; the front's finish reads them)
            if rctx is not None:
                obs.tail.finish_root(rctx, time.monotonic() - t0,
                                     outcome=outcome)
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()

    def _infer_routed(self, arrays, deadline, priority):
        cands = self._candidates()
        if not cands:
            raise RequestRejected("no ready replicas")
        shed_err = None
        hard_err = None
        i = 0
        while i < len(cands):
            if deadline is not None and time.monotonic() >= deadline:
                raise DeadlineExceeded(
                    "deadline expired during fleet failover")
            hedge_ok = (self.hedge_ms is not None and i + 1 < len(cands)
                        and (deadline is None
                             or (deadline - time.monotonic()) * 1e3
                             > 2 * self.hedge_ms))
            if hedge_ok:
                ok, val = self._attempt_hedged(cands[i], cands[i + 1],
                                               arrays, deadline, priority)
                i += 2
            else:
                ok, val = self._attempt(cands[i], arrays, deadline, priority)
                i += 1
            if ok:
                outs, version = val
                if version != self._fleet_version:
                    # a reply from a generation the fleet no longer serves
                    # must never escape — reject and fail over (the pool
                    # resyncs the straggler)
                    self.stale_rejected += 1
                    obs.inc("fleet.stale_version_rejected")
                    hard_err = ServeError(
                        f"stale param version {version} "
                        f"(fleet at {self._fleet_version})")
                    continue
                return outs, version
            if isinstance(val, DeadlineExceeded):
                raise val
            if isinstance(val, (RequestRejected, Draining)):
                shed_err = val
            else:
                hard_err = val
            if i < len(cands):
                self.failovers += 1
                obs.inc("fleet.failovers")
        if hard_err is not None:
            raise ServeError(
                f"all {len(cands)} replicas failed; last: {hard_err}")
        raise shed_err if shed_err is not None \
            else RequestRejected("no replica accepted the request")

    # -- DynamicBatcher duck-type (FleetServer mounts this) -------------
    def submit(self, inputs, deadline_ms: Optional[float] = None,
               priority: int = 1) -> Future:
        """Route inline and return a resolved Future (concurrency comes
        from the front's thread-per-connection handlers); shed/deadline
        errors raise here, matching ``DynamicBatcher.submit`` fail-fast."""
        fut = Future()
        fut._set_result(self.infer(inputs, deadline_ms=deadline_ms,
                                   priority=priority))
        return fut

    def generate(self, tokens, *, max_new_tokens: Optional[int] = None,
                 deadline_ms: Optional[float] = None, priority: int = 1,
                 temperature: float = 0.0):
        """Route one streaming generation to a replica and relay its
        tokens (the ``DecodeScheduler.generate`` duck-type — a
        FleetServer front mounts this as its INFER_STREAM source).

        Failover is only legal BEFORE the first token: a shed or hard
        failure with nothing streamed moves to the next candidate like
        ``infer``, but once a replica has emitted a chunk the generation
        is COMMITTED there — a retry elsewhere would splice a different
        token sequence into the same stream — so a mid-stream failure
        propagates to the caller as the typed error. Streams do not hold
        the reload-flip gate (a generation can outlive a flip); a flip
        that restarts the serving replica surfaces as a mid-stream
        ``ServeError``, which the caller handles exactly like any other
        broken stream."""
        prompt = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32))
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms else None)
        cands = self._candidates()
        if not cands:
            raise RequestRejected("no ready replicas")
        shed_err = None
        hard_err = None
        for i, m in enumerate(cands):
            if i:
                self.failovers += 1
                obs.inc("fleet.failovers")
            rem = None if deadline is None else deadline - time.monotonic()
            if rem is not None and rem <= 0:
                raise DeadlineExceeded(
                    "deadline expired during fleet failover")
            br = self._breaker(m)
            if not br.allow():
                shed_err = shed_err or RequestRejected(
                    f"replica {m.idx} circuit breaker open")
                continue
            rpc_timeout = self._client_timeout if rem is None \
                else min(self._client_timeout, rem + 0.5)
            committed = False
            try:
                # no span across the yields (a span must not stay open
                # while the generator is suspended) — the client's wire
                # key already carries the active context to the replica
                with self._conn(m) as cli:
                    it = cli.generate(
                        prompt, max_new_tokens=max_new_tokens,
                        deadline_ms=rem * 1e3 if rem is not None else None,
                        priority=priority, temperature=temperature,
                        rpc_timeout=rpc_timeout)
                    try:
                        first = next(it)
                    except StopIteration:
                        br.success()  # empty stream is still an answer
                        m.rpcs += 1
                        return
                    br.success()
                    m.rpcs += 1
                    committed = True
                    obs.trace.event("fleet.route_stream", replica=m.idx,
                                    priority=priority)
                    yield first
                    yield from it
                    return
            except (RequestRejected, Draining) as e:
                br.success()  # an answering replica is a healthy replica
                m.sheds += 1
                shed_err = e
            except DeadlineExceeded:
                # pre-commit: no health verdict, free the probe slot
                # (post-commit success() already closed it — harmless);
                # either way the budget is gone, so no failover
                br.release()
                raise
            except (ServeError, ConnectionError, OSError) as e:
                if committed:
                    # the stream is committed to this replica: surface
                    # the break instead of splicing another generation
                    raise
                if br.failure():
                    obs.inc("fleet.breaker_trips")
                    obs.event("fleet.breaker_trip", replica=m.idx)
                    obs.tail.note(breaker=True)
                m.errors += 1
                m.last_error = f"{type(e).__name__}: {e}"
                hard_err = e
        if hard_err is not None:
            raise ServeError(
                f"all {len(cands)} replicas failed; last: {hard_err}")
        raise shed_err if shed_err is not None \
            else RequestRejected("no replica accepted the stream")

    def ready(self) -> bool:
        return self._gate.is_set() and bool(self._pool.ready_members())

    @property
    def version(self) -> int:
        return self._fleet_version

    def queue_depth(self) -> int:
        return 0  # routing is synchronous; queues live in the replicas

    def stats(self) -> dict:
        _BR_STATE = {"closed": 0, "half_open": 1, "open": 2}
        replicas = {}
        for m in self._pool.members():
            br = self._breaker(m).snapshot()
            replicas[str(m.idx)] = {
                "state": m.state,
                "addr": f"{m.addr[0]}:{m.addr[1]}" if m.addr else None,
                "incarnation": m.incarnation, "restarts": m.restarts,
                "version": m.version, "rpcs": m.rpcs, "errors": m.errors,
                "sheds": m.sheds, "last_error": m.last_error,
                "queue_depth": m.queue_depth,
                "occupancy": round(m.occupancy, 4),
                "breaker": br,
            }
            # numeric breaker state per replica in the exposition
            # (0 closed / 1 half-open / 2 open) — operators and the
            # autoscaler read the router's own verdicts, not a copy.
            # Checked + set under the POOL lock: remove_replica flips the
            # state under that lock before deleting the member's gauges,
            # so this can never resurrect a removed replica's gauge
            with self._pool._lock:
                if m.state not in ("leaving", "removed", "stopped"):
                    obs.set_gauge(f"fleet.replica{m.idx}.breaker_state",
                                  _BR_STATE.get(br["state"], 2))
        open_s = sum(r["breaker"]["open_seconds"]
                     for r in replicas.values())
        # mirrored into the registry so fleet-level SLO math works off the
        # merged metrics snapshot alone (no stats dict in hand)
        obs.set_gauge("fleet.breaker_open_seconds", open_s)
        return {"fleet_version": self._fleet_version,
                "ready_replicas": len(self._pool.ready_members()),
                "failovers": self.failovers, "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "stale_rejected": self.stale_rejected,
                "breaker_trips": sum(b.trips
                                     for b in self._breakers.values()),
                "breaker_open_seconds": round(open_s, 4),
                "inflight": self._inflight,
                "intake_paused": not self._gate.is_set(),
                "hedge_ms": self.hedge_ms,
                "replicas": replicas}

    def collect_telemetry(self, drain: bool = True,
                          retain: Optional[list] = None) -> list:
        """Pull every ready replica's telemetry part over ``OP_TELEMETRY``
        (drained rings: repeated collections are increments). A replica
        that fails mid-pull is skipped and counted — the fleet's timeline
        must assemble from whoever is alive; the dead leave their JSONL
        evidence instead.

        ``retain`` fans the tail-retention verdict list out to every
        replica: a replica's briefly-held pending spans for a retained
        trace promote into the very part this collection returns — the
        fleet keeps or drops a trace as a unit."""
        parts = []
        for m in self._pool.ready_members():
            try:
                with self._conn(m) as cli:
                    tel = cli.telemetry(drain=drain, retained=retain)
                for p in tel.get("parts", []):
                    p["role"] = f"replica{m.idx}"
                    parts.append(p)
            except (ServeError, ConnectionError, OSError) as e:
                obs.inc("fleet.telemetry_errors")
                obs.event("fleet.telemetry_error", replica=m.idx,
                          error=str(e)[:160])
        return parts

    def drain(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=remaining)
        return True

    def close(self, timeout: float = 30.0) -> None:
        self.drain(timeout)
        with self._lock:
            for pool in self._pools.values():
                pool.close()
            self._pools.clear()

    def _wait_inflight_zero(self, timeout: float) -> bool:
        return self.drain(timeout)

    # -- fleet-atomic reload -------------------------------------------
    def reload(self, path: str, epoch: Optional[int] = None,
               prefix: str = "ckpt") -> int:
        """Two-phase fleet flip: every replica serves the new generation or
        none does (see the module docstring for the atomicity argument).
        Returns the new fleet version."""
        with self._reload_lock:
            members = self._pool.ready_members()
            if not members:
                raise ServeError("no ready replicas to reload")
            new_version = self._fleet_version + 1
            self._reload_epoch += 1
            token = (self._controller_id, self._reload_epoch)
            with obs.trace.span("fleet.reload", version=new_version,
                                replicas=len(members)):
                self._prepare_all(members, token, path, epoch, prefix,
                                  new_version)
                kill_point("fleet:post_prepare")
                # holding _reload_lock across the flip drain is the POINT
                # (reloads are serialized fleet-wide) and the drain is
                # bounded by flip_timeout
                self._commit_all(members, token, path, epoch, prefix,
                                 new_version)  # lint: disable=blocking-call-under-lock
            obs.inc("fleet.reloads")
            obs.event("fleet.reload", version=new_version)
            return new_version

    def _prepare_all(self, members, token, path, epoch, prefix, version):
        """Phase one — a barrier: every ready replica stages the new
        generation (all fallible work happens here) or the whole reload
        aborts and nothing changed anywhere."""
        prepared = []
        try:
            for m in members:
                with self._conn(m) as cli:
                    cli.prepare_reload(path, epoch=epoch, prefix=prefix,
                                       version=version, token=token,
                                       retries=3)
                prepared.append(m)
        except Exception as e:
            for p in prepared:
                try:
                    with self._conn(p) as cli:
                        cli.abort_reload(token)
                except Exception:  # noqa: BLE001 — rollback is best-effort
                    pass
            raise ServeError(f"fleet reload prepare failed "
                             f"(rolled back on {len(prepared)} replicas): "
                             f"{type(e).__name__}: {e}")

    def _commit_all(self, members, token, path, epoch, prefix, version):
        """Phase two — pause intake, drain in-flight, flip every live
        replica (a pure pointer swap), stamp the fleet version. A replica
        that dies mid-phase serves nothing and restarts onto the committed
        target; one that errors while alive is resynced and version-gated
        until it is."""
        self._gate.clear()
        try:
            if not self._wait_inflight_zero(self._flip_timeout):
                for m in members:
                    try:
                        with self._conn(m) as cli:
                            cli.abort_reload(token)
                    except Exception:  # noqa: BLE001 — best-effort rollback
                        pass
                raise ServeError(
                    f"fleet reload: in-flight requests did not drain within "
                    f"{self._flip_timeout}s flip window; aborted (still "
                    f"serving v{self._fleet_version} everywhere)")
            # commit point: from here the reload WILL happen. Restarts must
            # land on the new generation even if every commit RPC dies.
            self._pool.set_target(path, epoch, prefix, version)
            for m in members:
                kill_point("fleet:pre_commit")
                if self._commit_hook is not None:
                    self._commit_hook(m)  # chaos injection for tests
                try:
                    with self._conn(m) as cli:
                        cli.commit_reload(token, retries=3)
                    m.version = version
                except (ServeError, ConnectionError, OSError) as e:
                    # dead mid-flip → serves nothing; alive-but-errored →
                    # resynced by the pool and version-gated meanwhile
                    obs.inc("fleet.commit_failures")
                    obs.event("fleet.commit_failure", replica=m.idx,
                              error=str(e)[:160])
                    m.last_error = f"commit: {type(e).__name__}: {e}"
                    self._pool.request_resync(m.idx)
            self._fleet_version = version
        finally:
            self._gate.set()


# ---------------------------------------------------------------------------
# socket front
# ---------------------------------------------------------------------------

class FleetServer(ServeServer):
    """One socket endpoint for the whole fleet: the Router is mounted as
    the server's batcher, so INFER routes with failover/hedging, READY
    reflects live replicas + the fleet version, RELOAD is the fleet-atomic
    two-phase flip, and STATS returns per-replica breaker/failover state —
    all on the unchanged serve wire protocol (``ServeClient``,
    ``serve_bench``, and the chaos rule table work as-is)."""

    def __init__(self, router: Router, host: str = "127.0.0.1",
                 port: int = 0, *, default_timeout: float = 30.0):
        super().__init__(engine=None, batcher=router, host=host, port=port,
                         default_timeout=default_timeout)
        self._router = router

    def reload(self, path: str, epoch: Optional[int] = None,
               prefix: str = "ckpt") -> int:
        return self._router.reload(path, epoch=epoch, prefix=prefix)

    def telemetry(self, drain: bool = True,
                  retained: Optional[list] = None) -> dict:
        """The fleet collection plane: one ``OP_TELEMETRY`` against the
        front returns the front's own part (client rpc + fleet.route
        spans, router metrics, breaker state) PLUS one part per live
        replica — everything ``obs.export.merge_chrome_parts`` needs for
        the single merged timeline, and ``parts_to_prometheus`` for the
        pid/role-labeled exposition.

        Tail retention: the caller's verdict list (client-rooted traces)
        resolves this process's pending buffer, then the union of those
        ids and the front's OWN recent verdicts fans out with the replica
        pulls — one collection settles the whole fleet's held spans for
        every retained trace.

        Parts are deduped by pid: an in-process LocalReplica fleet shares
        ONE tracer ring and registry with the front, so its replica parts
        would be copies (peek) or already-claimed spans (drain) — only a
        real subprocess fleet contributes distinct lanes."""
        if retained:
            obs.tail.resolve(retained)
        fan_out = sorted(set(list(retained or ())
                             + obs.tail.retained_ids()))
        # stats FIRST: Router.stats() refreshes the breaker-open-time
        # gauge, which must land in the snapshot the part takes — the
        # other order would export the gauge one collection stale
        st = self.stats(include_metrics=False)
        front = obs.telemetry_part(drain=drain, role="fleet")
        front["stats"] = st
        parts, seen = [front], {front["pid"]}
        for p in self._router.collect_telemetry(drain=drain,
                                                retain=fan_out or None):
            if p.get("pid") in seen:
                continue
            seen.add(p.get("pid"))
            parts.append(p)
        return {"parts": parts}
