"""``mxnet_tpu.serve`` — compiled inference with dynamic batching and
SLO-aware scheduling (docs/SERVING.md).

The inference half of the framework: take any trained artifact and turn it
into a concurrent, low-latency endpoint.

Layers
------
- :class:`~mxnet_tpu.serve.engine.InferenceEngine` — one compiled XLA
  program per bucketed input shape, parameters device-resident and
  hot-reloadable (``engine.py``);
- :class:`~mxnet_tpu.serve.batcher.DynamicBatcher` — micro-batching with
  deadlines, priority lanes, and load shedding (``batcher.py``);
- :class:`~mxnet_tpu.serve.server.ServeServer` /
  :class:`~mxnet_tpu.serve.client.ServeClient` — a threaded socket front
  end on the parameter-server wire format, with health/readiness probes,
  draining shutdown, and hot model reload (``server.py`` / ``client.py``);
- :class:`~mxnet_tpu.serve.fleet.ReplicaPool` /
  :class:`~mxnet_tpu.serve.fleet.Router` /
  :class:`~mxnet_tpu.serve.fleet.FleetServer` — supervised replica fleet:
  restart-with-backoff, per-replica circuit breakers, failover + hedging,
  fleet-atomic two-phase hot reload, and elastic membership (quarantine →
  activate-at-boundary joins, drain-then-leave) with data-parallel replica
  groups placed on mesh slices (``ReplicaPool.sharded``; ``fleet.py``,
  docs/ROBUSTNESS.md "Serving fleet");
- :class:`~mxnet_tpu.serve.autoscale.Autoscaler` /
  :class:`~mxnet_tpu.serve.autoscale.AutoscalePolicy` — SLO-driven elastic
  autoscaling: windowed error-budget burn + queue-depth/occupancy signals
  grow and shrink the pool live (``autoscale.py``, docs/SERVING.md).

Typical session::

    import mxnet_tpu as mx

    engine = mx.serve.load("model/ckpt", epoch=3)        # any artifact kind
    engine.warmup((3, 32, 32))                           # compile buckets
    server = mx.serve.ServeServer(engine, port=9191)
    server.start()
    ...
    client = mx.serve.ServeClient("localhost", 9191)
    probs = client.infer(batch, deadline_ms=50, priority=0)

``load`` understands three artifact kinds:

1. a ``Module.save_checkpoint`` prefix (``prefix-symbol.json`` +
   ``prefix-NNNN.params``) — ``epoch`` picks the file (default: newest);
2. a ``HybridBlock.export`` path whose descriptor embeds the traced graph
   (exports made by this version do automatically);
3. a ``checkpoint/`` manager directory (crash-safe training checkpoints) —
   pass ``symbol=`` since training checkpoints store only tensors.

``quantize_model`` int8 rewrites serve through the same engine: construct
:class:`InferenceEngine` directly with ``(qsym, qarg, aux)``.
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import Optional, Tuple

from .batcher import DynamicBatcher, Future
from .engine import (DeadlineExceeded, Draining, InferenceEngine,
                     RequestRejected, ServeError, default_buckets)
from .server import ServeServer
from .client import ServeClient
from .fleet import (CircuitBreaker, FleetServer, LocalReplica, ProcReplica,
                    ReplicaPool, Router)
from .autoscale import Autoscaler, AutoscalePolicy
from .kvcache import PageLeakError, PagePool, PagesExhausted
from .decode import DecodeEngine, DecodeScheduler, default_decode_buckets

__all__ = ["load", "load_params", "ship_programs", "programs_dir_for",
           "InferenceEngine", "DynamicBatcher",
           "Future", "ServeServer", "ServeClient", "ServeError",
           "RequestRejected", "DeadlineExceeded", "Draining",
           "default_buckets", "CircuitBreaker", "FleetServer",
           "LocalReplica", "ProcReplica", "ReplicaPool", "Router",
           "Autoscaler", "AutoscalePolicy",
           "DecodeEngine", "DecodeScheduler", "default_decode_buckets",
           "PagePool", "PageLeakError", "PagesExhausted"]


def _newest_epoch(path: str) -> int:
    pat = re.compile(re.escape(os.path.basename(path))
                     + r"-(\d{4,})\.params$")
    epochs = [int(m.group(1)) for f in glob.glob(f"{path}-*.params")
              for m in [pat.match(os.path.basename(f))] if m]
    if not epochs:
        raise ServeError(f"no {path}-NNNN.params files found")
    return max(epochs)


def _split_arg_aux(params: dict, symbol) -> Tuple[dict, dict]:
    aux_names = set(symbol.list_auxiliary_states())
    arg = {k: v for k, v in params.items() if k not in aux_names}
    aux = {k: v for k, v in params.items() if k in aux_names}
    return arg, aux


def _load_artifact(path: str, epoch: Optional[int], symbol,
                   prefix: str):
    """Resolve an artifact to ``(symbol, arg_params, aux_params)``."""
    from ..symbol import load_json as sym_load_json

    if os.path.isdir(path):
        # checkpoint-manager directory (crash-safe training checkpoints)
        from ..checkpoint import CheckpointManager

        if symbol is None:
            raise ServeError(
                f"{path!r} is a checkpoint directory; training checkpoints "
                "store tensors only — pass symbol= (the trained graph)")
        mgr = CheckpointManager(path, prefix=prefix)
        state = mgr.load(epoch) if epoch is not None else mgr.load_latest()
        if state is None:
            raise ServeError(f"no valid checkpoint found in {path!r}")
        return symbol, state.arg_params(), state.aux_params()

    sym_file = f"{path}-symbol.json"
    if not os.path.exists(sym_file):
        raise ServeError(
            f"{path!r} is neither a checkpoint directory nor a checkpoint "
            f"prefix ({sym_file} missing)")
    with open(sym_file) as f:
        desc = json.load(f)
    if isinstance(desc, dict) and "nodes" in desc:
        # Module.save_checkpoint artifact: graph json + arg:/aux: params
        from ..model import load_checkpoint

        if epoch is None:
            epoch = _newest_epoch(path)
        sym, arg, aux = load_checkpoint(path, epoch)
        return (symbol or sym), arg, aux
    if isinstance(desc, dict) and desc.get("format") == "mxnet_tpu-hybrid":
        # HybridBlock.export artifact: descriptor + save_parameters file
        from ..ndarray import load as nd_load

        if symbol is None:
            if "symbol" not in desc:
                raise ServeError(
                    f"{sym_file} has no embedded graph (exported by an "
                    "older version, or the block does not trace "
                    "symbolically); re-export, or pass symbol=")
            symbol = sym_load_json(desc["symbol"])
        if epoch is None:
            epoch = _newest_epoch(path)
        loaded = nd_load(f"{path}-{epoch:04d}.params")
        # save_parameters keys are attribute paths; the embedded map takes
        # them to the graph's variable names
        param_map = desc.get("param_map") or {}
        renamed = {param_map.get(k, k): v for k, v in loaded.items()}
        arg, aux = _split_arg_aux(renamed, symbol)
        return symbol, arg, aux
    raise ServeError(f"unrecognized artifact descriptor {sym_file}")


def programs_dir_for(path: str) -> str:
    """The conventional location of an artifact's shipped program-cache
    payload (``mxnet_tpu/progcache.py``): ``<dir>/programs`` for a
    checkpoint-manager directory, ``<prefix>-programs`` for the file
    kinds. ``ship_programs`` writes it; ``load`` auto-discovers it."""
    if os.path.isdir(path):
        return os.path.join(path, "programs")
    return f"{path}-programs"


def ship_programs(engine: InferenceEngine, path: str) -> int:
    """Export ``engine``'s compiled bucket executables as the artifact's
    ``programs/`` payload, so every process that ``load``s the artifact
    warms by deserializing instead of compiling (O(load) cold start —
    docs/PERFORMANCE.md "Program cache and cold start"). For a gluon
    export, the descriptor json additionally records the payload dirname.
    Returns the number of programs written."""
    d = programs_dir_for(path)
    n = engine.save_programs(d)
    if n == 0:
        # a payload dir with nothing in it (backend refused every export)
        # must not exist: load() would auto-discover it and let the empty
        # dir override a populated env-armed cache
        try:
            os.rmdir(d)
        except OSError:
            pass  # non-empty (foreign files) or already gone — leave it
    sym_file = f"{path}-symbol.json"
    if n and os.path.exists(sym_file):
        try:
            with open(sym_file) as f:
                desc = json.load(f)
            if isinstance(desc, dict) \
                    and desc.get("format") == "mxnet_tpu-hybrid":
                from ..checkpoint.atomic import atomic_write_json

                desc["programs"] = os.path.basename(d)
                atomic_write_json(sym_file, desc)
        except (OSError, ValueError):
            pass  # the payload still loads by the dir convention
    return n


def _discover_programs(path: str) -> Optional[str]:
    d = programs_dir_for(path)
    try:
        # only a payload with at least one entry beats the env-armed
        # cache — an empty/foreign dir is no payload at all
        if any(e.endswith(".mxprog") for e in os.listdir(d)):
            return d
    except OSError:
        pass
    return None


def load(path: str, epoch: Optional[int] = None, symbol=None, *,
         prefix: str = "ckpt", **engine_kwargs) -> InferenceEngine:
    """Build an :class:`InferenceEngine` from any trained artifact (see
    the module docstring for the three artifact kinds). Extra kwargs go to
    the engine (``max_batch_size``, ``buckets``, ``data_names``,
    ``lint``). An artifact shipping a ``programs/`` payload
    (:func:`ship_programs`) becomes the engine's program cache — its
    buckets warm from disk."""
    sym, arg, aux = _load_artifact(path, epoch, symbol, prefix)
    if "progcache_dir" not in engine_kwargs:
        shipped = _discover_programs(path)
        if shipped is not None:
            engine_kwargs["progcache_dir"] = shipped
    return InferenceEngine(sym, arg, aux, **engine_kwargs)


def load_params(path: str, epoch: Optional[int] = None, *,
                prefix: str = "ckpt", symbol=None) -> Tuple[dict, dict]:
    """Load just ``(arg_params, aux_params)`` from an artifact — the hot
    model-reload path (``ServeServer.reload`` / ``engine.reload``)."""
    if os.path.isdir(path):
        from ..checkpoint import CheckpointManager

        mgr = CheckpointManager(path, prefix=prefix)
        state = mgr.load(epoch) if epoch is not None else mgr.load_latest()
        if state is None:
            raise ServeError(f"no valid checkpoint found in {path!r}")
        return state.arg_params(), state.aux_params()
    sym, arg, aux = _load_artifact(path, epoch, symbol, prefix)
    return arg, aux
