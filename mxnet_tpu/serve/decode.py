"""Autoregressive decode engine: paged KV cache + continuous batching.

The generation counterpart of ``serve/engine.py``. Two halves:

:class:`DecodeEngine` — the device half. Exactly TWO compiled program
shapes serve every generation:

- a **bucketed prefill program** (one trace per prompt bucket; buckets
  are powers of two in *positions*, always multiples of the page size):
  full causal forward over one padded prompt, per-layer K/V scattered
  into the page pool at the sequence's page ids, first token sampled
  on-device;
- a **single decode-step program** (one trace, period): one new position
  for every slot of the fixed continuous batch — embed, per-layer
  paged-KV write + paged attention (ops/flash_attention.decode_attention),
  LM head, on-device greedy/temperature sampling.

Growing a sequence never changes a program shape: the KV pool is one
fixed array ``(pages, layers, 2, page_size, heads, head_dim)`` and growth
is a host-side page-table edit (serve/kvcache.py) — the engine.py
pad-and-slice idiom applied to the time axis. Program accounting mirrors
InferenceEngine exactly: ``compile_log`` entries, progcache get/put so a
scaled-out replica deserializes instead of compiling
(``decode.cache_hit`` vs ``decode.compile``), and
analysis/trace.py::check_decode_engine proves the
``len(prompt_buckets) + 1`` program bound.

:class:`DecodeScheduler` — the host half, beside serve/batcher.py but
token-granular: requests **join and leave the running decode batch at
step boundaries** instead of waiting for a drain. Priority lanes and the
batcher's shed discipline (queue watermark → 429, dead-on-arrival and
mid-generation deadline → DeadlineExceeded, draining → Draining) carry
over; page exhaustion sheds the newest admission rather than stalling
the batch. Per-step ``decode.occupancy`` gauge, ``decode.kv_pages_used``
from the pool, per-token spans onto the request's trace context.

Wire integration: serve/server.py streams tokens per
``OP_INFER_STREAM`` (wire.py codes 44-47); ``ServeClient.generate()``
and ``Router.generate`` consume the same iterator protocol this module's
``DecodeScheduler.generate`` exposes.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import copytrack, obs, tsan
from ..obs import context as obs_context
from ..obs._env import env_float, env_int
from .engine import DeadlineExceeded, Draining, RequestRejected, ServeError
from .kvcache import SCRATCH_PAGE, PagePool, PagesExhausted, pages_for

__all__ = ["DecodeEngine", "DecodeScheduler", "StreamHandle",
           "default_decode_buckets"]


def default_decode_buckets(max_prompt: int, page_size: int) -> List[int]:
    """Power-of-two prompt buckets, every one a multiple of the page size
    (so a bucketed prefill always fills whole pages): page 16, max 100 →
    [16, 32, 64, 112]."""
    max_prompt = int(max_prompt)
    page_size = int(page_size)
    if max_prompt < 1:
        raise ValueError("max_prompt must be >= 1")
    cap = pages_for(max_prompt, page_size) * page_size
    out = []
    b = page_size
    while b < cap:
        out.append(b)
        b *= 2
    out.append(cap)
    return out


class DecodeEngine:
    """Paged-KV generation engine around a :class:`TransformerLM`.

    Parameters
    ----------
    lm : TransformerLM or dict
        An initialized LM block (config/params extracted via
        models/transformer.decode_config/decode_params), or the config
        dict itself when ``params`` is given.
    params : dict, optional
        Pre-extracted param dict (host numpy) when ``lm`` is a config.
    slots : int
        Width of the continuous decode batch — THE shape of the single
        decode-step program. Default ``MXNET_DECODE_SLOTS`` (8).
    page_size : int
        KV positions per page. Default ``MXNET_DECODE_PAGE_SIZE`` (16).
    num_pages : int
        Pool size (page 0 is reserved scratch). Default
        ``MXNET_DECODE_PAGES`` (64).
    prompt_buckets : list of int, optional
        Prefill pad targets; defaults to ``default_decode_buckets`` over
        the model's max_length (capped at the pool's capacity).
    progcache_dir : str, optional
        Explicit persistent program cache; defaults to the process-wide
        ``progcache.cache()`` (``MXNET_PROGCACHE=1``).
    """

    def __init__(self, lm, params=None, *, slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 prompt_buckets: Optional[List[int]] = None,
                 progcache_dir: Optional[str] = None):
        from ..models.transformer import decode_config, decode_params

        if params is None:
            self.cfg = decode_config(lm)
            params = decode_params(lm)
        else:
            self.cfg = dict(lm)
        self.slots = int(slots if slots is not None
                         else env_int("MXNET_DECODE_SLOTS", 8))
        self.page_size = int(page_size if page_size is not None
                             else env_int("MXNET_DECODE_PAGE_SIZE", 16))
        self.num_pages = int(num_pages if num_pages is not None
                             else env_int("MXNET_DECODE_PAGES", 64))
        self.max_length = int(self.cfg["max_length"])
        # page-table width of the step program: enough for a full-context
        # sequence, but never more than the pool could back
        self.max_pages = min(pages_for(self.max_length, self.page_size),
                             self.num_pages - 1)
        max_prompt = min(self.max_length,
                         (self.num_pages - 1) * self.page_size)
        if prompt_buckets is None:
            prompt_buckets = default_decode_buckets(max_prompt,
                                                    self.page_size)
        buckets = sorted({int(b) for b in prompt_buckets})
        for b in buckets:
            if b % self.page_size or b < 1 or b > max_prompt:
                raise ValueError(
                    f"prompt bucket {b} must be a positive multiple of "
                    f"page_size={self.page_size} and <= {max_prompt}")
        self.buckets = buckets
        self.pool = PagePool(self.num_pages, self.page_size)

        import jax
        import jax.numpy as jnp

        self._params = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a, jnp.float32), params)
        self._param_avals = tuple(
            (tuple(a.shape), str(a.dtype))
            for a in jax.tree_util.tree_leaves(self._params))
        cfg = self.cfg
        self.kv = jnp.zeros(
            (self.num_pages, cfg["layers"], 2, self.page_size,
             cfg["heads"], cfg["head_dim"]), jnp.float32)

        # donating the pool buffer makes the per-step KV write in-place on
        # TPU; CPU/GPU test backends would only warn about it
        donate = (1,) if jax.default_backend() == "tpu" else ()
        self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=donate)
        self._step_jit = jax.jit(self._step_fn, donate_argnums=donate)

        # program accounting — mirrors InferenceEngine so the TraceLinter
        # and the coldstart idiom read both the same way
        self._programs: Dict[tuple, int] = {}
        self._aot: Dict[tuple, object] = {}
        self._sig_key: Dict[tuple, object] = {}
        self.compile_log: List[dict] = []
        self.cache_hits = 0
        self.exec_count = 0
        self._stat_lock = tsan.lock("serve.decode.stats")

        from .. import progcache as _progcache

        self._progcache = (_progcache.ProgramCache(progcache_dir)
                           if progcache_dir else _progcache.cache())
        self._key_statics = (
            tuple(sorted(self.cfg.items())), self.slots, self.page_size,
            self.num_pages, self.max_pages, tuple(self.buckets),
            self._param_avals)

    # -- pure device programs ------------------------------------------

    def _prefill_fn(self, params, kv, tokens, length, page_ids, seed, temp):
        """One padded prompt (1, S) → KV pages written, first token.
        S is the bucket (multiple of page_size); ``page_ids``
        (S // page_size,) are the sequence's pages in position order.
        Pad positions scatter garbage K/V — masked by ``length`` until
        each slot is overwritten by a decode step."""
        import jax
        import jax.numpy as jnp

        from ..models.transformer import lm_prefill, sample_token

        logits, k, v = lm_prefill(self.cfg, params, tokens)
        s = tokens.shape[1]
        n = s // self.page_size
        cfg = self.cfg

        def blocks(x):  # (L, 1, S, H, D) → (n, L, page, H, D)
            x = jnp.squeeze(x, 1).reshape(
                cfg["layers"], n, self.page_size, cfg["heads"],
                cfg["head_dim"])
            return jnp.transpose(x, (1, 0, 2, 3, 4))

        kv = kv.at[page_ids, :, 0].set(blocks(k))
        kv = kv.at[page_ids, :, 1].set(blocks(v))
        last = logits[0, length - 1]
        tok = sample_token(last[None], jax.random.PRNGKey(seed), temp)
        return kv, tok[0]

    def _step_fn(self, params, kv, tokens, positions, page_tables, lengths,
                 seed, temps):
        """One token for every slot. tokens/positions/lengths (B,),
        page_tables (B, max_pages). Inactive slots carry length 0 and a
        scratch page table — their writes land on the scratch page and
        their outputs are garbage the host discards."""
        import jax
        import jax.numpy as jnp

        from ..models.transformer import (_dense, _ln, decode_layer,
                                          sample_token)
        from ..ops.flash_attention import decode_attention

        cfg = self.cfg
        rows = jnp.arange(self.slots)
        pids = page_tables[rows, positions // self.page_size]
        offs = positions % self.page_size
        x = params["embed"][tokens] + params["pos"][positions]
        for i, lp in enumerate(params["layers"]):
            def attend(q, k_new, v_new, _i=i):
                nonlocal kv
                kv = kv.at[pids, _i, 0, offs].set(k_new)
                kv = kv.at[pids, _i, 1, offs].set(v_new)
                return decode_attention(q, kv[:, _i, 0], kv[:, _i, 1],
                                        page_tables, lengths)

            x, _, _ = decode_layer(cfg, lp, x, attend)
        x = _ln(x, params["final_g"], params["final_b"])
        logits = _dense(x, params["dec_w"], params["dec_b"])
        toks = sample_token(logits, jax.random.PRNGKey(seed), temps)
        return kv, toks

    # -- program accounting (the engine.py compile path, decode-keyed) --

    def _program_key(self, sig, label: str):
        pk = self._sig_key.get(sig)
        if pk is None:
            from .. import progcache as _progcache

            pk = _progcache.program_key("decode", label,
                                        (self._key_statics, sig))
            self._sig_key[sig] = pk
        return pk

    def _execute(self, kind: str, label: str, jitted, args):
        """Run one program call with full accounting: compile_log entry +
        progcache get/put on a fresh signature, ``decode.*`` metrics, and
        the pool array swap. Returns the sampled token(s) on host."""
        import jax

        sig = (kind,) + tuple(
            (tuple(np.shape(a)), str(np.asarray(a).dtype)) for a in args)
        rec = obs.enabled()
        t0 = time.monotonic()
        is_compile = sig not in self._programs
        cache_hit = False
        call_args = (self._params, self.kv) + tuple(args)
        if is_compile:
            entry = {"sig": sig, "kind": kind, "label": label,
                     "param_avals": self._param_avals}
            pc = self._progcache
            pk = None
            if pc is not None:
                pk = self._program_key(sig, label)
                entry["program_key"] = pk.digest
                cached = pc.get(pk)
                if cached is not None:
                    cache_hit = True
                    self._aot[sig] = cached.executable
                    cost = obs.device.adopt_cached_cost(pk, cached.meta)
                    if cost:
                        entry.update(cost)
            entry["cache_hit"] = cache_hit
            if not cache_hit and (obs.device.active() or pc is not None):
                if obs.device.active():
                    compiled, cost = obs.device.capture(
                        jitted, call_args, site="decode", label=label,
                        key=pk)
                else:
                    from .. import progcache as _progcache

                    compiled = _progcache.aot_compile(jitted, call_args)
                    cost = (obs.device.analyze_compiled(compiled)
                            if compiled is not None else None)
                if compiled is not None:
                    self._aot[sig] = compiled
                    if pc is not None:
                        pc.put(pk, compiled,
                               meta=dict(cost or {}, kind=kind))
                if cost:
                    entry.update(cost)
            self.compile_log.append(entry)
            if cache_hit:
                with self._stat_lock:
                    self.cache_hits += 1
        fn = self._aot.get(sig, jitted)
        with obs.trace.span("decode.execute", kind=kind, label=label,
                            compile=is_compile, cache_hit=cache_hit):
            new_kv, toks = fn(*call_args)
            self.kv = new_kv
            # the step's sampled tokens ARE the wire payload — this d2h is
            # the one accounted sync of the decode hot path
            copytrack.TRACKER.host_sync("serve.decode.device_get")
            host = np.asarray(jax.device_get(toks))  # lint: disable=host-sync-on-hot-path
        if rec:
            dt = time.monotonic() - t0
            if is_compile and not cache_hit:
                obs.inc("decode.compile")
                obs.observe("decode.compile_seconds", dt)
            elif cache_hit:
                obs.inc("decode.cache_hit")
                obs.observe("decode.deserialize_seconds", dt)
            else:
                obs.observe("decode.execute_seconds", dt)
        with self._stat_lock:
            self._programs[sig] = self._programs.get(sig, 0) + 1
            self.exec_count += 1
        return host

    # -- host-facing calls ---------------------------------------------

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if b >= prompt_len:
                return b
        raise RequestRejected(
            f"prompt length {prompt_len} exceeds max bucket "
            f"{self.buckets[-1]}")

    def prefill(self, tokens: np.ndarray, page_ids: List[int], *,
                temperature: float = 0.0, seed: int = 0) -> int:
        """Prefill one prompt into its pages; returns the first sampled
        token. ``tokens`` is the unpadded 1-D prompt; ``page_ids`` must
        cover its bucket (``bucket_for(len) // page_size`` pages)."""
        tokens = np.asarray(tokens, np.uint32).astype(np.int32)
        n = int(tokens.shape[0])
        bucket = self.bucket_for(n)
        if len(page_ids) != bucket // self.page_size:
            raise ServeError(
                f"prefill needs {bucket // self.page_size} pages for "
                f"bucket {bucket}, got {len(page_ids)}")
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = tokens
        out = self._execute(
            "prefill", f"prefill{bucket}", self._prefill_jit,
            (padded, np.int32(n), np.asarray(page_ids, np.int32),
             np.uint32(seed), np.float32(temperature)))
        return int(out)

    def step(self, tokens, positions, page_tables, lengths, temps, *,
             seed: int = 0) -> np.ndarray:
        """One continuous-batch decode step; returns (slots,) int32
        sampled tokens (garbage at inactive rows, i.e. lengths == 0)."""
        return self._execute(
            "step", "step", self._step_jit,
            (np.asarray(tokens, np.int32), np.asarray(positions, np.int32),
             np.asarray(page_tables, np.int32),
             np.asarray(lengths, np.int32), np.uint32(seed),
             np.asarray(temps, np.float32)))

    def warmup(self) -> int:
        """Compile (or progcache-load) every prefill bucket plus the step
        program before traffic. Warmup calls write only the reserved
        scratch page. Returns the number of fresh XLA compiles."""
        before = sum(1 for e in self.compile_log if not e["cache_hit"])
        scratch_tables = np.full((self.slots, self.max_pages), SCRATCH_PAGE,
                                 np.int32)
        for b in self.buckets:
            self.prefill(np.zeros((b,), np.int32),
                         [SCRATCH_PAGE] * (b // self.page_size))
        self.step(np.zeros((self.slots,), np.int32),
                  np.zeros((self.slots,), np.int32), scratch_tables,
                  np.zeros((self.slots,), np.int32),
                  np.zeros((self.slots,), np.float32))
        return sum(1 for e in self.compile_log if not e["cache_hit"]) - before

    def stats(self) -> dict:
        with self._stat_lock:
            out = {
                "slots": self.slots,
                "page_size": self.page_size,
                "buckets": list(self.buckets),
                "num_programs": len(self._programs),
                "executions": self.exec_count,
                "compiles": len(self.compile_log),
                "cache_hits": self.cache_hits,
                "programs": {repr(k): v for k, v in self._programs.items()},
            }
        out["pool"] = self.pool.stats()
        if self._progcache is not None:
            out["progcache"] = dict(self._progcache.stats,
                                    dir=self._progcache.root)
        return out


# ---------------------------------------------------------------------------
# Continuous batching
# ---------------------------------------------------------------------------


class StreamHandle:
    """Client half of one generation: a bounded event queue the scheduler
    feeds and ``generate`` drains. Events: ("token", tok, index),
    ("end", reason, n_tokens), ("error", exc). The queue is sized so the
    scheduler can always emit a full generation without blocking —
    backpressure past that cancels the stream instead of stalling the
    shared decode batch."""

    def __init__(self, capacity: int):
        self._q: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._cancelled = threading.Event()

    def cancel(self) -> None:
        """Ask the scheduler to retire this generation at the next step
        boundary (its pages are reclaimed there)."""
        self._cancelled.set()

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def _emit(self, ev) -> bool:
        try:
            self._q.put_nowait(ev)
            return True
        except queue.Full:
            return False

    def get(self, timeout: float):
        return self._q.get(timeout=timeout)


class _Gen:
    """One generation's scheduler-side state."""

    __slots__ = ("seq", "tokens", "prompt_len", "max_new", "deadline",
                 "priority", "temperature", "ctx", "handle", "produced",
                 "last_token", "t_submit", "t_admit", "seed")

    def __init__(self, seq, tokens, max_new, deadline, priority,
                 temperature, handle, seed):
        self.seq = seq
        self.tokens = tokens
        self.prompt_len = int(tokens.shape[0])
        self.max_new = max_new
        self.deadline = deadline
        self.priority = priority
        self.temperature = temperature
        self.ctx = obs_context.current()
        self.handle = handle
        self.produced = 0
        self.last_token = -1
        self.t_submit = time.monotonic()
        self.t_admit = 0.0
        self.seed = seed


class DecodeScheduler:
    """Token-level continuous batching over a :class:`DecodeEngine`.

    A single scheduler thread owns the engine: each loop iteration is one
    ``step()`` — admit queued requests into free slots (prefill at the
    step boundary), run ONE decode-step program over every active slot,
    distribute the sampled tokens, retire finished/cancelled/expired
    generations and free their pages. Requests therefore join and leave
    the running batch between steps, never mid-program.
    """

    def __init__(self, engine: DecodeEngine, *, max_queue: int = 64,
                 lanes: int = 2, default_timeout: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 max_new_tokens: Optional[int] = None):
        self.engine = engine
        self.max_queue = int(max_queue)
        self.default_timeout = float(
            default_timeout if default_timeout is not None
            else env_float("MXNET_DECODE_TIMEOUT", 30.0))
        self.eos_id = eos_id
        self.max_new_tokens = int(
            max_new_tokens if max_new_tokens is not None
            else env_int("MXNET_DECODE_MAX_NEW", 64))
        self._cv = tsan.condition("serve.decode.cv")
        self._lanes: List[List[_Gen]] = [[] for _ in range(int(lanes))]
        self._slots: List[Optional[_Gen]] = [None] * engine.slots
        self._running = True
        self._draining = False
        self._seq = 0
        # shed discipline — the batcher.py aggregate/by-reason invariant:
        # self.shed == sum(shed_by_reason.values())
        self.shed = 0
        self.shed_by_reason = {"queue_full": 0, "deadline": 0,
                               "draining": 0, "pages": 0,
                               "backpressure": 0}
        self.submitted = 0
        self.completed = 0
        self.cancelled = 0
        self.steps = 0
        self.tokens_out = 0
        self._occupancy = 0.0
        self.stopped_clean = True
        self._thread = threading.Thread(target=self._loop,
                                        name="mxnet-decode-sched",
                                        daemon=True)
        self._thread.start()

    # -- admission ------------------------------------------------------

    def _qsize(self) -> int:
        return sum(len(l) for l in self._lanes)

    def _active(self) -> int:
        return sum(1 for g in self._slots if g is not None)

    def _shed(self, why: str, exc: ServeError):
        self.shed += 1
        self.shed_by_reason[why] += 1
        obs.inc(f"decode.shed_{why}")
        obs.tail.note(shed=why)
        raise exc

    def submit(self, tokens, *, max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None, priority: int = 1,
               temperature: float = 0.0,
               seed: int = 0) -> StreamHandle:
        """Queue one generation; returns its :class:`StreamHandle`.
        Sheds synchronously (batcher discipline) when the queue is over
        watermark, the scheduler drains, or the deadline is already
        dead on arrival."""
        arr = np.ascontiguousarray(np.asarray(tokens, np.int64)
                                   .astype(np.int32)).reshape(-1)
        if arr.shape[0] < 1:
            raise RequestRejected("empty prompt")
        self.engine.bucket_for(arr.shape[0])  # rejects over-long prompts
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.max_new_tokens)
        max_new = max(1, min(max_new, self.engine.max_length
                             - arr.shape[0]))
        deadline = (time.monotonic() + deadline_ms / 1000.0
                    if deadline_ms is not None else None)
        lane = max(0, min(int(priority), len(self._lanes) - 1))
        handle = StreamHandle(capacity=max_new + 2)
        with self._cv:
            if not self._running or self._draining:
                self._shed("draining", Draining("decode scheduler draining"))
            if self._qsize() >= self.max_queue:
                self._shed("queue_full", RequestRejected(
                    f"decode queue over watermark ({self.max_queue})"))
            if deadline is not None and time.monotonic() >= deadline:
                self._shed("deadline", DeadlineExceeded(
                    "deadline expired before admission"))
            self._seq += 1
            g = _Gen(self._seq, arr, max_new, deadline, lane, temperature,
                     handle, seed)
            self._lanes[lane].append(g)
            self.submitted += 1
            depth = self._qsize()
            self._cv.notify_all()
        obs.set_gauge("decode.queue_depth", depth)
        return handle

    def generate(self, tokens, *, max_new_tokens: Optional[int] = None,
                 deadline_ms: Optional[float] = None, priority: int = 1,
                 temperature: float = 0.0, seed: int = 0):
        """Yield tokens as the scheduler produces them. Closing the
        generator mid-stream cancels the generation — its KV pages are
        reclaimed at the next step boundary. Raises the batcher's typed
        errors (RequestRejected / DeadlineExceeded / Draining) — possibly
        mid-stream."""
        h = self.submit(tokens, max_new_tokens=max_new_tokens,
                        deadline_ms=deadline_ms, priority=priority,
                        temperature=temperature, seed=seed)
        budget = (deadline_ms / 1000.0 + 5.0 if deadline_ms is not None
                  else self.default_timeout)
        t_end = time.monotonic() + budget
        try:
            while True:
                try:
                    ev = h.get(timeout=max(0.01, t_end - time.monotonic()))
                except queue.Empty:
                    raise ServeError(
                        "decode stream stalled (scheduler wedged?)")
                if ev[0] == "token":
                    yield ev[1]
                elif ev[0] == "end":
                    return
                else:
                    raise ev[1]
        finally:
            h.cancel()
            with self._cv:
                self._cv.notify_all()

    # -- the scheduler loop --------------------------------------------

    def _loop(self):
        try:
            while True:
                with self._cv:
                    while (self._running and self._qsize() == 0
                           and self._active() == 0):
                        self._cv.wait(1.0)
                    if not self._running:
                        return
                self.step()
        finally:
            # whatever ends this thread, nothing may keep pages: retire
            # every resident generation and flush the queue
            self._abort_all(ServeError("decode scheduler stopped"))

    def step(self) -> int:
        """One continuous-batch step: admit → decode → distribute →
        retire. Returns the number of tokens produced. This is the
        decode data plane's hot root (analysis/dataplane.py)."""
        now = time.monotonic()
        joined = self._admit(now)
        active = [(i, g) for i, g in enumerate(self._slots)
                  if g is not None]
        if not active:
            return 0
        eng = self.engine
        tokens = np.zeros((eng.slots,), np.int32)
        positions = np.zeros((eng.slots,), np.int32)
        lengths = np.zeros((eng.slots,), np.int32)
        temps = np.zeros((eng.slots,), np.float32)
        tables = np.full((eng.slots, eng.max_pages), SCRATCH_PAGE,
                         np.int32)
        stepping = []
        for i, g in active:
            pos = g.prompt_len + g.produced - 1
            try:
                table = self._ensure_pages(g, pos)
            except PagesExhausted as e:
                # shedding a RUNNING stream, not a queued one: freeing its
                # pages is what lets the rest of the batch keep stepping
                self.shed += 1
                self.shed_by_reason["pages"] += 1
                obs.inc("decode.shed_pages")
                self._retire(i, g, "pages", error=e)
                continue
            tokens[i] = g.last_token
            positions[i] = pos
            lengths[i] = pos + 1
            temps[i] = g.temperature
            tables[i, :len(table)] = table
            stepping.append((i, g))
        if not stepping:
            return 0
        t0 = time.monotonic()
        out = eng.step(tokens, positions, tables, lengths, temps,
                       seed=self._step_seed())
        dt = time.monotonic() - t0
        left = 0
        now = time.monotonic()
        for i, g in stepping:
            tok = int(out[i])
            g.last_token = tok
            g.produced += 1
            self.tokens_out += 1
            obs.observe("decode.token_seconds", dt)
            if g.ctx is not None and g.ctx.sampled:
                obs.trace.complete("decode.token", t0, dt, ctx=g.ctx,
                                   index=g.produced, slot=i)
            if not g.handle._emit(("token", tok, g.produced)):
                self._retire(i, g, "backpressure", error=RequestRejected(
                    "stream consumer too slow (token buffer full)"))
                left += 1
                continue
            if self._done(g, tok, now):
                left += 1
        self.steps += 1
        occ = len(stepping) / eng.slots
        self._occupancy = (occ if self.steps == 1
                           else 0.7 * self._occupancy + 0.3 * occ)
        obs.set_gauge("decode.occupancy", self._occupancy)
        obs.trace.complete("decode.step", t0, dt, active=len(stepping),
                           joined=joined, left=left)
        return len(stepping)

    def _step_seed(self) -> int:
        # deterministic per step-count: replays reproduce token-for-token
        return (self.steps * 1000003 + 12345) & 0x7FFFFFFF

    def _admit(self, now: float) -> int:
        """Move queued generations into free slots (prefill at the step
        boundary). Page exhaustion leaves the request queued."""
        admitted = []
        with self._cv:
            free = [i for i, g in enumerate(self._slots) if g is None]
            for lane in self._lanes:
                while lane and free:
                    g = lane[0]
                    if g.handle.cancelled():
                        lane.pop(0)
                        self.cancelled += 1
                        g.handle._emit(("end", "cancelled", 0))
                        continue
                    if g.deadline is not None and now >= g.deadline:
                        lane.pop(0)
                        self.shed += 1
                        self.shed_by_reason["deadline"] += 1
                        obs.inc("decode.shed_deadline")
                        g.handle._emit(("error", DeadlineExceeded(
                            "deadline expired in decode queue")))
                        continue
                    bucket = self.engine.bucket_for(g.prompt_len)
                    try:
                        self.engine.pool.alloc(
                            g.seq, bucket // self.engine.page_size)
                    except PagesExhausted:
                        # stays queued: pages free as running streams end
                        free = []
                        break
                    lane.pop(0)
                    slot = free.pop(0)
                    self._slots[slot] = g
                    admitted.append(g)
        for g in admitted:
            g.t_admit = time.monotonic()
            obs.trace.complete("decode.queue_wait", g.t_submit,
                              g.t_admit - g.t_submit, ctx=g.ctx,
                              priority=g.priority)
            tok = self.engine.prefill(
                g.tokens, self.engine.pool.table(g.seq),
                temperature=g.temperature, seed=g.seed)
            g.last_token = tok
            g.produced = 1
            self.tokens_out += 1
            if not g.handle._emit(("token", tok, 1)):
                idx = self._slots.index(g)
                self._retire(idx, g, "backpressure",
                             error=RequestRejected(
                                 "stream consumer too slow"))
                continue
            self._done(g, tok, time.monotonic())
        return len(admitted)

    def _ensure_pages(self, g: _Gen, pos: int) -> List[int]:
        """Grow ``g``'s page table to cover position ``pos`` (at most one
        page per step — step granularity by construction)."""
        pool = self.engine.pool
        table = pool.table(g.seq)
        while len(table) * pool.page_size <= pos:
            pool.alloc(g.seq, 1)
            table = pool.table(g.seq)
        return table

    def _done(self, g: _Gen, tok: int, now: float) -> bool:
        """Post-token retirement checks, in precedence order."""
        idx = self._slots.index(g)
        if self.eos_id is not None and tok == self.eos_id:
            self._retire(idx, g, "eos")
            return True
        if g.produced >= g.max_new:
            self._retire(idx, g, "length")
            return True
        if g.prompt_len + g.produced >= self.engine.max_length:
            self._retire(idx, g, "overflow")
            return True
        if g.deadline is not None and now >= g.deadline:
            self.shed_by_reason["deadline"] += 1
            self.shed += 1
            obs.inc("decode.shed_deadline")
            self._retire(idx, g, "deadline", error=DeadlineExceeded(
                f"deadline expired after {g.produced} tokens"))
            return True
        if g.handle.cancelled():
            self._retire(idx, g, "cancelled")
            return True
        return False

    def _retire(self, slot: int, g: _Gen, reason: str,
                error: Optional[ServeError] = None):
        """Leave the batch: free pages, emit the terminal event, complete
        the request span. EVERY exit path funnels here — the page-leak
        guarantee lives in this one place."""
        self._slots[slot] = None
        self.engine.pool.free(g.seq)
        if reason == "cancelled":
            self.cancelled += 1
        else:
            self.completed += 1
        if error is not None:
            g.handle._emit(("error", error))
        else:
            g.handle._emit(("end", reason, g.produced))
        obs.inc("decode.finished")
        obs.trace.complete(
            "decode.generate", g.t_admit or g.t_submit,
            time.monotonic() - (g.t_admit or g.t_submit), ctx=g.ctx,
            tokens=g.produced, outcome=reason)
        with self._cv:
            self._cv.notify_all()

    def _abort_all(self, exc: ServeError):
        with self._cv:
            queued = [g for lane in self._lanes for g in lane]
            for lane in self._lanes:
                del lane[:]
        for i, g in enumerate(list(self._slots)):
            if g is not None:
                self._retire(i, g, "aborted", error=exc)
        for g in queued:
            g.handle._emit(("error", exc))

    # -- lifecycle ------------------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Refuse new work, let running generations finish. True when
        queue and batch emptied within ``timeout``."""
        deadline = time.monotonic() + timeout
        with self._cv:
            self._draining = True
            self._cv.notify_all()
            while self._qsize() or self._active():
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                self._cv.wait(min(rem, 0.1))
        return True

    def close(self, timeout: float = 5.0):
        """Stop the scheduler thread; resident generations get a
        structured abort and their pages are reclaimed."""
        with self._cv:
            if not self._running:
                return
            self._running = False
            self._draining = True
            self._cv.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():
            self.stopped_clean = False
            obs.inc("decode.scheduler_thread_leaked")

    def ready(self) -> bool:
        return self._running and not self._draining

    @property
    def version(self) -> int:
        return 0

    def stats(self) -> dict:
        with self._cv:
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "cancelled": self.cancelled,
                "shed": self.shed,
                "shed_by_reason": dict(self.shed_by_reason),
                "steps": self.steps,
                "tokens_out": self.tokens_out,
                "queued": self._qsize(),
                "active": self._active(),
                "occupancy": self._occupancy,
                "draining": self._draining,
            }
        out["engine"] = self.engine.stats()
        return out
