"""SLO-driven elastic autoscaling for the serving fleet
(docs/SERVING.md "Mesh-sharded serving and elastic autoscaling").

The reference fleet is sized by hand; this module closes the loop the
"millions of users" north star needs: a controller that watches the SLO
signals the platform already measures — error-budget burn (``obs/slo.py``),
per-replica queue depth and batch occupancy (the ``fleet.replica<i>.*``
gauges the :class:`~mxnet_tpu.serve.fleet.ReplicaPool` supervisor exports)
— and grows or shrinks the pool live. The join/leave *mechanics* are the
``kvstore/elastic.py`` protocol ported to the serve plane and live in
``ReplicaPool``: scale-out is quarantine → resync-to-committed-generation →
activate-at-a-generation-boundary, scale-in is deactivate-at-boundary →
drain → stop (zero requests shed by construction). This module only
decides WHEN.

Two layers, deliberately split so the policy is testable as a pure
function (tests/test_autoscale.py):

- :class:`AutoscalePolicy` — ``decide(signals, now)``: a decision function
  over one signal window. Scale **out** on SLO pressure (windowed burn
  over ``burn_out``, queue depth over ``queue_out``, occupancy over
  ``occupancy_out``), rate-limited by ``cooldown_s``. Scale **in** only
  after ``hysteresis`` *consecutive* quiet windows AND
  ``scale_in_cooldown_s`` since the last action — flapping is a worse
  failure mode than a briefly oversized fleet (every flap pays an XLA
  warmup on the way back up). ``min_replicas``/``max_replicas`` clamp.
- :class:`Autoscaler` — the controller: a thread that assembles the signal
  window each ``interval`` (windowed burn from
  :meth:`~mxnet_tpu.obs.slo.SLOMonitor.burn_window` over metric-snapshot
  deltas, queue/occupancy from pool member records), applies the policy,
  and drives the pool. One join in flight at a time — bring-up includes
  XLA warmup, and deciding again while a replica is mid-join would
  overshoot. Every decision lands in ``self.events`` and the
  ``autoscale.*`` metrics/events, so a load ramp's scale-out is a measured
  artifact (``tools/serve_bench.py --ramp``), not a claim.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from .. import obs
from ..obs.slo import SLOMonitor
from .engine import ServeError

__all__ = ["AutoscalePolicy", "Autoscaler"]


class AutoscalePolicy:
    """Pure scale-out/scale-in decision over one signal window.

    ``signals`` keys (missing keys default to quiet): ``ready`` (int),
    ``burn`` (windowed error-budget burn rate), ``queue_depth`` (max
    per-replica queued requests), ``occupancy`` (mean batch occupancy in
    [0, 1]), ``joining`` (replicas mid-bring-up, counted as capacity
    already ordered).

    Decision dict: ``{"action": "scale_out"|"scale_in"|"hold",
    "reason": str, "signals": signals}``.
    """

    def __init__(self, min_replicas: int = 1, max_replicas: int = 8, *,
                 burn_out: float = 1.0, queue_out: float = 8.0,
                 occupancy_out: float = 0.9,
                 burn_in: float = 0.25, queue_in: float = 0.0,
                 occupancy_in: float = 0.3,
                 hysteresis: int = 3, cooldown_s: float = 5.0,
                 scale_in_cooldown_s: float = 15.0):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}/{max_replicas}")
        if hysteresis < 1:
            raise ValueError("hysteresis must be >= 1")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.burn_out = float(burn_out)
        self.queue_out = float(queue_out)
        self.occupancy_out = float(occupancy_out)
        self.burn_in = float(burn_in)
        self.queue_in = float(queue_in)
        self.occupancy_in = float(occupancy_in)
        self.hysteresis = int(hysteresis)
        self.cooldown_s = float(cooldown_s)
        self.scale_in_cooldown_s = float(scale_in_cooldown_s)
        self._low_streak = 0
        self._last_action_at: Optional[float] = None
        self._prev_action_at: Optional[float] = None

    def reset(self) -> None:
        self._low_streak = 0
        self._last_action_at = None
        self._prev_action_at = None

    def _stamp(self, now: float) -> None:
        self._prev_action_at = self._last_action_at
        self._last_action_at = now

    def undo_action(self) -> None:
        """The controller could not execute the last decided action (e.g.
        the scale-out factory failed) — roll the cooldown stamp back so a
        fleet under genuine pressure doesn't wait out a cooldown for an
        action that never happened."""
        self._last_action_at = self._prev_action_at

    def _decision(self, action: str, reason: str, signals: dict) -> dict:
        return {"action": action, "reason": reason, "signals": signals}

    def decide(self, signals: dict, now: float) -> dict:
        ready = int(signals.get("ready", 0))
        joining = int(signals.get("joining", 0))
        burn = float(signals.get("burn", 0.0))
        queue_depth = float(signals.get("queue_depth", 0.0))
        occupancy = float(signals.get("occupancy", 0.0))
        capacity = ready + joining  # ordered capacity counts

        # capacity restoration outranks every damper: a fleet below its
        # floor (replica death, cold start) is an outage in progress
        if capacity < self.min_replicas:
            self._low_streak = 0
            self._stamp(now)
            return self._decision("scale_out",
                                  f"capacity {capacity} below floor "
                                  f"{self.min_replicas}", signals)

        pressure = []
        if burn > self.burn_out:
            pressure.append(f"burn {burn:.2f}x > {self.burn_out}x")
        if queue_depth > self.queue_out:
            pressure.append(f"queue {queue_depth:.0f} > {self.queue_out:.0f}")
        if occupancy > self.occupancy_out:
            pressure.append(
                f"occupancy {occupancy:.2f} > {self.occupancy_out}")

        if pressure:
            self._low_streak = 0
            if capacity >= self.max_replicas:
                return self._decision("hold",
                                      "pressure but fleet at max "
                                      f"({self.max_replicas}): "
                                      + "; ".join(pressure), signals)
            if (self._last_action_at is not None
                    and now - self._last_action_at < self.cooldown_s):
                return self._decision("hold",
                                      "pressure in cooldown: "
                                      + "; ".join(pressure), signals)
            self._stamp(now)
            return self._decision("scale_out", "; ".join(pressure), signals)

        quiet = (burn <= self.burn_in and queue_depth <= self.queue_in
                 and occupancy <= self.occupancy_in)
        if not quiet:
            # mid-band: neither pressure nor provably idle — the streak
            # resets so a blip can't sneak a scale-in through hysteresis
            self._low_streak = 0
            return self._decision("hold", "steady", signals)

        self._low_streak += 1
        if ready <= self.min_replicas:
            return self._decision("hold", "quiet at floor", signals)
        if self._low_streak < self.hysteresis:
            return self._decision(
                "hold", f"quiet {self._low_streak}/{self.hysteresis} "
                "(hysteresis)", signals)
        if (self._last_action_at is not None
                and now - self._last_action_at < self.scale_in_cooldown_s):
            return self._decision("hold", "quiet but in scale-in cooldown",
                                  signals)
        self._low_streak = 0
        self._stamp(now)
        return self._decision("scale_in",
                              f"quiet {self.hysteresis} consecutive windows",
                              signals)


class Autoscaler:
    """Drive a :class:`~mxnet_tpu.serve.fleet.ReplicaPool` from an
    :class:`AutoscalePolicy`.

    Parameters
    ----------
    pool / router
        The supervised fleet and its Router (the router's stats feed the
        SLO monitor; the pool executes joins and leaves).
    factory : callable, optional
        Zero-arg callable returning a fresh replica handle for scale-out.
        Default: ``pool.new_sharded_handle`` for sharded pools (the next
        spare mesh slice) — a non-sharded pool must pass one.
    policy / slo
        Decision policy and the SLO monitor whose ``burn_window`` supplies
        the windowed burn signal (defaults: :class:`AutoscalePolicy()`,
        ``SLOMonitor()``).
    interval : float
        Seconds between control-loop evaluations when started as a thread.
    drain_timeout : float
        Scale-in drain budget per replica.
    """

    def __init__(self, pool, router, factory: Optional[Callable] = None, *,
                 policy: Optional[AutoscalePolicy] = None,
                 slo: Optional[SLOMonitor] = None,
                 interval: float = 1.0, drain_timeout: float = 30.0):
        self._pool = pool
        self._router = router
        if factory is None:
            if getattr(pool, "_make_server", None) is None:
                raise ValueError(
                    "pass factory= for a non-sharded pool "
                    "(sharded pools default to pool.new_sharded_handle)")
            factory = pool.new_sharded_handle
        self._factory = factory
        self.policy = policy or AutoscalePolicy()
        self.slo = slo or SLOMonitor()
        self.interval = float(interval)
        self.drain_timeout = float(drain_timeout)
        self.events: List[dict] = []
        self.last_decision: Optional[dict] = None
        self._prev_snapshot: Optional[dict] = None
        self._leave_thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signal assembly ------------------------------------------------
    def signals(self) -> dict:
        """One signal window: windowed burn from metric-snapshot deltas,
        queue depth / occupancy / membership from the pool's member records
        (the same numbers the supervisor exports as ``fleet.replica<i>.*``
        gauges — operator dashboards and this controller cannot drift)."""
        snap = obs.metrics.snapshot()
        win = self.slo.burn_window(self._prev_snapshot, snap)
        self._prev_snapshot = snap
        pst = self._pool.stats()
        members = pst.get("members", {})
        ready = [v for v in members.values() if v["state"] == "ready"]
        # "joining" = every member that is ordered-but-not-serving: a
        # joiner mid-bring-up AND a dead/resyncing member the supervisor
        # is restoring. Counting only happy-path joiners would make a
        # failed bring-up (state "dead" during restart backoff) invisible
        # and the controller would pop a fresh mesh slice per cooldown
        # window for the SAME pressure — capacity already ordered must
        # never be ordered twice
        joining = sum(1 for v in members.values()
                      if v["state"] in ("new", "starting", "quarantined",
                                        "dead", "resync"))
        queue_depth = max((v.get("queue_depth", 0) for v in ready), default=0)
        occ = (sum(v.get("occupancy", 0.0) for v in ready) / len(ready)
               if ready else 0.0)
        return {"burn": win["burn"], "attainment": win["attainment"],
                "window_completed": win["completed"],
                "window_misses": win["misses"],
                "queue_depth": queue_depth, "occupancy": round(occ, 4),
                "ready": pst["ready"], "joining": joining,
                "generation": pst.get("generation", 0)}

    # -- control loop ---------------------------------------------------
    def tick(self, signals: Optional[dict] = None) -> dict:
        """One control-loop evaluation (tests and benches call this
        directly; ``signals`` overrides the live window). Returns the
        decision actually applied."""
        now = time.monotonic()
        sig = self.signals() if signals is None else signals
        d = self.policy.decide(sig, now)
        if d["action"] == "scale_out":
            d = self._scale_out(d)
        elif d["action"] == "scale_in":
            d = self._scale_in(d)
        if d["action"] != "hold":
            self.events.append({"t": now, "action": d["action"],
                                "reason": d["reason"],
                                "ready": sig.get("ready")})
            obs.inc(f"autoscale.{d['action']}")
            obs.event(f"autoscale.{d['action']}", reason=d["reason"],
                      ready=sig.get("ready"))
        obs.set_gauge("autoscale.ready", sig.get("ready", 0))
        self.last_decision = d
        return d

    def _scale_out(self, d: dict) -> dict:
        if int(d["signals"].get("joining", 0)) > 0:
            # one join at a time: bring-up includes XLA warmup; deciding
            # again mid-join would order capacity twice for one signal
            return {**d, "action": "hold",
                    "reason": f"join in flight ({d['reason']})"}
        try:
            handle = self._factory()
        except ServeError as e:
            # no capacity was ordered: give the cooldown back, or genuine
            # pressure would wait out a damper for a no-op
            self.policy.undo_action()
            return {**d, "action": "hold", "reason": f"factory: {e}"}
        self._pool.add_replica(handle, wait_ready=False)
        return d

    def _scale_in(self, d: dict) -> dict:
        if self._leave_thread is not None and self._leave_thread.is_alive():
            self.policy.undo_action()
            return {**d, "action": "hold", "reason": "leave in flight"}
        ready = self._pool.ready_members()
        if len(ready) <= self.policy.min_replicas:
            self.policy.undo_action()
            return {**d, "action": "hold", "reason": "at floor"}
        victim = max(ready, key=lambda m: m.idx)  # youngest member leaves

        def leave():
            self._pool.remove_replica(victim.idx,
                                      drain_timeout=self.drain_timeout)

        # drain off the control thread: a slow drain must not freeze the
        # signal loop (pending-leave detection keeps decisions sane)
        self._leave_thread = threading.Thread(target=leave, daemon=True,
                                              name="mxtpu-autoscale-leave")
        self._leave_thread.start()
        return d

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.interval):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the controller must
                # outlive a transient stats/RPC failure; the next window
                # gets a fresh read
                obs.inc("autoscale.tick_errors")
                obs.event("autoscale.tick_error",
                          error=f"{type(e).__name__}: {e}"[:160])

    def start(self) -> "Autoscaler":
        self._stop_evt.clear()
        self._prev_snapshot = obs.metrics.snapshot()  # window starts now
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxtpu-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                obs.inc("autoscale.thread_leaked")
                obs.event("autoscale.thread_leaked", which="control")
            self._thread = None
        if self._leave_thread is not None:
            self._leave_thread.join(timeout=self.drain_timeout + 5)
            if self._leave_thread.is_alive():
                # the drain outlived its budget: the replica will still be
                # stopped by remove_replica's own timeout, but the leak is
                # an operator signal
                obs.inc("autoscale.thread_leaked")
                obs.event("autoscale.thread_leaked", which="leave")
