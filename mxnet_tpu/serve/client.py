"""Client for the ``mxnet_tpu.serve`` socket endpoint.

Mirrors ``kvstore/ps_client.py``: every RPC has a socket timeout and a
reconnect-retry loop with capped exponential backoff + jitter (the delay
policy is literally shared — ``base.capped_backoff`` — so the training and
serving planes can never drift apart), and the chaos layer
(``mxnet_tpu.chaos.rpc``) can deterministically drop / delay / duplicate
frames at the marked points — so the degradation paths the server promises
are *tested* against a real flaky wire, not hoped for.

Connection is **lazy**: the constructor records the address and the first
RPC connects, inside the jittered retry loop. An eager ``__init__``
connect would make a fleet of clients reconnect in lockstep after a
replica restart (every constructor fails at the same instant, every
caller's retry clock starts together); routing the very first connect
through the same backoff+jitter path decorrelates the herd.

Inference is stateless, so retrying an INFER whose reply was lost is safe
(the server may execute it twice; both executions return the same answer
for the same parameter generation). Deadlines still bound the total retry
budget: a request whose SLO has expired is not worth re-sending, so the
retry loop gives up once the deadline passes and surfaces
:class:`DeadlineExceeded`.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from typing import List, Optional, Tuple, Union

import numpy as np

from .. import obs
from ..obs import context as obs_context
from ..base import capped_backoff, configure_socket_keepalive
from ..chaos import rpc as chaos_rpc
from ..kvstore.ps_server import (_pack_arrays, _recv_msg, _send_msg,
                                 _unpack_arrays)
from .engine import (DeadlineExceeded, Draining, RequestRejected, ServeError)
from .server import (OP_ABORT_RELOAD, OP_COMMIT_RELOAD, OP_DRAIN, OP_DUMP,
                     OP_HEALTH, OP_INFER, OP_INFER_STREAM,
                     OP_PREPARE_RELOAD, OP_READY, OP_RELOAD, OP_SHUTDOWN,
                     OP_STATS, OP_STREAM_END, OP_STREAM_ERROR,
                     OP_STREAM_TOKEN, OP_TELEMETRY, SERVE_OP_NAMES,
                     STATUS_BAD_REQUEST, STATUS_DEADLINE, STATUS_DRAINING,
                     STATUS_INTERNAL, STATUS_NOT_READY, STATUS_OK,
                     STATUS_REJECTED, _INFER_HDR, _STREAM_HDR,
                     _TOKEN_FRAME)

__all__ = ["ServeClient"]

_STATUS_ERRORS = {
    STATUS_REJECTED: RequestRejected,
    STATUS_DEADLINE: DeadlineExceeded,
    STATUS_DRAINING: Draining,
    STATUS_BAD_REQUEST: ServeError,
    STATUS_INTERNAL: ServeError,
    STATUS_NOT_READY: ServeError,
}


class ServeClient:
    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 retries: int = 3, retry_interval: float = 0.2,
                 retry_max_interval: float = 2.0):
        self._addr = (host, port)
        self._timeout = float(timeout)
        self._retries = max(1, int(retries))
        self._retry_interval = retry_interval
        self._retry_max_interval = retry_max_interval
        self._lock = threading.Lock()
        # lazy connect: the first RPC dials inside the jittered retry loop
        # (see the module docstring — no reconnect lockstep after restarts)
        self._sock: Optional[socket.socket] = None

    # ------------------------------------------------------------------
    def _connect(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout)
        # half-open detection: the shared keepalive policy (base.py) the PS
        # client uses — a SIGKILL'd replica is noticed by the kernel, not
        # only by the next RPC timeout
        configure_socket_keepalive(self._sock)

    def _backoff(self, attempt: int) -> float:
        return capped_backoff(attempt, self._retry_interval,
                              self._retry_max_interval)

    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _rpc(self, opcode: int, payload: bytes = b"",
             deadline: Optional[float] = None,
             retries: Optional[int] = None,
             timeout: Optional[float] = None):
        """Send one frame, return the reply payload. Reconnect-retries on
        connection errors; gives up early once ``deadline`` (monotonic
        seconds) has passed — retrying past the SLO only adds load.
        ``timeout`` overrides the socket timeout for this one RPC (the
        fleet router bounds each failover attempt by the request's
        remaining deadline, not the connection default)."""
        retries = self._retries if retries is None else max(1, int(retries))
        last_err = None
        opname = SERVE_OP_NAMES.get(opcode, str(opcode))
        # the wire is strictly serial per socket: the connection lock MUST
        # span the whole send->recv roundtrip (and the backoff between
        # attempts — a peer RPC could not use the half-open socket anyway);
        # socket timeouts bound every hold. Hence the blocking-under-lock
        # waivers below.
        with self._lock:
            for attempt in range(retries):
                if deadline is not None and time.monotonic() >= deadline:
                    raise DeadlineExceeded(
                        f"deadline expired during {opname} retries "
                        f"(last error: {last_err})")
                try:
                    if self._sock is None:
                        self._connect()
                    if timeout is not None:
                        self._sock.settimeout(timeout)
                    rec = obs.enabled()
                    t0 = time.monotonic() if rec else 0.0
                    with obs.trace.span("serve.client.rpc", op=opname,
                                        attempt=attempt):
                        # the span re-activated itself as the current
                        # context, so the wire key carries ITS span_id —
                        # the server's spans become its children. No
                        # active context (or obs off) → key stays "",
                        # byte-identical to the old wire format.
                        key = obs_context.inject_key(
                            "", obs_context.current())
                        dup = chaos_rpc.on_send(opcode, "")
                        _send_msg(self._sock, opcode, key, payload)  # lint: disable=blocking-call-under-lock
                        if dup == "dup":
                            _send_msg(self._sock, opcode, key, payload)  # lint: disable=blocking-call-under-lock
                        reply = _recv_msg(self._sock)  # lint: disable=blocking-call-under-lock
                        if dup == "dup":
                            reply = _recv_msg(self._sock)  # lint: disable=blocking-call-under-lock
                        chaos_rpc.on_reply(opcode, "")
                    if rec:
                        obs.observe(f"serve.client.{opname}_seconds",
                                    time.monotonic() - t0)
                    if timeout is not None:
                        self._sock.settimeout(self._timeout)
                    return reply[2]
                except (ConnectionError, OSError) as e:
                    last_err = e
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    if attempt + 1 >= retries:
                        break  # no retry left: surface the error NOW —
                        # sleeping a backoff nobody follows only delays
                        # the caller's failover past its hedge window
                    delay = self._backoff(attempt)
                    if obs.enabled():
                        obs.inc("serve.client.retries")
                        obs.observe("serve.client.backoff_seconds", delay)
                        obs.trace.event("serve.client.retry", op=opname,
                                        attempt=attempt, error=str(e))
                    time.sleep(delay)  # lint: disable=blocking-call-under-lock
        obs.inc("serve.client.failures")
        raise ServeError(
            f"serve rpc {opname} failed after {retries} attempts: "
            f"{last_err}")

    @staticmethod
    def _check(payload, what: str) -> memoryview:
        status = payload[0]
        if status == STATUS_OK:
            return payload[1:]
        msg = bytes(payload[1:]).decode("utf-8", "replace") or what
        raise _STATUS_ERRORS.get(status, ServeError)(msg)

    # ------------------------------------------------------------------
    # API
    # ------------------------------------------------------------------
    def infer(self, *inputs, deadline_ms: Optional[float] = None,
              priority: int = 1, return_version: bool = False,
              rpc_timeout: Optional[float] = None
              ) -> Union[np.ndarray, List[np.ndarray], tuple]:
        """Run inference on one request batch (one array per model input).
        ``deadline_ms`` propagates to the server's scheduler — an expired
        request is shed there, never executed late. ``priority`` 0 is the
        tight-SLO lane. ``rpc_timeout`` caps this call's socket wait (the
        fleet router keeps a hung replica from eating the whole deadline).
        Returns the output array (or list), plus the serving parameter
        version when ``return_version``."""
        arrays = [np.ascontiguousarray(np.asarray(x)) for x in inputs]
        payload = (_INFER_HDR.pack(float(deadline_ms or 0.0),
                                   min(max(int(priority), 0), 255))
                   + _pack_arrays(arrays))
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms else None)
        # the trace is born here (unless the caller already carries one):
        # the head-based sampling decision this root takes — or, under
        # tail mode, the tail-pending bit — rides the wire to the router
        # and every replica this request touches
        ctx = None
        root_here = False
        if obs.enabled():
            ctx = obs_context.current()
            if ctx is None:
                ctx = obs_context.new_root()
                root_here = True
        t0 = time.monotonic()
        try:
            with obs_context.use(ctx):
                reply = self._check(self._rpc(OP_INFER, payload,
                                              deadline=deadline,
                                              timeout=rpc_timeout),
                                    "inference failed")
        except BaseException as e:
            # tail retention: the server's verdict on this request rode
            # the existing reply path as the status byte — _check raised
            # it as a typed error, which becomes the root-close outcome
            if root_here:
                outcome = "deadline" if isinstance(e, DeadlineExceeded) \
                    else "shed" if isinstance(e, (RequestRejected,
                                                  Draining)) \
                    else "error"
                obs.tail.finish_root(ctx, time.monotonic() - t0,
                                     outcome=outcome)
            raise
        if root_here:
            obs.tail.finish_root(ctx, time.monotonic() - t0)
        (version,) = struct.unpack_from("<I", reply, 0)
        outs, _ = _unpack_arrays(reply[4:])
        result = outs[0] if len(outs) == 1 else outs
        return (result, version) if return_version else result

    def generate(self, tokens, *, max_new_tokens: Optional[int] = None,
                 deadline_ms: Optional[float] = None, priority: int = 1,
                 temperature: float = 0.0,
                 rpc_timeout: Optional[float] = None):
        """Stream one autoregressive generation, yielding int token ids
        as the server emits them (``OP_INFER_STREAM`` → chunked
        TOKEN/END/ERROR reply sequence). The typed serve errors
        (:class:`RequestRejected`, :class:`DeadlineExceeded`,
        :class:`Draining`, :class:`ServeError`) can raise MID-iteration —
        a deadline that expires or a shed that lands while tokens are
        already flowing surfaces at the next ``next()``, not only at
        submit. Closing the generator early hangs up the connection —
        the server's client-lost path cancels the generation at the next
        step boundary and reclaims its KV pages.

        Retry policy: unlike stateless ``infer``, the request frame is
        only retried while NO reply chunk has arrived. Once the first
        chunk lands the stream is committed — re-sending after observed
        tokens could interleave two generations — so a broken wire
        mid-stream surfaces as ``ServeError("stream broken after N
        tokens")`` instead of retrying.

        The connection lock is held for the whole stream (the wire is
        strictly serial per socket), so issuing another RPC on this
        client from the SAME thread while iterating would deadlock —
        finish or close the generator first.
        """
        prompt = np.ascontiguousarray(
            np.asarray(tokens, dtype=np.int32).reshape(-1))
        payload = (_STREAM_HDR.pack(float(deadline_ms or 0.0),
                                    min(max(int(priority), 0), 255),
                                    int(max_new_tokens or 0),
                                    float(temperature))
                   + _pack_arrays([prompt]))
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms else None)
        # same trace-birth rule as infer(): the root born here rides the
        # wire to the replica, so its decode spans join this trace
        ctx = None
        root_here = False
        if obs.enabled():
            ctx = obs_context.current()
            if ctx is None:
                ctx = obs_context.new_root()
                root_here = True
        t0 = time.monotonic()
        try:
            yield from self._generate_stream(payload, ctx, deadline,
                                             rpc_timeout)
        except BaseException as e:
            if root_here:
                outcome = "deadline" if isinstance(e, DeadlineExceeded) \
                    else "shed" if isinstance(e, (RequestRejected,
                                                  Draining)) \
                    else "cancelled" if isinstance(e, GeneratorExit) \
                    else "error"
                obs.tail.finish_root(ctx, time.monotonic() - t0,
                                     outcome=outcome)
            raise
        if root_here:
            obs.tail.finish_root(ctx, time.monotonic() - t0)
        if obs.enabled():
            obs.observe("serve.client.infer_stream_seconds",
                        time.monotonic() - t0)

    def _generate_stream(self, payload: bytes, ctx, deadline, timeout):
        """The wire half of :meth:`generate`: send the request (with the
        pre-commit retry loop), then relay the chunk sequence."""
        opname = SERVE_OP_NAMES.get(OP_INFER_STREAM, "infer_stream")
        # the lock spans the whole send -> chunk... -> terminal-frame
        # conversation: chunks from a peer RPC interleaving on the socket
        # would be garbage. Socket timeouts bound every hold; generator
        # close() releases it via the with-block.
        with self._lock:
            dup = None
            last_err = None
            for attempt in range(self._retries):
                if deadline is not None and time.monotonic() >= deadline:
                    raise DeadlineExceeded(
                        f"deadline expired during {opname} retries "
                        f"(last error: {last_err})")
                try:
                    if self._sock is None:
                        self._connect()
                    if timeout is not None:
                        self._sock.settimeout(timeout)
                    key = obs_context.inject_key("", ctx)
                    dup = chaos_rpc.on_send(OP_INFER_STREAM, "")
                    _send_msg(self._sock, OP_INFER_STREAM, key, payload)  # lint: disable=blocking-call-under-lock
                    if dup == "dup":
                        _send_msg(self._sock, OP_INFER_STREAM, key, payload)  # lint: disable=blocking-call-under-lock
                    break
                except (ConnectionError, OSError) as e:
                    last_err = e
                    self._drop_sock()
                    if attempt + 1 >= self._retries:
                        obs.inc("serve.client.failures")
                        raise ServeError(
                            f"serve rpc {opname} failed after "
                            f"{self._retries} attempts: {last_err}")
                    delay = self._backoff(attempt)
                    if obs.enabled():
                        obs.inc("serve.client.retries")
                        obs.observe("serve.client.backoff_seconds", delay)
                        obs.trace.event("serve.client.retry", op=opname,
                                        attempt=attempt, error=str(e))
                    time.sleep(delay)  # lint: disable=blocking-call-under-lock
            n = 0
            err = None
            try:
                while True:
                    opcode, _key, chunk = _recv_msg(self._sock)  # lint: disable=blocking-call-under-lock
                    chaos_rpc.on_reply(opcode, "")
                    if opcode == OP_STREAM_TOKEN:
                        n += 1
                        tok, _idx = _TOKEN_FRAME.unpack_from(chunk, 0)
                        yield int(tok)
                    elif opcode == OP_STREAM_END:
                        break
                    elif opcode == OP_STREAM_ERROR:
                        status = chunk[0] if len(chunk) else \
                            STATUS_INTERNAL
                        msg = bytes(chunk[1:]).decode("utf-8", "replace") \
                            or "generation failed"
                        err = _STATUS_ERRORS.get(status, ServeError)(msg)
                        break
                    else:
                        self._drop_sock()
                        raise ServeError(
                            f"unexpected opcode {opcode} in stream reply")
                # terminal frame seen: after draining a chaos-dup echo the
                # wire is frame-aligned again and the socket stays usable
                if dup == "dup":
                    self._drain_echo()  # lint: disable=blocking-call-under-lock
                if timeout is not None:
                    self._sock.settimeout(self._timeout)
            except GeneratorExit:
                # the consumer abandoned a live stream: hanging up is the
                # cancel signal — the server's client-lost path closes the
                # generation and reclaims its KV pages at the next step
                # boundary. The socket is desynced (chunks in flight), so
                # it cannot be reused.
                self._drop_sock()
                obs.inc("serve.client.stream_cancelled")
                raise
            except (ConnectionError, OSError, struct.error) as e:
                self._drop_sock()
                obs.inc("serve.client.stream_broken")
                raise ServeError(f"stream broken after {n} tokens: {e}")
            if obs.enabled() and n:
                obs.inc("serve.client.stream_tokens", n)
            if err is not None:
                raise err

    def _drain_echo(self) -> None:
        """Consume and discard one full chunk sequence — the server's
        answer to a chaos-duplicated INFER_STREAM frame — so the socket
        is frame-aligned for the next RPC. Called under the connection
        lock (from the stream that owns it)."""
        while True:
            opcode, _key, _chunk = _recv_msg(self._sock)  # lint: disable=blocking-call-under-lock
            chaos_rpc.on_reply(opcode, "")
            if opcode in (OP_STREAM_END, OP_STREAM_ERROR):
                return

    def health(self) -> bool:
        """Liveness probe (True = the process answers)."""
        try:
            return self._rpc(OP_HEALTH)[0] == STATUS_OK
        except ServeError:
            return False

    def ready(self) -> bool:
        """Readiness probe (True = model loaded and accepting traffic —
        False while draining, so a load balancer rotates this replica
        out before requests start bouncing)."""
        try:
            return self._rpc(OP_READY)[0] == STATUS_OK
        except ServeError:
            return False

    def ready_version(self) -> Tuple[bool, int]:
        """Readiness plus the serving parameter version in one probe — the
        fleet router gates a rejoining replica on version coherence with
        this (a replica restarted mid-reload must rejoin at the committed
        fleet version, never a stale one)."""
        try:
            reply = self._rpc(OP_READY)
            if len(reply) >= 5:
                status, version = struct.unpack_from("<BI", reply, 0)
                return status == STATUS_OK, int(version)
            return reply[0] == STATUS_OK, 0
        except ServeError:
            return False, -1

    def stats(self, include_metrics: bool = True) -> dict:
        """Server stats json. ``include_metrics=False`` skips the metrics
        registry snapshot (the fleet supervisor's cheap per-probe poll)."""
        payload = b"" if include_metrics \
            else json.dumps({"metrics": False}).encode("utf-8")
        reply = self._check(self._rpc(OP_STATS, payload), "stats failed")
        return json.loads(bytes(reply).decode("utf-8"))

    def dump(self, reason: str = "wire", write: bool = False) -> dict:
        """Pull the server's flight-recorder bundle (``OP_DUMP``,
        obs/blackbox.py): the always-on ring of recent spans, a metrics
        snapshot, profiler samples, and per-thread stacks — a remote
        "what is this replica doing right now" snapshot. ``write=True``
        additionally persists the bundle server-side (when the recorder
        is armed with a directory) and returns its path in ``"path"``.
        Read-only: nothing drains, retries are harmless."""
        payload = json.dumps({"reason": reason,
                              "write": bool(write)}).encode("utf-8")
        reply = self._check(self._rpc(OP_DUMP, payload), "dump failed")
        return json.loads(bytes(reply).decode("utf-8"))

    def telemetry(self, drain: bool = True, fmt: str = "json",
                  retained: Optional[list] = None,
                  openmetrics: bool = True):
        """Pull the server's telemetry (``OP_TELEMETRY``): ``fmt="json"``
        returns ``{"parts": [...]}`` — one part per process behind the
        endpoint (a FleetServer appends every live replica's), each with
        its drained span ring, metrics snapshot, and clock anchor.
        ``fmt="prometheus"`` returns the text exposition instead
        (OpenMetrics with tail exemplars by default; pass
        ``openmetrics=False`` for strict 0.0.4 output — a mid-line
        exemplar ``#`` is a whole-scrape parse error to classic parsers,
        so a reply feeding a node_exporter textfile collector or a
        pushgateway needs the strict form).
        ``drain=False`` peeks without consuming the rings.

        Exactly-once under retries: draining is destructive, so the
        request carries a fresh collection token — a retried frame whose
        reply was lost re-serves the server's cached reply instead of
        draining (and losing) a second batch.

        Tail retention (obs/tail.py): the collection carries this
        process's retained-trace verdict log (plus any ``retained`` ids
        the caller adds), so a downstream hop's pending spans promote
        with the very collection that fetches them."""
        spec = {"drain": bool(drain), "format": fmt,
                "token": os.urandom(8).hex()}
        if fmt == "prometheus" and not openmetrics:
            spec["openmetrics"] = False
        ids = list(retained or ())
        if obs.tail.enabled():
            ids.extend(obs.tail.retained_ids())
        if ids:
            spec["retained"] = sorted(set(ids))
        payload = json.dumps(spec).encode("utf-8")
        reply = self._check(self._rpc(OP_TELEMETRY, payload),
                            "telemetry failed")
        if fmt == "prometheus":
            return bytes(reply).decode("utf-8")
        return json.loads(bytes(reply).decode("utf-8"))

    def reload(self, path: str, epoch: Optional[int] = None,
               prefix: str = "ckpt") -> int:
        """Hot-swap the server onto a newer checkpoint of the same model.
        Returns the new parameter version."""
        spec = {"path": path, "epoch": epoch, "prefix": prefix}
        reply = self._check(
            self._rpc(OP_RELOAD, json.dumps(spec).encode("utf-8")),
            "reload failed")
        (version,) = struct.unpack_from("<I", reply, 0)
        return version

    def prepare_reload(self, path: str, epoch: Optional[int] = None,
                       prefix: str = "ckpt", *,
                       version: Optional[int] = None,
                       token: Optional[Tuple[int, int]] = None,
                       retries: Optional[int] = None) -> int:
        """Phase one of the fleet-atomic reload: the replica loads,
        validates, and stages the new generation without flipping. Returns
        the staged version (the fleet-stamped ``version`` when given)."""
        spec = {"path": path, "epoch": epoch, "prefix": prefix,
                "version": version,
                "token": list(token) if token is not None else None}
        reply = self._check(
            self._rpc(OP_PREPARE_RELOAD, json.dumps(spec).encode("utf-8"),
                      retries=retries),
            "prepare_reload failed")
        (staged,) = struct.unpack_from("<I", reply, 0)
        return staged

    def commit_reload(self, token: Tuple[int, int],
                      retries: Optional[int] = None) -> int:
        """Phase two: flip the staged generation. Safe to retry — the
        server dedups the token, so a lost ack cannot double-flip."""
        reply = self._check(
            self._rpc(OP_COMMIT_RELOAD, struct.pack("<QQ", *token),
                      retries=retries),
            "commit_reload failed")
        (ver,) = struct.unpack_from("<I", reply, 0)
        return ver

    def abort_reload(self, token: Tuple[int, int]) -> None:
        """Discard a staged generation (idempotent rollback)."""
        self._check(self._rpc(OP_ABORT_RELOAD, struct.pack("<QQ", *token)),
                    "abort_reload failed")

    def drain(self, stop: bool = False) -> bool:
        """Ask the server to finish in-flight work and refuse new requests
        (``stop=True`` also closes the listener afterwards)."""
        payload = struct.pack("<B", 1 if stop else 0)
        return self._rpc(OP_DRAIN, payload)[0] == STATUS_OK

    def shutdown(self) -> None:
        self._rpc(OP_SHUTDOWN)

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
