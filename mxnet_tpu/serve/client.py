"""Client for the ``mxnet_tpu.serve`` socket endpoint.

Mirrors ``kvstore/ps_client.py``: every RPC has a socket timeout and a
reconnect-retry loop with capped exponential backoff + jitter, and the
chaos layer (``mxnet_tpu.chaos.rpc``) can deterministically drop / delay /
duplicate frames at the marked points — so the degradation paths the
server promises are *tested* against a real flaky wire, not hoped for.

Inference is stateless, so retrying an INFER whose reply was lost is safe
(the server may execute it twice; both executions return the same answer
for the same parameter generation). Deadlines still bound the total retry
budget: a request whose SLO has expired is not worth re-sending, so the
retry loop gives up once the deadline passes and surfaces
:class:`DeadlineExceeded`.
"""
from __future__ import annotations

import json
import random
import socket
import struct
import threading
import time
from typing import List, Optional, Union

import numpy as np

from .. import obs
from ..chaos import rpc as chaos_rpc
from ..kvstore.ps_server import (_pack_arrays, _recv_msg, _send_msg,
                                 _unpack_arrays)
from .engine import (DeadlineExceeded, Draining, RequestRejected, ServeError)
from .server import (OP_DRAIN, OP_HEALTH, OP_INFER, OP_READY, OP_RELOAD,
                     OP_SHUTDOWN, OP_STATS, SERVE_OP_NAMES, STATUS_BAD_REQUEST,
                     STATUS_DEADLINE, STATUS_DRAINING, STATUS_INTERNAL,
                     STATUS_NOT_READY, STATUS_OK, STATUS_REJECTED, _INFER_HDR)

__all__ = ["ServeClient"]

_STATUS_ERRORS = {
    STATUS_REJECTED: RequestRejected,
    STATUS_DEADLINE: DeadlineExceeded,
    STATUS_DRAINING: Draining,
    STATUS_BAD_REQUEST: ServeError,
    STATUS_INTERNAL: ServeError,
    STATUS_NOT_READY: ServeError,
}


class ServeClient:
    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 retries: int = 3, retry_interval: float = 0.2,
                 retry_max_interval: float = 2.0):
        self._addr = (host, port)
        self._timeout = float(timeout)
        self._retries = max(1, int(retries))
        self._retry_interval = retry_interval
        self._retry_max_interval = retry_max_interval
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._connect()

    # ------------------------------------------------------------------
    def _connect(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout)

    def _backoff(self, attempt: int) -> float:
        delay = min(self._retry_max_interval,
                    self._retry_interval * (2.0 ** attempt))
        return delay * (0.5 + random.random() / 2.0)

    def _rpc(self, opcode: int, payload: bytes = b"",
             deadline: Optional[float] = None):
        """Send one frame, return the reply payload. Reconnect-retries on
        connection errors; gives up early once ``deadline`` (monotonic
        seconds) has passed — retrying past the SLO only adds load."""
        retries = self._retries
        last_err = None
        opname = SERVE_OP_NAMES.get(opcode, str(opcode))
        with self._lock:
            for attempt in range(retries):
                if deadline is not None and time.monotonic() >= deadline:
                    raise DeadlineExceeded(
                        f"deadline expired during {opname} retries "
                        f"(last error: {last_err})")
                try:
                    if self._sock is None:
                        self._connect()
                    rec = obs.enabled()
                    t0 = time.monotonic() if rec else 0.0
                    with obs.trace.span("serve.client.rpc", op=opname,
                                        attempt=attempt):
                        dup = chaos_rpc.on_send(opcode, "")
                        _send_msg(self._sock, opcode, "", payload)
                        if dup == "dup":
                            _send_msg(self._sock, opcode, "", payload)
                        reply = _recv_msg(self._sock)
                        if dup == "dup":
                            reply = _recv_msg(self._sock)
                        chaos_rpc.on_reply(opcode, "")
                    if rec:
                        obs.observe(f"serve.client.{opname}_seconds",
                                    time.monotonic() - t0)
                    return reply[2]
                except (ConnectionError, OSError) as e:
                    last_err = e
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    delay = self._backoff(attempt)
                    if obs.enabled():
                        obs.inc("serve.client.retries")
                        obs.trace.event("serve.client.retry", op=opname,
                                        attempt=attempt, error=str(e))
                    time.sleep(delay)
        obs.inc("serve.client.failures")
        raise ServeError(
            f"serve rpc {opname} failed after {retries} attempts: "
            f"{last_err}")

    @staticmethod
    def _check(payload, what: str) -> memoryview:
        status = payload[0]
        if status == STATUS_OK:
            return payload[1:]
        msg = bytes(payload[1:]).decode("utf-8", "replace") or what
        raise _STATUS_ERRORS.get(status, ServeError)(msg)

    # ------------------------------------------------------------------
    # API
    # ------------------------------------------------------------------
    def infer(self, *inputs, deadline_ms: Optional[float] = None,
              priority: int = 1, return_version: bool = False
              ) -> Union[np.ndarray, List[np.ndarray], tuple]:
        """Run inference on one request batch (one array per model input).
        ``deadline_ms`` propagates to the server's scheduler — an expired
        request is shed there, never executed late. ``priority`` 0 is the
        tight-SLO lane. Returns the output array (or list), plus the
        serving parameter version when ``return_version``."""
        arrays = [np.ascontiguousarray(np.asarray(x)) for x in inputs]
        payload = (_INFER_HDR.pack(float(deadline_ms or 0.0),
                                   min(max(int(priority), 0), 255))
                   + _pack_arrays(arrays))
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms else None)
        reply = self._check(self._rpc(OP_INFER, payload, deadline=deadline),
                            "inference failed")
        (version,) = struct.unpack_from("<I", reply, 0)
        outs, _ = _unpack_arrays(reply[4:])
        result = outs[0] if len(outs) == 1 else outs
        return (result, version) if return_version else result

    def health(self) -> bool:
        """Liveness probe (True = the process answers)."""
        try:
            return self._rpc(OP_HEALTH)[0] == STATUS_OK
        except ServeError:
            return False

    def ready(self) -> bool:
        """Readiness probe (True = model loaded and accepting traffic —
        False while draining, so a load balancer rotates this replica
        out before requests start bouncing)."""
        try:
            return self._rpc(OP_READY)[0] == STATUS_OK
        except ServeError:
            return False

    def stats(self) -> dict:
        reply = self._check(self._rpc(OP_STATS), "stats failed")
        return json.loads(bytes(reply).decode("utf-8"))

    def reload(self, path: str, epoch: Optional[int] = None,
               prefix: str = "ckpt") -> int:
        """Hot-swap the server onto a newer checkpoint of the same model.
        Returns the new parameter version."""
        spec = {"path": path, "epoch": epoch, "prefix": prefix}
        reply = self._check(
            self._rpc(OP_RELOAD, json.dumps(spec).encode("utf-8")),
            "reload failed")
        (version,) = struct.unpack_from("<I", reply, 0)
        return version

    def drain(self, stop: bool = False) -> bool:
        """Ask the server to finish in-flight work and refuse new requests
        (``stop=True`` also closes the listener afterwards)."""
        payload = struct.pack("<B", 1 if stop else 0)
        return self._rpc(OP_DRAIN, payload)[0] == STATUS_OK

    def shutdown(self) -> None:
        self._rpc(OP_SHUTDOWN)

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
