"""``mx.rnn`` — the legacy symbolic RNN cell API + bucketing iterator
(reference ``python/mxnet/rnn/`` — TBV)."""
from .io import BucketSentenceIter  # noqa: F401
from .rnn_cell import (BaseRNNCell, BidirectionalCell, DropoutCell,  # noqa: F401
                       FusedRNNCell, GRUCell, LSTMCell, ResidualCell,
                       RNNCell, SequentialRNNCell, ZoneoutCell)
