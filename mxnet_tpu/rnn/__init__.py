"""``mx.rnn`` — the legacy symbolic RNN cell API + bucketing iterator
(reference ``python/mxnet/rnn/`` — TBV)."""
from .io import BucketSentenceIter  # noqa: F401
from .rnn_cell import (BaseRNNCell, DropoutCell, FusedRNNCell, GRUCell,  # noqa: F401
                       LSTMCell, RNNCell, SequentialRNNCell)
