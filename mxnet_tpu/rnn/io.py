"""BucketSentenceIter (reference ``python/mxnet/rnn/io.py`` — TBV): the
bucketing data iterator the BucketingModule examples pair with the cell
API. Sentences land in the smallest bucket that fits, pad with
``invalid_label``, and each batch carries ``bucket_key`` plus
provide_data/provide_label for that bucket's length.
"""
from __future__ import annotations

import warnings

import numpy as np

from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray import array

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter(DataIter):
    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data", label_name="softmax_label",
                 dtype="float32", layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size and i > 0]
        buckets = sorted(buckets)
        if not buckets:
            raise ValueError(
                "BucketSentenceIter: no buckets could be formed — no "
                "sentence length occurs >= batch_size times; pass an "
                "explicit buckets list")
        self.buckets = buckets
        self.data_name, self.label_name = data_name, label_name
        self.invalid_label = invalid_label
        self.dtype = dtype
        self.layout = layout

        self._data = [[] for _ in buckets]
        ndiscard = 0
        for s in sentences:
            bkt = np.searchsorted(buckets, len(s))
            if bkt == len(buckets):
                ndiscard += 1
                continue
            buf = np.full((buckets[bkt],), invalid_label, dtype=dtype)
            buf[:len(s)] = s
            self._data[bkt].append(buf)
        self._data = [np.asarray(x, dtype=dtype) if x else
                      np.empty((0, b), dtype=dtype)
                      for x, b in zip(self._data, buckets)]
        self.ndiscard = ndiscard
        if ndiscard:
            warnings.warn(
                f"BucketSentenceIter: discarded {ndiscard} sentences "
                f"longer than the largest bucket ({buckets[-1]})")

        self.default_bucket_key = max(buckets)
        self._plan = []
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size, self.default_bucket_key),
                         self.dtype)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.default_bucket_key),
                         self.dtype)]

    def reset(self):
        self._plan = []
        for i, arr in enumerate(self._data):
            np.random.shuffle(arr)
            for start in range(0, len(arr) - self.batch_size + 1,
                               self.batch_size):
                self._plan.append((i, start))
        np.random.shuffle(self._plan)
        self._cursor = 0

    def next(self):
        if self._cursor >= len(self._plan):
            raise StopIteration
        bkt, start = self._plan[self._cursor]
        self._cursor += 1
        data = self._data[bkt][start:start + self.batch_size]
        # label = next-token shift, padded with invalid_label
        label = np.full_like(data, self.invalid_label)
        label[:, :-1] = data[:, 1:]
        blen = self.buckets[bkt]
        batch = DataBatch([array(data)], [array(label)], 0, None)
        batch.bucket_key = blen
        batch.provide_data = [DataDesc(self.data_name,
                                       (self.batch_size, blen), self.dtype)]
        batch.provide_label = [DataDesc(self.label_name,
                                        (self.batch_size, blen), self.dtype)]
        return batch
