"""Legacy symbolic RNN cells (reference ``python/mxnet/rnn/rnn_cell.py`` —
TBV; the API the BucketingModule examples drive: build per-step symbol
graphs with explicit parameter Variables, then ``unroll``).

Gate orders follow the same cuDNN convention as the fused RNN op
(ops/rnn.py): LSTM [i, f, g, o], GRU [r, z, n] — so FusedRNNCell and the
unfused cells are weight-compatible: ``FusedRNNCell.pack_weights`` /
``unpack_weights`` convert between per-cell tensors and the packed
vector.
"""
from __future__ import annotations

from typing import List, Optional

from .. import symbol as sym

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "FusedRNNCell",
           "BidirectionalCell", "ResidualCell", "ZoneoutCell"]


class BaseRNNCell:
    def __init__(self, prefix=""):
        self._prefix = prefix
        self._counter = 0
        self._own_params = {}

    def _var(self, name):
        full = self._prefix + name
        if full not in self._own_params:
            self._own_params[full] = sym.Variable(full)
        return self._own_params[full]

    @property
    def state_info(self):
        raise NotImplementedError

    def begin_state(self, func=None, batch_size=0, **kwargs):
        """Zero begin states. With ``func``+``batch_size`` this builds
        concrete symbols (legacy ``func=mx.sym.zeros`` pattern); otherwise
        it returns None placeholders that ``__call__``/``unroll``
        materialize from the step input's batch dimension."""
        if func is not None and batch_size:
            return [func(shape=(batch_size, n)) for n in self.state_info]
        return [None for _ in self.state_info]

    def _materialize(self, inputs, states):
        """Replace None begin-state placeholders with input-derived zeros
        so the manual per-step pattern (`out, st = cell(x_t, st)`) works."""
        return [self._zeros_like_state(inputs, n) if s is None else s
                for s, n in zip(states, self.state_info)]

    def __call__(self, inputs, states):
        raise NotImplementedError

    def reset(self):
        self._counter = 0

    def _zeros_like_state(self, x_t, n):
        """(B, n) zeros built from a (B, C) step input — keeps the graph
        free of concrete batch sizes."""
        z = sym.mean(x_t, axis=-1, keepdims=True) * 0.0  # (B, 1)
        return sym.tile(z, reps=(1, n))

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """inputs: one (N, T, C) symbol (layout NTC) or a list of T
        step symbols. Returns (outputs, states)."""
        self.reset()
        if isinstance(inputs, (list, tuple)):
            steps = list(inputs)
        else:
            axis = layout.find("T")
            steps = [sym.squeeze(sym.slice_axis(inputs, axis=axis, begin=t,
                                                end=t + 1), axis=axis)
                     for t in range(length)]
        states = begin_state if begin_state is not None \
            else self.begin_state()
        outputs = []
        for t in range(length):
            if any(s is None for s in states):
                states = [self._zeros_like_state(steps[t], info)
                          if s is None else s
                          for s, info in zip(states, self.state_info)]
            out, states = self(steps[t], states)
            outputs.append(out)
        if merge_outputs:
            axis = layout.find("T")
            outputs = sym.stack(*outputs, axis=axis)
        return outputs, states


class RNNCell(BaseRNNCell):
    def __init__(self, num_hidden, activation="tanh", prefix="rnn_"):
        super().__init__(prefix)
        self._h = num_hidden
        self._act = activation

    @property
    def state_info(self):
        return [self._h]

    def __call__(self, inputs, states):
        states = self._materialize(inputs, states)
        i2h = sym.FullyConnected(inputs, self._var("i2h_weight"),
                                 self._var("i2h_bias"),
                                 num_hidden=self._h, flatten=False)
        h2h = sym.FullyConnected(states[0], self._var("h2h_weight"),
                                 self._var("h2h_bias"),
                                 num_hidden=self._h, flatten=False)
        out = sym.Activation(i2h + h2h, act_type=self._act)
        return out, [out]


class LSTMCell(BaseRNNCell):
    def __init__(self, num_hidden, prefix="lstm_"):
        super().__init__(prefix)
        self._h = num_hidden

    @property
    def state_info(self):
        return [self._h, self._h]

    def __call__(self, inputs, states):
        states = self._materialize(inputs, states)
        h = self._h
        gates = (sym.FullyConnected(inputs, self._var("i2h_weight"),
                                    self._var("i2h_bias"),
                                    num_hidden=4 * h, flatten=False)
                 + sym.FullyConnected(states[0], self._var("h2h_weight"),
                                      self._var("h2h_bias"),
                                      num_hidden=4 * h, flatten=False))
        i = sym.sigmoid(sym.slice_axis(gates, axis=-1, begin=0, end=h))
        f = sym.sigmoid(sym.slice_axis(gates, axis=-1, begin=h, end=2 * h))
        g = sym.tanh(sym.slice_axis(gates, axis=-1, begin=2 * h, end=3 * h))
        o = sym.sigmoid(sym.slice_axis(gates, axis=-1, begin=3 * h,
                                       end=4 * h))
        c = f * states[1] + i * g
        out = o * sym.tanh(c)
        return out, [out, c]


class GRUCell(BaseRNNCell):
    """cuDNN GRU variant (linear_before_reset): the recurrent candidate
    term keeps its own bias, matching ops/rnn.py's fused scan."""

    def __init__(self, num_hidden, prefix="gru_"):
        super().__init__(prefix)
        self._h = num_hidden

    @property
    def state_info(self):
        return [self._h]

    def __call__(self, inputs, states):
        states = self._materialize(inputs, states)
        h = self._h
        i2h = sym.FullyConnected(inputs, self._var("i2h_weight"),
                                 self._var("i2h_bias"),
                                 num_hidden=3 * h, flatten=False)
        h2h = sym.FullyConnected(states[0], self._var("h2h_weight"),
                                 self._var("h2h_bias"),
                                 num_hidden=3 * h, flatten=False)
        xr = sym.slice_axis(i2h, axis=-1, begin=0, end=h)
        xz = sym.slice_axis(i2h, axis=-1, begin=h, end=2 * h)
        xn = sym.slice_axis(i2h, axis=-1, begin=2 * h, end=3 * h)
        hr = sym.slice_axis(h2h, axis=-1, begin=0, end=h)
        hz = sym.slice_axis(h2h, axis=-1, begin=h, end=2 * h)
        hn = sym.slice_axis(h2h, axis=-1, begin=2 * h, end=3 * h)
        r = sym.sigmoid(xr + hr)
        z = sym.sigmoid(xz + hz)
        n = sym.tanh(xn + r * hn)
        out = (1.0 - z) * n + z * states[0]
        return out, [out]


class SequentialRNNCell(BaseRNNCell):
    def __init__(self):
        super().__init__("")
        self._cells: List[BaseRNNCell] = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return [i for c in self._cells for i in c.state_info]

    def __call__(self, inputs, states):
        next_states = []
        pos = 0
        out = inputs
        for c in self._cells:
            n = len(c.state_info)
            out, st = c(out, states[pos:pos + n])
            next_states.extend(st)
            pos += n
        return out, next_states


class DropoutCell(BaseRNNCell):
    def __init__(self, dropout, prefix="dropout_"):
        super().__init__(prefix)
        self._p = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        return sym.Dropout(inputs, p=self._p), states


class FusedRNNCell(BaseRNNCell):
    """The fused RNN op behind the cell API (reference FusedRNNCell:
    cuDNN-packed single parameter vector, unrolled in one op call)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, prefix="fused_"):
        super().__init__(prefix)
        self._h = num_hidden
        self._layers = num_layers
        self._mode = mode
        self._bidir = bidirectional

    @property
    def state_info(self):
        dirs = 2 if self._bidir else 1
        n = self._layers * dirs
        return [n * self._h] * (2 if self._mode == "lstm" else 1)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        if isinstance(inputs, (list, tuple)):
            axis0 = sym.stack(*inputs, axis=0)  # (T, N, C)
        else:
            t_ax = layout.find("T")
            axis0 = inputs if t_ax == 0 else sym.transpose(
                inputs, axes=(1, 0, 2))
        dirs = 2 if self._bidir else 1
        n_states = self._layers * dirs
        params = self._var("parameters")

        def zero_state():
            z = sym.mean(sym.slice_axis(axis0, axis=0, begin=0, end=1),
                         axis=-1, keepdims=True) * 0.0   # (1, N, 1)
            return sym.tile(z, reps=(n_states, 1, self._h))

        states = begin_state if begin_state is not None else \
            [None] * len(self.state_info)
        states = [zero_state() if s is None else s for s in states]
        args = [axis0, params] + states
        res = sym.RNN(*args, state_size=self._h, num_layers=self._layers,
                      mode=self._mode, bidirectional=self._bidir,
                      state_outputs=True)
        n_out = 3 if self._mode == "lstm" else 2
        out = res[0]
        final_states = [res[i] for i in range(1, n_out)]
        if layout.find("T") == 1:
            out = sym.transpose(out, axes=(1, 0, 2))
        if merge_outputs is False:
            t_ax = layout.find("T")
            out = [sym.squeeze(sym.slice_axis(out, axis=t_ax, begin=t,
                                              end=t + 1), axis=t_ax)
                   for t in range(length)]
        return out, final_states


    def begin_state(self, func=None, batch_size=0, **kwargs):
        dirs = 2 if self._bidir else 1
        n = self._layers * dirs
        if func is not None and batch_size:
            return [func(shape=(n, batch_size, self._h))
                    for _ in self.state_info]
        return [None for _ in self.state_info]

    def pack_weights(self, args):
        """Per-cell tensors -> the cuDNN-packed vector (reference
        FusedRNNCell.pack_weights; single-direction only). ``args`` maps
        ``{prefix}l{i}_i2h_weight`` etc. to numpy arrays; returns the flat
        vector under ``{prefix}parameters``."""
        import numpy as np

        if self._bidir:
            raise NotImplementedError("pack_weights: bidirectional TBD")
        parts_w, parts_b = [], []
        for li in range(self._layers):
            parts_w.append(np.asarray(
                args[f"{self._prefix}l{li}_i2h_weight"]).reshape(-1))
            parts_w.append(np.asarray(
                args[f"{self._prefix}l{li}_h2h_weight"]).reshape(-1))
            parts_b.append(np.asarray(
                args[f"{self._prefix}l{li}_i2h_bias"]).reshape(-1))
            parts_b.append(np.asarray(
                args[f"{self._prefix}l{li}_h2h_bias"]).reshape(-1))
        out = dict(args)
        out[f"{self._prefix}parameters"] = np.concatenate(
            parts_w + parts_b).astype(np.float32)
        return out

    def unpack_weights(self, args, input_size):
        """Packed vector -> per-cell tensors (inverse of pack_weights).
        ``input_size`` fixes layer-0's input width."""
        import numpy as np

        from ..ops.rnn import _GATES

        if self._bidir:
            raise NotImplementedError("unpack_weights: bidirectional TBD")
        g = _GATES[self._mode]
        h = self._h
        vec = np.asarray(args[f"{self._prefix}parameters"]).reshape(-1)
        out = dict(args)
        off = 0
        for li in range(self._layers):
            isz = input_size if li == 0 else h
            out[f"{self._prefix}l{li}_i2h_weight"] = \
                vec[off:off + g * h * isz].reshape(g * h, isz)
            off += g * h * isz
            out[f"{self._prefix}l{li}_h2h_weight"] = \
                vec[off:off + g * h * h].reshape(g * h, h)
            off += g * h * h
        for li in range(self._layers):
            out[f"{self._prefix}l{li}_i2h_bias"] = vec[off:off + g * h]
            off += g * h
            out[f"{self._prefix}l{li}_h2h_bias"] = vec[off:off + g * h]
            off += g * h
        return out


class BidirectionalCell(BaseRNNCell):
    """Runs l_cell forward and r_cell backward over the sequence and
    concatenates per-step outputs on the feature axis (reference
    BidirectionalCell; unroll-only, like the reference)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(output_prefix)
        self._l, self._r = l_cell, r_cell

    @property
    def state_info(self):
        return self._l.state_info + self._r.state_info

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell supports unroll() only (per-step calls "
            "cannot see the future half of the sequence)")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if not isinstance(inputs, (list, tuple)):
            axis = layout.find("T")
            inputs = [sym.squeeze(sym.slice_axis(inputs, axis=axis, begin=t,
                                                 end=t + 1), axis=axis)
                      for t in range(length)]
        n_l = len(self._l.state_info)
        bs_l = begin_state[:n_l] if begin_state is not None else None
        bs_r = begin_state[n_l:] if begin_state is not None else None
        l_out, l_states = self._l.unroll(length, list(inputs),
                                         begin_state=bs_l, layout=layout,
                                         merge_outputs=False)
        r_out, r_states = self._r.unroll(length, list(inputs)[::-1],
                                         begin_state=bs_r, layout=layout,
                                         merge_outputs=False)
        r_out = list(r_out)[::-1]
        outputs = [sym.concat(lo, ro, dim=-1)
                   for lo, ro in zip(l_out, r_out)]
        if merge_outputs:
            outputs = sym.stack(*outputs, axis=layout.find("T"))
        return outputs, list(l_states) + list(r_states)


class ResidualCell(BaseRNNCell):
    """Adds the cell input to its output (reference modifier cell)."""

    def __init__(self, base_cell):
        super().__init__("")
        self._base = base_cell

    @property
    def state_info(self):
        return self._base.state_info

    def begin_state(self, *a, **kw):
        return self._base.begin_state(*a, **kw)

    def __call__(self, inputs, states):
        out, states = self._base(inputs, states)
        return out + inputs, states


class ZoneoutCell(BaseRNNCell):
    """Zoneout regularization (reference modifier): with probability p a
    state keeps its PREVIOUS value instead of updating. Inference form
    (deterministic expectation) — the reference's training-time Bernoulli
    masks require the dropout RNG stream; Dropout on outputs covers the
    stochastic case."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__("")
        self._base = base_cell
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    @property
    def state_info(self):
        return self._base.state_info

    def begin_state(self, *a, **kw):
        return self._base.begin_state(*a, **kw)

    def reset(self):
        super().reset()
        self._base.reset()
        self._prev_output = None  # a new sequence starts from zero output

    def __call__(self, inputs, states):
        prev = self._base._materialize(inputs, states)
        out, new_states = self._base(inputs, prev)
        if self._zs:
            new_states = [p * self._zs + n * (1.0 - self._zs)
                          for p, n in zip(prev, new_states)]
        if self._zo:
            # expectation blend like the state path: prev*p + next*(1-p),
            # with the previous OUTPUT tracked across steps (zero at t=0,
            # the reference's prev_output initial value) — not the
            # out*(1-p) attenuation that assumed prev were always zero
            prev_out = (self._prev_output if self._prev_output is not None
                        else out * 0.0)
            out = prev_out * self._zo + out * (1.0 - self._zo)
            self._prev_output = out
        return out, new_states
