"""``mx.engine`` — execution-engine controls.

Reference: ``python/mxnet/engine.py`` (``bulk`` scope batching engine pushes,
``set_bulk_size`` — TBV, SURVEY.md §2.1 Engine). TPU mapping: XLA's async
dispatch already pipelines eager ops, and the real bulking mechanisms are

- ``hybridize`` — the forward/backward graph compiles to one program, and
- the **fused update engine** (``mxnet_tpu/optimizer/fused.py``) — every
  optimizer update in a ``Trainer.step`` / ``Module.update`` runs as ONE
  donated XLA program per step (docs/PERFORMANCE.md).

so ``bulk`` is a compatibility scope — it suspends the MX_SYNC debug-sync
behavior for its duration (the closest analog of batching engine pushes) and
restores it after.  ``set_bulk_size`` is kept for script parity; it does not
influence the fused paths (they always bulk the whole parameter set).

Note: a training loop that keeps retracing the fused update (e.g. by
rebinding static optimizer hyperparameters every step) defeats the bulking;
the TraceLinter's ``update-retrace-churn`` rule diagnoses this — per-step
scalars like the learning rate are traced arguments and never retrace.
"""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size"]

_bulk_size = 15


def set_bulk_size(size):
    """Returns the previous bulk size (reference contract)."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


@contextlib.contextmanager
def bulk(size=None):
    """Scope under which eager ops dispatch without per-op sync."""
    from .ndarray import ndarray as _nd

    prev = _nd._MX_SYNC
    _nd._MX_SYNC = False
    try:
        yield
    finally:
        _nd._MX_SYNC = prev
