"""``mx.name`` — symbol auto-naming scopes.

Reference: ``python/mxnet/name.py`` (NameManager auto-suffixes op names;
Prefix prepends — TBV).
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]


class _State(threading.local):
    def __init__(self):
        self.current = None


_STATE = _State()


class NameManager:
    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return f"{hint}{n}"

    def __enter__(self):
        self._old = _STATE.current
        _STATE.current = self
        return self

    def __exit__(self, *exc):
        _STATE.current = self._old

    @staticmethod
    def current_manager():
        if _STATE.current is None:
            _STATE.current = NameManager()
        return _STATE.current


class Prefix(NameManager):
    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)
