"""Custom operator API (``mx.operator``).

Reference: ``python/mxnet/operator.py`` + ``src/operator/custom/custom.cc``
(Python callbacks on a dedicated engine thread — TBV, SURVEY.md §2.2).

TPU redesign: a custom op is registered like any built-in — its ``forward``
runs as a host callback in eager mode; when the user supplies pure-jax
compute it traces under jit too. ``CustomOpProp`` keeps the reference's
(list_arguments / infer_shape / create_operator) contract so existing
custom-op classes port over.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from .ndarray import NDArray
from .ops.registry import OpDef, register as _register_op

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered_operators"]

_CUSTOM: Dict[str, type] = {}


class CustomOp:
    """User op: override forward/backward using ``self.assign``."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst: NDArray, req: str, src):
        if req in ("null",):
            return
        src_nd = src if isinstance(src, NDArray) else NDArray(src)
        if req == "add":
            dst._set_data(dst._data + src_nd._data)
        else:
            dst._set_data(src_nd._data)


class CustomOpProp:
    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str):
    """Decorator: ``@mx.operator.register("myop")`` on a CustomOpProp class.
    Makes ``nd.Custom(..., op_type="myop")`` (and the generated wrapper)
    available, like the reference's MXCustomOpRegister."""

    def deco(prop_cls):
        _CUSTOM[reg_name] = prop_cls
        return prop_cls

    return deco


def get_all_registered_operators():
    return sorted(_CUSTOM)


def _run_custom(*datas, op_type=None, **kwargs):
    if op_type not in _CUSTOM:
        raise ValueError(f"custom op {op_type!r} is not registered "
                         f"({sorted(_CUSTOM)})")
    prop = _CUSTOM[op_type]()
    in_shapes = [tuple(d.shape) for d in datas]
    _, out_shapes, _ = prop.infer_shape(list(in_shapes))
    op = prop.create_operator(None, in_shapes, [d.dtype for d in datas])
    in_nd = [NDArray(d) for d in datas]
    out_nd = [NDArray(np.zeros(s, np.float32)) if not _tracing(datas)
              else NDArray(_zeros_like_traced(s, datas[0].dtype))
              for s in out_shapes]
    from . import autograd

    op.forward(autograd.is_training(), ["write"] * len(out_nd), in_nd, out_nd, [])
    outs = tuple(o._data for o in out_nd)
    return outs[0] if len(outs) == 1 else outs


def _tracing(datas):
    import jax

    return any(isinstance(d, jax.core.Tracer) for d in datas)


def _zeros_like_traced(shape, dtype):
    import jax.numpy as jnp

    return jnp.zeros(shape, dtype)


_register_op("Custom", num_outputs=lambda kw: 1)(_run_custom)
