"""Data-plane copy/sync accounting (``MXNET_COPYTRACK=1``) — the runtime
twin of ``mxnet_tpu.analysis.dataplane``.

The static pass proves *where* array bytes can be copied or a host sync
can happen on a hot path; this module measures *how much*, per process,
at the choke points every request transits:

- wire framing (``kvstore/ps_server.py`` ``_pack_array``/``_pack_arrays``/
  ``_send_msg``/``_recv_exact``/``_unpack_array``) — serialize calls and
  the bytes each redundant buffer copy moves;
- batcher assembly (``serve/batcher.py`` per-batch ``np.concatenate``);
- device boundary (``serve/engine.py`` ``device_get``/
  ``block_until_ready`` host syncs, h2d pad/put copies).

Counters: ``wire.bytes_copied`` (every byte moved by a host-side buffer
copy), ``wire.serialize_calls`` / ``wire.serialize_bytes`` (array→wire
packs), ``hotpath.host_syncs`` (device→host materialization points, by
site). They feed two consumers:

- ``copytrack.snapshot()`` — always available while enabled; the
  ``bench.py`` ``wire_hop`` leg divides deltas by request count to get
  bytes-copied-per-request, the committed denominator for ROADMAP item
  4's "≥2× hop-cost reduction";
- the ``mxnet_tpu.obs`` metrics registry (same counter names) when
  telemetry is ALSO on — so the numbers ride STATS replies, Prometheus
  exposition, and merged fleet timelines for free.

Zero-overhead-when-off contract (the ``tsan.py`` idiom): every
instrumented site calls ``copytrack.TRACKER.<method>(...)``. When
``MXNET_COPYTRACK`` is unset, ``TRACKER`` is the no-op singleton
``NULL`` — one attribute lookup plus an empty method call, no locks, no
env reads, no branches. Tests assert ``TRACKER is NULL`` stays true
after exercising the serve path with the flag off.
"""
from __future__ import annotations

import threading
from typing import Dict

from .base import get_env

__all__ = ["enabled", "enable", "disable", "reset", "snapshot",
           "TRACKER", "NULL"]


class _NullTracker:
    """No-op singleton bound to ``TRACKER`` while tracking is off."""

    __slots__ = ()
    enabled = False

    def copied(self, nbytes):
        pass

    def serialized(self, nbytes, calls=1):
        pass

    def host_sync(self, site=""):
        pass

    def snapshot(self) -> Dict[str, float]:
        return {}


class _Tracker:
    """Live counters; one lock, increments only (hot-path friendly)."""

    __slots__ = ("_mu", "bytes_copied", "serialize_calls",
                 "serialize_bytes", "host_syncs", "sync_sites")
    enabled = True

    def __init__(self):
        self._mu = threading.Lock()
        self.bytes_copied = 0
        self.serialize_calls = 0
        self.serialize_bytes = 0
        self.host_syncs = 0
        self.sync_sites: Dict[str, int] = {}

    def copied(self, nbytes) -> None:
        n = int(nbytes)
        with self._mu:
            self.bytes_copied += n
        _obs_inc("wire.bytes_copied", n)

    def serialized(self, nbytes, calls=1) -> None:
        n = int(nbytes)
        with self._mu:
            self.serialize_calls += calls
            self.serialize_bytes += n
        _obs_inc("wire.serialize_calls", calls)
        _obs_inc("wire.serialize_bytes", n)

    def host_sync(self, site="") -> None:
        with self._mu:
            self.host_syncs += 1
            if site:
                self.sync_sites[site] = self.sync_sites.get(site, 0) + 1
        _obs_inc("hotpath.host_syncs", 1)

    def snapshot(self) -> Dict[str, float]:
        with self._mu:
            return {
                "wire.bytes_copied": self.bytes_copied,
                "wire.serialize_calls": self.serialize_calls,
                "wire.serialize_bytes": self.serialize_bytes,
                "hotpath.host_syncs": self.host_syncs,
                "hotpath.sync_sites": dict(self.sync_sites),
            }


def _obs_inc(name: str, n: int) -> None:
    # forward into the metrics registry so STATS/Prometheus surface the
    # counters when telemetry is on; obs.inc is itself no-op-when-off
    from . import obs

    obs.inc(name, n)


NULL = _NullTracker()
TRACKER = NULL  # rebound by enable()/disable(); call sites read it live


def enabled() -> bool:
    return TRACKER is not NULL


def enable() -> "_Tracker":
    """Swap in a live tracker (idempotent; keeps existing counters)."""
    global TRACKER
    if TRACKER is NULL:
        TRACKER = _Tracker()
    return TRACKER


def disable() -> None:
    global TRACKER
    TRACKER = NULL


def reset() -> None:
    """Zero the counters without changing the enabled state."""
    global TRACKER
    if TRACKER is not NULL:
        TRACKER = _Tracker()


def snapshot() -> Dict[str, float]:
    """Current counters (``{}`` while disabled)."""
    return TRACKER.snapshot()


if get_env("MXNET_COPYTRACK", False, bool):
    enable()
