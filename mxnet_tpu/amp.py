"""AMP — automatic mixed precision, TPU-native.

Reference: ``python/mxnet/contrib/amp/`` (op allow/deny lists patching fp16
casts into the graph, dynamic loss scaling — TBV, SURVEY.md §2.3).

TPU redesign: the MXU's native fast dtype is **bfloat16**, which shares
float32's exponent range — so the reference's loss-scaling machinery is
unnecessary (kept as an API-compatible no-op shim for fp16 parity). AMP
here = cast-to-bf16 policy on parameters/inputs; accumulations stay fp32
inside XLA (dot_general's preferred_element_type).
"""
from __future__ import annotations

import warnings

import numpy as np

__all__ = ["init", "init_trainer", "convert_model", "convert_hybrid_block",
           "amp_cast", "LossScaler", "scale_loss", "unscale"]

_TARGET = {"dtype": None}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Install the global AMP dtype (reference amp.init patches op lists;
    here eager math follows jax dtype promotion once inputs are bf16)."""
    if target_dtype in ("float16", np.float16):
        warnings.warn("float16 has no MXU fast path on TPU; using bfloat16")
        target_dtype = "bfloat16"
    _TARGET["dtype"] = target_dtype


def init_trainer(trainer, loss_scaler=None):
    """Attach a dynamic loss scaler to a gluon Trainer.

    bf16 (the TPU default) needs no loss scaling (exponent range == fp32),
    so with no explicit ``loss_scaler`` this stays a no-op.  When a scaler
    is attached (fp16 parity runs), the Trainer's fused update program takes
    over the whole scaler protocol in-graph: gradient unscale, the found-inf
    reduction, the skip-step masking, and the scale/window bookkeeping — the
    scale and counters live device-resident and no step pays a host sync
    (docs/PERFORMANCE.md)."""
    if loss_scaler is None and _TARGET["dtype"] in ("float16", np.float16):
        loss_scaler = LossScaler()
    if loss_scaler is not None and hasattr(trainer, "_amp_loss_scaler"):
        trainer._amp_loss_scaler = loss_scaler
    return loss_scaler


def convert_hybrid_block(block, target_dtype="bfloat16", ctx=None,
                         cast_optional_params=False):
    """Cast a Gluon block's parameters to bf16 (BatchNorm stats stay fp32,
    like the reference keeps BN in fp32)."""
    for p in block._iter_params():
        name = p.name
        if name.endswith(("running_mean", "running_var", "moving_mean",
                          "moving_var", "gamma", "beta")):
            continue
        p.cast(target_dtype)
    return block


convert_model = convert_hybrid_block


def amp_cast(x, dtype="bfloat16"):
    return x.astype(dtype)


class LossScaler:
    """API-compatible shim of the reference's dynamic loss scaler. On TPU
    (bf16) scale stays 1.0; the update logic is kept for fp16 parity tests."""

    def __init__(self, init_scale=2 ** 16, scale_factor=2.0, scale_window=2000):
        self.loss_scale = 1.0
        self._init_scale = init_scale
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0
        # consecutive skipped steps (maintained in-graph by the fused
        # engine, host-side by the eager oracle) — the health plane's
        # skip-loop signal (health.scaler.skip_streak)
        self.skip_streak = 0

    def has_overflow(self, params):
        """One batched finiteness reduction + a single device→host sync
        (was: one blocking asnumpy per parameter). The fused update path
        never calls this — its found-inf decision stays on device."""
        import jax
        import jax.numpy as jnp

        flags = []
        for p in params:
            g = p.grad() if callable(getattr(p, "grad", None)) else None
            if g is not None:
                flags.append(jnp.all(jnp.isfinite(g._data.astype(jnp.float32))))
        if not flags:
            return False
        return not bool(np.all(jax.device_get(flags)))

    def update_scale(self, skip):
        if skip:
            self.loss_scale = max(self.loss_scale / self._factor, 1e-4)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self.loss_scale = min(self.loss_scale * self._factor, 2 ** 24)
                self._unskipped = 0


def scale_loss(loss, scaler: LossScaler):
    return loss * scaler.loss_scale


def unscale(grads, scaler: LossScaler):
    return [g / scaler.loss_scale for g in grads]
