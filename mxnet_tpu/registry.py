"""``mx.registry`` — generic named-class registries (reference
``python/mxnet/registry.py`` — TBV: ``get_register_func`` /
``get_create_func`` / ``get_alias_func`` power the optimizer/initializer/
metric registries; exposed so user code can build its own).
"""
from __future__ import annotations

from typing import Dict, Type

__all__ = ["get_register_func", "get_alias_func", "get_create_func"]

_REGISTRIES: Dict[type, Dict[str, type]] = {}


def _registry(base_class) -> Dict[str, type]:
    return _REGISTRIES.setdefault(base_class, {})


def get_register_func(base_class, nickname):
    """Returns a ``register(cls, name=None)`` decorator for ``base_class``."""
    reg = _registry(base_class)

    def register(klass: Type, name=None):
        if not issubclass(klass, base_class):
            raise TypeError(
                f"cannot register {klass.__name__}: not a subclass of "
                f"{base_class.__name__}")
        reg[(name or klass.__name__).lower()] = klass
        return klass

    register.__name__ = f"register_{nickname}"
    return register


def get_alias_func(base_class, nickname):
    """Returns an ``alias(*names)`` class decorator."""
    reg = _registry(base_class)

    def alias(*names):
        def deco(klass):
            for n in names:
                reg[n.lower()] = klass
            return klass
        return deco

    alias.__name__ = f"alias_{nickname}"
    return alias


def get_create_func(base_class, nickname):
    """Returns ``create(name_or_instance, *args, **kwargs)``. Accepts an
    instance (passthrough), a registered name, or ``"name, k=v"`` strings
    (the reference's optimizer-string form)."""
    reg = _registry(base_class)

    def create(obj, *args, **kwargs):
        if isinstance(obj, base_class):
            return obj
        if not isinstance(obj, str):
            raise TypeError(f"need a {nickname} name or instance, got "
                            f"{type(obj).__name__}")
        name, _, tail = obj.partition(",")
        for kv in filter(None, (p.strip() for p in tail.split(","))):
            k, _, v = kv.partition("=")
            try:
                kwargs[k.strip()] = float(v) if "." in v or "e" in v.lower() \
                    else int(v)
            except ValueError:
                kwargs[k.strip()] = v.strip()
        key = name.strip().lower()
        if key not in reg:
            raise ValueError(
                f"unknown {nickname} {name!r}; registered: {sorted(reg)}")
        return reg[key](*args, **kwargs)

    create.__name__ = f"create_{nickname}"
    return create
