"""Framework RNG: a counter-based PRNG stream over jax.random keys.

Reference: per-context RNG resources (``ResourceRequest::kRandom``,
``src/resource.cc``, ``MXNET_SEED`` — TBV, SURVEY.md §2.1/§5.6). TPU-native
redesign: JAX's splittable threefry keys replace per-device curand states.

Two regimes:
- **Eager:** a process-global key advanced (split) per draw; seeded by
  ``mx.random.seed(n)`` / env ``MXNET_SEED``.
- **Traced (hybridize / jit):** the jitted step function takes the key as an
  argument; a trace-scope installs that traced key here, and each draw
  ``fold_in``s a call-site counter — so the compiled function is pure and the
  stream is reproducible across replays.
"""
from __future__ import annotations

import threading

import jax
import numpy as np

from .base import get_env

__all__ = ["seed", "next_key", "trace_key_scope", "get_state",
           "get_state_data", "set_state_data", "uniform", "normal",
           "randint", "randn", "bernoulli", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial", "multinomial",
           "shuffle"]


class _KeyState(threading.local):
    def __init__(self):
        self.key = None
        self.trace_key = None
        self.trace_counter = 0
        self.np_rng = None


_STATE = _KeyState()


def np_rng() -> np.random.Generator:
    """Host-side numpy generator tied to the framework seed. Used by
    initializers so ``mx.random.seed(n)`` makes parameter init reproducible
    (reference behavior: initializers draw from the seeded MXNet RNG)."""
    if _STATE.np_rng is None:
        s = get_env("MXNET_SEED", None, int)
        _STATE.np_rng = np.random.default_rng(s)
    return _STATE.np_rng


def _root_key():
    if _STATE.key is None:
        s = get_env("MXNET_SEED", None, int)
        _STATE.key = jax.random.key(s if s is not None else np.random.randint(0, 2**31))
    return _STATE.key


def seed(seed_state: int, ctx="all") -> None:
    """Seed the global stream (reference mx.random.seed; MXNET_SEED env)."""
    _STATE.key = jax.random.key(int(seed_state))
    _STATE.np_rng = np.random.default_rng(int(seed_state))


def next_key():
    """Next PRNG key. Trace-safe: inside a trace scope, folds a counter into
    the traced key instead of advancing global state."""
    if _STATE.trace_key is not None:
        _STATE.trace_counter += 1
        return jax.random.fold_in(_STATE.trace_key, _STATE.trace_counter)
    k = _root_key()
    _STATE.key, sub = jax.random.split(k)
    return sub


class trace_key_scope:
    """Install a (possibly traced) key as the draw source, e.g. inside CachedOp."""

    def __init__(self, key):
        self.key = key

    def __enter__(self):
        self.saved = (_STATE.trace_key, _STATE.trace_counter)
        _STATE.trace_key = self.key
        _STATE.trace_counter = 0
        return self

    def __exit__(self, *exc):
        _STATE.trace_key, _STATE.trace_counter = self.saved


def get_state():
    return _root_key()


def get_state_data():
    """Serializable view of the global key stream (checkpoint capture):
    the raw uint32 key data, or None when the stream was never seeded/used
    (a resumed process will lazily seed exactly like a fresh one)."""
    if _STATE.key is None:
        return None
    key = _STATE.key
    try:
        data = jax.random.key_data(key)
    except (TypeError, AttributeError):  # already a raw uint32 key array
        data = key
    return np.asarray(data)


def set_state_data(data) -> None:
    """Restore the stream captured by :func:`get_state_data` (checkpoint
    resume) — draws after this replay bit-identically."""
    arr = np.asarray(data, np.uint32)
    try:
        _STATE.key = jax.random.wrap_key_data(arr)
    except (TypeError, AttributeError):  # older jax: raw arrays are keys
        _STATE.key = arr


# ---------------------------------------------------------------------------
# Sampling front-ends (mx.random.* / mx.nd.random.*). Reference:
# src/operator/random/sample_op.* (TBV). Return NDArray.
# ---------------------------------------------------------------------------

def _as_nd(arr, ctx=None):
    from .ndarray import NDArray

    return NDArray(arr, ctx=ctx)


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    import jax.numpy as jnp

    from .base import dtype_np

    r = jax.random.uniform(next_key(), _shape(shape), dtype_np(dtype), low, high)
    return _store(out, r, ctx)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from .base import dtype_np

    r = loc + scale * jax.random.normal(next_key(), _shape(shape), dtype_np(dtype))
    return _store(out, r, ctx)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None, **kw):
    return normal(loc, scale, shape, dtype, ctx)


def randint(low, high=None, shape=None, dtype="int32", ctx=None, out=None, **kw):
    from .base import dtype_np

    if high is None:
        low, high = 0, low
    r = jax.random.randint(next_key(), _shape(shape), int(low), int(high), dtype_np(dtype))
    return _store(out, r, ctx)


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from .base import dtype_np

    r = jax.random.bernoulli(next_key(), prob, _shape(shape)).astype(dtype_np(dtype))
    return _store(out, r, ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from .base import dtype_np

    r = jax.random.gamma(next_key(), alpha, _shape(shape), dtype_np(dtype)) * beta
    return _store(out, r, ctx)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from .base import dtype_np

    r = jax.random.exponential(next_key(), _shape(shape), dtype_np(dtype)) * scale
    return _store(out, r, ctx)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    from .base import dtype_np

    r = jax.random.poisson(next_key(), lam, _shape(shape)).astype(dtype_np(dtype))
    return _store(out, r, ctx)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    g = jax.random.gamma(next_key(), k, _shape(shape)) * ((1 - p) / p)
    from .base import dtype_np

    r = jax.random.poisson(next_key(), g).astype(dtype_np(dtype))
    return _store(out, r, ctx)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype="float32",
                                  ctx=None, out=None, **kw):
    import jax.numpy as jnp

    a = 1.0 / max(alpha, 1e-12)
    g = jax.random.gamma(next_key(), a, _shape(shape)) * (mu / a)
    from .base import dtype_np

    r = jax.random.poisson(next_key(), g).astype(dtype_np(dtype))
    return _store(out, r, ctx)


def multinomial(data, shape=1, get_prob=False, dtype="int32", **kw):
    """Sample class indices from probability rows; with get_prob=True also
    return log-probabilities of the draws (reinforce-style usage)."""
    import jax.numpy as jnp

    from .base import dtype_np
    from .ndarray import NDArray

    probs = data.asjax() if isinstance(data, NDArray) else jnp.asarray(data)
    n = int(np.prod(_shape(shape))) if not isinstance(shape, int) else int(shape)
    logits = jnp.log(jnp.maximum(probs, 1e-38))
    if probs.ndim == 1:
        draws = jax.random.categorical(next_key(), logits, shape=(n,))  # (n,)
    else:
        draws = jax.vmap(lambda lg, k: jax.random.categorical(k, lg, shape=(n,)))(
            logits, jax.random.split(next_key(), probs.shape[0]))  # (B, n)
    tail = _shape(shape) if not isinstance(shape, int) else ((shape,) if shape != 1 else ())
    out_shape = (probs.shape[:1] + tail) if probs.ndim > 1 else tail
    result = draws.reshape(out_shape) if out_shape else draws.reshape(())
    if get_prob:
        logp = jax.nn.log_softmax(logits, axis=-1)
        if probs.ndim == 1:
            lp = logp[draws]  # (n,)
        else:
            lp = jnp.take_along_axis(logp, draws.astype(jnp.int32), axis=-1)  # (B, n)
        lp = lp.reshape(out_shape) if out_shape else lp.reshape(())
        return _as_nd(result.astype(dtype_np(dtype))), _as_nd(lp)
    return _as_nd(result.astype(dtype_np(dtype)))


def shuffle(data, **kw):
    from .ndarray import NDArray

    arr = data.asjax() if isinstance(data, NDArray) else data
    perm = jax.random.permutation(next_key(), arr.shape[0])
    return _as_nd(arr[perm])


def _store(out, arr, ctx):
    if out is not None:
        out._set_data(arr)
        return out
    return _as_nd(arr, ctx)
