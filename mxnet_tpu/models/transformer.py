"""Transformer encoder / BERT / decoder-LM — the flagship family.

Reference counterpart: gluon-nlp's BERTModel/TransformerEncoder (external
repo, driven through the mx API — SURVEY.md §2.5 BERT-base config). Built
TPU-first:

- One fused QKV projection (one MXU matmul instead of three).
- bf16-friendly: params stay fp32; cast policy applied by AMP/trainer.
- Tensor parallel: ``bert_sharding_rules()`` shards QKV/FFN-in over the
  mesh ``tp`` axis on the output dim and out-proj/FFN-out on the input
  dim (Megatron layout: one all-reduce per block, inserted by XLA).
- Sequence parallel: when the active mesh (parallel.mesh_scope) has an
  ``sp`` axis > 1, attention runs as ring attention over the ICI
  (parallel/ring_attention.py) — long-context support the reference lacks.
"""
from __future__ import annotations

import math

import numpy as np

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.parameter import Parameter

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "BERTEncoder", "BERTModel", "TransformerLM", "bert_base", "bert_large",
           "bert_tiny", "transformer_lm", "bert_sharding_rules",
           "decode_config", "decode_params", "prefill_layer", "decode_layer",
           "lm_prefill", "lm_decode_step", "sample_token"]


class MultiHeadAttention(HybridBlock):
    """Fused-QKV multi-head self-attention with optional ring execution."""

    def __init__(self, units, num_heads, dropout=0.0, causal=False, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._heads = num_heads
        self._causal = causal
        self.qkv = nn.Dense(3 * units, flatten=False, use_bias=True,
                            in_units=units, prefix=self.prefix + "qkv_")
        self.proj = nn.Dense(units, flatten=False, use_bias=True, in_units=units,
                             prefix=self.prefix + "proj_")
        self._dropout = dropout

    def hybrid_forward(self, F, x, mask=None):
        # x: (B, S, U)
        b, s, u = x.shape
        h, d = self._heads, self._units // self._heads
        qkv = self.qkv(x)  # (B, S, 3U)
        # split (not tensor indexing) keeps this F-generic: the same code
        # traces eagerly and symbolically (Symbol has no tensor indexing)
        qkv = qkv.reshape((b, s, 3, h, d))
        q, k, v = F.split(qkv, num_outputs=3, axis=2, squeeze_axis=True)
        q = q.transpose((0, 2, 1, 3))  # (B, H, S, D)
        k = k.transpose((0, 2, 1, 3))
        v = v.transpose((0, 2, 1, 3))

        from .. import parallel as par
        from ..ndarray.ndarray import invoke_fn

        mesh = par.current_mesh()
        sp = 1
        if mesh is not None:
            sp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("sp", 1)

        from ..ops.attention import fused_attention

        if mesh is not None and sp > 1:
            out = invoke_fn(
                lambda qq, kk, vv: par.sequence_sharded_attention(
                    qq, kk, vv, mesh, causal=self._causal),
                [q, k, v])
        else:
            # single-chip path: flash (Pallas) for long sequences, fused
            # XLA softmax-attention otherwise — see ops/attention.py policy
            def attn(qq, kk, vv, mm=None):
                return fused_attention(qq, kk, vv, mask=mm,
                                       causal=self._causal)

            ins = [q, k, v] + ([mask] if mask is not None else [])
            out = invoke_fn(attn, ins)
        out = out.transpose((0, 2, 1, 3)).reshape((b, s, u))
        out = self.proj(out)
        if self._dropout:
            out = F.Dropout(out, p=self._dropout)
        return out


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu", **kwargs):
        super().__init__(**kwargs)
        self.ffn_1 = nn.Dense(hidden_size, flatten=False, in_units=units,
                              prefix=self.prefix + "ffn1_")
        self.ffn_2 = nn.Dense(units, flatten=False, in_units=hidden_size,
                              prefix=self.prefix + "ffn2_")
        self._act = activation
        self._dropout = dropout

    def hybrid_forward(self, F, x):
        out = self.ffn_1(x)
        out = F.Activation(out, act_type=self._act) if self._act != "gelu" \
            else F.gelu(out, approximation="tanh")
        out = self.ffn_2(out)
        if self._dropout:
            out = F.Dropout(out, p=self._dropout)
        return out


class TransformerEncoderCell(HybridBlock):
    """Post-LN transformer block (BERT layout)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0, causal=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.attention = MultiHeadAttention(units, num_heads, dropout=dropout,
                                            causal=causal,
                                            prefix=self.prefix + "attn_")
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                   prefix=self.prefix + "ffn_")
        self.ln2 = nn.LayerNorm(in_channels=units)
        self._dropout = dropout

    def hybrid_forward(self, F, x, mask=None):
        att = self.attention(x, mask)
        x = self.ln1(x + att)
        out = self.ffn(x)
        return self.ln2(x + out)


class BERTEncoder(HybridBlock):
    def __init__(self, units=768, hidden_size=3072, num_layers=12, num_heads=12,
                 max_length=512, dropout=0.1, causal=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        self.position_weight = self.params.get(
            "position_weight", shape=(max_length, units), init="zeros")
        self.cells = []
        for i in range(num_layers):
            cell = TransformerEncoderCell(units, hidden_size, num_heads,
                                          dropout=dropout, causal=causal,
                                          prefix=f"{self.prefix}layer{i}_")
            self.register_child(cell, f"layer{i}")
            self.cells.append(cell)
        self._dropout = dropout

    def hybrid_forward(self, F, x, position_weight, mask=None):
        b, s, u = x.shape
        pos = F.slice_axis(position_weight, axis=0, begin=0,
                           end=s).reshape((1, s, u))
        x = x + pos
        if self._dropout:
            x = F.Dropout(x, p=self._dropout)
        for cell in self.cells:
            x = cell(x, mask)
        return x


class BERTModel(HybridBlock):
    """BERT with MLM head (gluon-nlp BERTModel counterpart)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512, dropout=0.1,
                 num_token_types=2, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self.word_embed = nn.Embedding(vocab_size, units,
                                       prefix=self.prefix + "word_embed_")
        self.token_type_embed = nn.Embedding(num_token_types, units,
                                             prefix=self.prefix + "type_embed_")
        self.embed_ln = nn.LayerNorm(in_channels=units)
        self.encoder = BERTEncoder(units, hidden_size, num_layers, num_heads,
                                   max_length, dropout,
                                   prefix=self.prefix + "enc_")
        self.mlm_dense = nn.Dense(units, flatten=False, in_units=units,
                                  prefix=self.prefix + "mlm_dense_")
        self.mlm_ln = nn.LayerNorm(in_channels=units)
        self.mlm_decoder = nn.Dense(vocab_size, flatten=False, in_units=units,
                                    prefix=self.prefix + "mlm_decoder_")

    def hybrid_forward(self, F, inputs, token_types=None):
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = self.embed_ln(x)
        seq = self.encoder(x)
        h = self.mlm_dense(seq)
        h = F.gelu(h, approximation="tanh")
        h = self.mlm_ln(h)
        return self.mlm_decoder(h)


class TransformerLM(HybridBlock):
    """Decoder-only causal LM (GPT-style) — the long-context flagship."""

    def __init__(self, vocab_size=32000, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=2048, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.word_embed = nn.Embedding(vocab_size, units,
                                       prefix=self.prefix + "word_embed_")
        self.encoder = BERTEncoder(units, hidden_size, num_layers, num_heads,
                                   max_length, dropout, causal=True,
                                   prefix=self.prefix + "enc_")
        self.final_ln = nn.LayerNorm(in_channels=units)
        self.decoder = nn.Dense(vocab_size, flatten=False, in_units=units,
                                prefix=self.prefix + "decoder_")

    def hybrid_forward(self, F, inputs):
        x = self.word_embed(inputs)
        x = self.encoder(x)
        x = self.final_ln(x)
        return self.decoder(x)


def bert_sharding_rules():
    """Megatron-style TP + dp-replicated rules for BERT/TransformerLM params.

    Works with parallel.ShardingRules spec pruning: on meshes without "tp"
    everything collapses to replicated.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel import ShardingRules

    return ShardingRules([
        (r"qkv_weight$", P("tp", None)),        # column parallel
        (r"ffn1_weight$", P("tp", None)),
        (r"qkv_bias$", P("tp")),
        (r"ffn1_bias$", P("tp")),
        (r"proj_weight$", P(None, "tp")),       # row parallel
        (r"ffn2_weight$", P(None, "tp")),
        (r"(word_embed|mlm_decoder|decoder)\d*_weight$", P("tp", None)),
    ], default=P())


def bert_tiny(vocab_size=1000, **kw):
    kw.setdefault("units", 64)
    kw.setdefault("hidden_size", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_length", 128)
    return BERTModel(vocab_size=vocab_size, **kw)


def bert_base(vocab_size=30522, **kw):
    return BERTModel(vocab_size=vocab_size, units=768, hidden_size=3072,
                     num_layers=12, num_heads=12, **kw)


def bert_large(vocab_size=30522, **kw):
    return BERTModel(vocab_size=vocab_size, units=1024, hidden_size=4096,
                     num_layers=24, num_heads=16, **kw)


def transformer_lm(vocab_size=32000, **kw):
    return TransformerLM(vocab_size=vocab_size, **kw)


# ---------------------------------------------------------------------------
# Causal-LM decode interface (serve/decode.py consumes this)
#
# The gluon forward above is the TRAINING path: full (B, S) sequences, no
# cache. Generation wants the incremental form — prefill the prompt once,
# then one position per step against cached K/V. These are pure JAX
# functions over a flat param dict (extracted once from an initialized
# TransformerLM) so the decode engine can jit exactly two programs around
# them and compose its own attention (dense reference here, paged flash in
# ops/flash_attention.py) without re-tracing any gluon machinery.
# ---------------------------------------------------------------------------

_LN_EPS = 1e-5  # nn.LayerNorm default


def decode_config(lm: "TransformerLM") -> dict:
    """Static shape/config facts of an LM, for building decode programs."""
    enc = lm.encoder
    cell = enc.cells[0]
    att = cell.attention
    return {
        "vocab": lm.decoder._units,
        "units": att._units,
        "heads": att._heads,
        "head_dim": att._units // att._heads,
        "layers": len(enc.cells),
        "max_length": enc._max_length,
    }


def decode_params(lm: "TransformerLM") -> dict:
    """Extract a flat numpy param dict from an initialized TransformerLM.

    The block must have run at least one forward pass (deferred init).
    Layout: top-level embed/pos/final-LN/decoder arrays plus one dict per
    layer under ``"layers"``.
    """

    def _np(p: Parameter) -> np.ndarray:
        return p.data().asnumpy()

    layers = []
    for cell in lm.encoder.cells:
        att, ffn = cell.attention, cell.ffn
        layers.append({
            "qkv_w": _np(att.qkv.weight), "qkv_b": _np(att.qkv.bias),
            "proj_w": _np(att.proj.weight), "proj_b": _np(att.proj.bias),
            "ln1_g": _np(cell.ln1.gamma), "ln1_b": _np(cell.ln1.beta),
            "ffn1_w": _np(ffn.ffn_1.weight), "ffn1_b": _np(ffn.ffn_1.bias),
            "ffn2_w": _np(ffn.ffn_2.weight), "ffn2_b": _np(ffn.ffn_2.bias),
            "ln2_g": _np(cell.ln2.gamma), "ln2_b": _np(cell.ln2.beta),
        })
    return {
        "embed": _np(lm.word_embed.weight),
        "pos": _np(lm.encoder.position_weight),
        "final_g": _np(lm.final_ln.gamma), "final_b": _np(lm.final_ln.beta),
        "dec_w": _np(lm.decoder.weight), "dec_b": _np(lm.decoder.bias),
        "layers": layers,
    }


def _ln(x, g, b):
    import jax.numpy as jnp
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + _LN_EPS) * g + b


def _dense(x, w, b):
    # gluon Dense stores weight as (out, in): y = x @ w.T + b
    return x @ w.T + b


def _gelu(x):
    import jax.nn
    return jax.nn.gelu(x, approximate=True)


def _split_heads(qkv, heads, head_dim):
    import jax.numpy as jnp
    # qkv (..., 3U) -> q, k, v each (..., H, D)
    parts = qkv.reshape(qkv.shape[:-1] + (3, heads, head_dim))
    return (jnp.squeeze(p, axis=-3)
            for p in jnp.split(parts, 3, axis=-3))


def prefill_layer(cfg, lp, x, mask):
    """One post-LN block over a full prompt. x (B, S, U), mask (S, S) or
    (B, S, S) additive-boolean (True = attend). Returns (x', k, v) with
    k/v shaped (B, S, H, D)."""
    import jax
    import jax.numpy as jnp
    h, d = cfg["heads"], cfg["head_dim"]
    q, k, v = _split_heads(_dense(x, lp["qkv_w"], lp["qkv_b"]), h, d)
    scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(mask[:, None] if mask.ndim == 3 else mask,
                       scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    att = _dense(ctx.reshape(x.shape), lp["proj_w"], lp["proj_b"])
    x = _ln(x + att, lp["ln1_g"], lp["ln1_b"])
    out = _dense(_gelu(_dense(x, lp["ffn1_w"], lp["ffn1_b"])),
                 lp["ffn2_w"], lp["ffn2_b"])
    return _ln(x + out, lp["ln2_g"], lp["ln2_b"]), k, v


def decode_layer(cfg, lp, x, attend):
    """One post-LN block for a single new position per sequence.

    x (B, U); ``attend(q, k_new, v_new) -> ctx`` supplies attention over
    the cached history (q/k_new/v_new/ctx all (B, H, D)) — the dense
    reference passes a mask-and-softmax closure, the decode engine passes
    a paged-KV closure that also writes k_new/v_new into the page pool.
    Returns (x', k_new, v_new)."""
    h, d = cfg["heads"], cfg["head_dim"]
    q, k, v = _split_heads(_dense(x, lp["qkv_w"], lp["qkv_b"]), h, d)
    ctx = attend(q, k, v)
    att = _dense(ctx.reshape(x.shape), lp["proj_w"], lp["proj_b"])
    x = _ln(x + att, lp["ln1_g"], lp["ln1_b"])
    out = _dense(_gelu(_dense(x, lp["ffn1_w"], lp["ffn1_b"])),
                 lp["ffn2_w"], lp["ffn2_b"])
    return _ln(x + out, lp["ln2_g"], lp["ln2_b"]), k, v


def lm_prefill(cfg, params, tokens):
    """Causal forward over a prompt batch. tokens (B, S) int32.

    Returns (logits (B, S, V), k (L, B, S, H, D), v (L, B, S, H, D)) —
    the dense KV state ``lm_decode_step`` consumes. Padded positions are
    harmless: causal masking means row i only sees columns <= i, and the
    caller reads logits at its true last position."""
    import jax.numpy as jnp
    b, s = tokens.shape
    x = params["embed"][tokens] + params["pos"][:s]
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))
    ks, vs = [], []
    for lp in params["layers"]:
        x, k, v = prefill_layer(cfg, lp, x, causal)
        ks.append(k)
        vs.append(v)
    x = _ln(x, params["final_g"], params["final_b"])
    logits = _dense(x, params["dec_w"], params["dec_b"])
    return logits, jnp.stack(ks), jnp.stack(vs)


def lm_decode_step(cfg, params, tokens, kv, positions):
    """One decode step over dense KV (the paged engine's reference).

    tokens (B,) int32; kv = (k, v) each (L, B, S, H, D) with S the cache
    capacity; positions (B,) int32 — the index being written this step.
    Returns (logits (B, V), (k, v) updated)."""
    import jax
    import jax.numpy as jnp
    k_all, v_all = kv
    b = tokens.shape[0]
    rows = jnp.arange(b)
    x = params["embed"][tokens] + params["pos"][positions]
    scale = 1.0 / math.sqrt(cfg["head_dim"])
    cols = jnp.arange(k_all.shape[2])
    for i, lp in enumerate(params["layers"]):
        def attend(q, k_new, v_new, _i=i):
            nonlocal k_all, v_all
            k_all = k_all.at[_i, rows, positions].set(k_new)
            v_all = v_all.at[_i, rows, positions].set(v_new)
            scores = jnp.einsum("bhd,bshd->bhs", q, k_all[_i],
                                preferred_element_type=jnp.float32) * scale
            live = cols[None, :] <= positions[:, None]  # (B, S)
            scores = jnp.where(live[:, None], scores, -1e30)
            p = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
            return jnp.einsum("bhs,bshd->bhd", p, v_all[_i])

        x, _, _ = decode_layer(cfg, lp, x, attend)
    x = _ln(x, params["final_g"], params["final_b"])
    return _dense(x, params["dec_w"], params["dec_b"]), (k_all, v_all)


def sample_token(logits, rng, temperature):
    """On-device sampling: temperature > 0 draws from softmax(logits / t),
    temperature <= 0 is greedy argmax. ``temperature`` may be scalar or
    per-row (B,). Returns int32 (B,)."""
    import jax
    import jax.numpy as jnp
    t = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32),
                         logits.shape[:-1])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.maximum(t, 1e-4)[..., None]
    drawn = jax.random.categorical(
        rng, logits.astype(jnp.float32) / safe_t).astype(jnp.int32)
    return jnp.where(t > 0, drawn, greedy)
