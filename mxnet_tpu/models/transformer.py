"""Transformer encoder / BERT / decoder-LM — the flagship family.

Reference counterpart: gluon-nlp's BERTModel/TransformerEncoder (external
repo, driven through the mx API — SURVEY.md §2.5 BERT-base config). Built
TPU-first:

- One fused QKV projection (one MXU matmul instead of three).
- bf16-friendly: params stay fp32; cast policy applied by AMP/trainer.
- Tensor parallel: ``bert_sharding_rules()`` shards QKV/FFN-in over the
  mesh ``tp`` axis on the output dim and out-proj/FFN-out on the input
  dim (Megatron layout: one all-reduce per block, inserted by XLA).
- Sequence parallel: when the active mesh (parallel.mesh_scope) has an
  ``sp`` axis > 1, attention runs as ring attention over the ICI
  (parallel/ring_attention.py) — long-context support the reference lacks.
"""
from __future__ import annotations

import math

import numpy as np

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.parameter import Parameter

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "BERTEncoder", "BERTModel", "TransformerLM", "bert_base", "bert_large",
           "bert_tiny", "transformer_lm", "bert_sharding_rules"]


class MultiHeadAttention(HybridBlock):
    """Fused-QKV multi-head self-attention with optional ring execution."""

    def __init__(self, units, num_heads, dropout=0.0, causal=False, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._heads = num_heads
        self._causal = causal
        self.qkv = nn.Dense(3 * units, flatten=False, use_bias=True,
                            in_units=units, prefix=self.prefix + "qkv_")
        self.proj = nn.Dense(units, flatten=False, use_bias=True, in_units=units,
                             prefix=self.prefix + "proj_")
        self._dropout = dropout

    def hybrid_forward(self, F, x, mask=None):
        # x: (B, S, U)
        b, s, u = x.shape
        h, d = self._heads, self._units // self._heads
        qkv = self.qkv(x)  # (B, S, 3U)
        # split (not tensor indexing) keeps this F-generic: the same code
        # traces eagerly and symbolically (Symbol has no tensor indexing)
        qkv = qkv.reshape((b, s, 3, h, d))
        q, k, v = F.split(qkv, num_outputs=3, axis=2, squeeze_axis=True)
        q = q.transpose((0, 2, 1, 3))  # (B, H, S, D)
        k = k.transpose((0, 2, 1, 3))
        v = v.transpose((0, 2, 1, 3))

        from .. import parallel as par
        from ..ndarray.ndarray import invoke_fn

        mesh = par.current_mesh()
        sp = 1
        if mesh is not None:
            sp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("sp", 1)

        from ..ops.attention import fused_attention

        if mesh is not None and sp > 1:
            out = invoke_fn(
                lambda qq, kk, vv: par.sequence_sharded_attention(
                    qq, kk, vv, mesh, causal=self._causal),
                [q, k, v])
        else:
            # single-chip path: flash (Pallas) for long sequences, fused
            # XLA softmax-attention otherwise — see ops/attention.py policy
            def attn(qq, kk, vv, mm=None):
                return fused_attention(qq, kk, vv, mask=mm,
                                       causal=self._causal)

            ins = [q, k, v] + ([mask] if mask is not None else [])
            out = invoke_fn(attn, ins)
        out = out.transpose((0, 2, 1, 3)).reshape((b, s, u))
        out = self.proj(out)
        if self._dropout:
            out = F.Dropout(out, p=self._dropout)
        return out


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu", **kwargs):
        super().__init__(**kwargs)
        self.ffn_1 = nn.Dense(hidden_size, flatten=False, in_units=units,
                              prefix=self.prefix + "ffn1_")
        self.ffn_2 = nn.Dense(units, flatten=False, in_units=hidden_size,
                              prefix=self.prefix + "ffn2_")
        self._act = activation
        self._dropout = dropout

    def hybrid_forward(self, F, x):
        out = self.ffn_1(x)
        out = F.Activation(out, act_type=self._act) if self._act != "gelu" \
            else F.gelu(out, approximation="tanh")
        out = self.ffn_2(out)
        if self._dropout:
            out = F.Dropout(out, p=self._dropout)
        return out


class TransformerEncoderCell(HybridBlock):
    """Post-LN transformer block (BERT layout)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0, causal=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.attention = MultiHeadAttention(units, num_heads, dropout=dropout,
                                            causal=causal,
                                            prefix=self.prefix + "attn_")
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                   prefix=self.prefix + "ffn_")
        self.ln2 = nn.LayerNorm(in_channels=units)
        self._dropout = dropout

    def hybrid_forward(self, F, x, mask=None):
        att = self.attention(x, mask)
        x = self.ln1(x + att)
        out = self.ffn(x)
        return self.ln2(x + out)


class BERTEncoder(HybridBlock):
    def __init__(self, units=768, hidden_size=3072, num_layers=12, num_heads=12,
                 max_length=512, dropout=0.1, causal=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        self.position_weight = self.params.get(
            "position_weight", shape=(max_length, units), init="zeros")
        self.cells = []
        for i in range(num_layers):
            cell = TransformerEncoderCell(units, hidden_size, num_heads,
                                          dropout=dropout, causal=causal,
                                          prefix=f"{self.prefix}layer{i}_")
            self.register_child(cell, f"layer{i}")
            self.cells.append(cell)
        self._dropout = dropout

    def hybrid_forward(self, F, x, position_weight, mask=None):
        b, s, u = x.shape
        pos = F.slice_axis(position_weight, axis=0, begin=0,
                           end=s).reshape((1, s, u))
        x = x + pos
        if self._dropout:
            x = F.Dropout(x, p=self._dropout)
        for cell in self.cells:
            x = cell(x, mask)
        return x


class BERTModel(HybridBlock):
    """BERT with MLM head (gluon-nlp BERTModel counterpart)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512, dropout=0.1,
                 num_token_types=2, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self.word_embed = nn.Embedding(vocab_size, units,
                                       prefix=self.prefix + "word_embed_")
        self.token_type_embed = nn.Embedding(num_token_types, units,
                                             prefix=self.prefix + "type_embed_")
        self.embed_ln = nn.LayerNorm(in_channels=units)
        self.encoder = BERTEncoder(units, hidden_size, num_layers, num_heads,
                                   max_length, dropout,
                                   prefix=self.prefix + "enc_")
        self.mlm_dense = nn.Dense(units, flatten=False, in_units=units,
                                  prefix=self.prefix + "mlm_dense_")
        self.mlm_ln = nn.LayerNorm(in_channels=units)
        self.mlm_decoder = nn.Dense(vocab_size, flatten=False, in_units=units,
                                    prefix=self.prefix + "mlm_decoder_")

    def hybrid_forward(self, F, inputs, token_types=None):
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = self.embed_ln(x)
        seq = self.encoder(x)
        h = self.mlm_dense(seq)
        h = F.gelu(h, approximation="tanh")
        h = self.mlm_ln(h)
        return self.mlm_decoder(h)


class TransformerLM(HybridBlock):
    """Decoder-only causal LM (GPT-style) — the long-context flagship."""

    def __init__(self, vocab_size=32000, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=2048, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.word_embed = nn.Embedding(vocab_size, units,
                                       prefix=self.prefix + "word_embed_")
        self.encoder = BERTEncoder(units, hidden_size, num_layers, num_heads,
                                   max_length, dropout, causal=True,
                                   prefix=self.prefix + "enc_")
        self.final_ln = nn.LayerNorm(in_channels=units)
        self.decoder = nn.Dense(vocab_size, flatten=False, in_units=units,
                                prefix=self.prefix + "decoder_")

    def hybrid_forward(self, F, inputs):
        x = self.word_embed(inputs)
        x = self.encoder(x)
        x = self.final_ln(x)
        return self.decoder(x)


def bert_sharding_rules():
    """Megatron-style TP + dp-replicated rules for BERT/TransformerLM params.

    Works with parallel.ShardingRules spec pruning: on meshes without "tp"
    everything collapses to replicated.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel import ShardingRules

    return ShardingRules([
        (r"qkv_weight$", P("tp", None)),        # column parallel
        (r"ffn1_weight$", P("tp", None)),
        (r"qkv_bias$", P("tp")),
        (r"ffn1_bias$", P("tp")),
        (r"proj_weight$", P(None, "tp")),       # row parallel
        (r"ffn2_weight$", P(None, "tp")),
        (r"(word_embed|mlm_decoder|decoder)\d*_weight$", P("tp", None)),
    ], default=P())


def bert_tiny(vocab_size=1000, **kw):
    kw.setdefault("units", 64)
    kw.setdefault("hidden_size", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_length", 128)
    return BERTModel(vocab_size=vocab_size, **kw)


def bert_base(vocab_size=30522, **kw):
    return BERTModel(vocab_size=vocab_size, units=768, hidden_size=3072,
                     num_layers=12, num_heads=12, **kw)


def bert_large(vocab_size=30522, **kw):
    return BERTModel(vocab_size=vocab_size, units=1024, hidden_size=4096,
                     num_layers=24, num_heads=16, **kw)


def transformer_lm(vocab_size=32000, **kw):
    return TransformerLM(vocab_size=vocab_size, **kw)
