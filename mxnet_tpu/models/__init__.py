"""Flagship model families built on gluon + parallel.

The reference's transformer/BERT workloads live in external repos
(gluon-nlp — SURVEY.md §2.5) but drive its headline benchmarks, so the
model family is first-class here: mesh-shardable transformer encoder/LM
with tensor-parallel rules and sequence-parallel (ring) attention.
"""
from .transformer import (MultiHeadAttention, PositionwiseFFN,  # noqa: F401
                          TransformerEncoderCell, BERTEncoder, BERTModel,
                          TransformerLM, bert_base, bert_large, bert_tiny,
                          transformer_lm, bert_sharding_rules)
