"""Flagship model families built on gluon + parallel.

The reference's transformer/BERT workloads live in external repos
(gluon-nlp — SURVEY.md §2.5) but drive its headline benchmarks, so the
model family is first-class here: mesh-shardable transformer encoder/LM
with tensor-parallel rules and sequence-parallel (ring) attention.
"""
from .transformer import (MultiHeadAttention, PositionwiseFFN,  # noqa: F401
                          TransformerEncoderCell, BERTEncoder, BERTModel,
                          TransformerLM, bert_base, bert_large, bert_tiny,
                          transformer_lm, bert_sharding_rules)
from .seq2seq import (TransformerDecoderCell, Seq2SeqTransformer,  # noqa: F401
                      beam_search, label_smoothing_loss)
from .ssd import SSD, SSDMultiBoxLoss, ssd_300  # noqa: F401
