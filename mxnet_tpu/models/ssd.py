"""SSD object detector (config 5 of the baseline set).

Reference counterpart: ``example/ssd`` + GluonCV SSD (multibox_* + box_nms
CUDA ops — TBV, SURVEY.md §2.5). Anchors via MultiBoxPrior, training via
MultiBoxTarget + SSDMultiBoxLoss, inference via MultiBoxDetection (NMS) —
all running as static-shape XLA (ops/contrib.py).
"""
from __future__ import annotations

import numpy as np

from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["SSD", "SSDMultiBoxLoss", "ssd_300"]


def _conv_block(channels, stride=1):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(channels, 3, stride, 1, use_bias=False))
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))
    return out


class SSD(HybridBlock):
    """Multi-scale single-shot detector.

    forward(x) -> (anchors (1, N, 4), cls_preds (B, N, classes+1),
                   box_preds (B, N*4))
    """

    def __init__(self, num_classes=20, base_channels=(32, 64, 128),
                 scale_channels=(128, 128, 128),
                 sizes=((0.2, 0.272), (0.37, 0.447), (0.54, 0.619)),
                 ratios=((1, 2, 0.5),) * 3, **kwargs):
        super().__init__(**kwargs)
        assert len(scale_channels) == len(sizes) == len(ratios)
        self._num_classes = num_classes
        self._sizes = sizes
        self._ratios = ratios
        self._num_anchors = [len(s) + len(r) - 1 for s, r in zip(sizes, ratios)]

        self.base = nn.HybridSequential()
        for i, c in enumerate(base_channels):
            self.base.add(_conv_block(c, stride=1))
            self.base.add(nn.MaxPool2D(2, 2))

        self.stages, self.cls_heads, self.box_heads = [], [], []
        for i, c in enumerate(scale_channels):
            stage = _conv_block(c, stride=1) if i == 0 else _seq(
                _conv_block(c), nn.MaxPool2D(2, 2))
            self.register_child(stage, f"stage{i}")
            self.stages.append(stage)
            k = self._num_anchors[i]
            cls = nn.Conv2D(k * (num_classes + 1), 3, padding=1)
            box = nn.Conv2D(k * 4, 3, padding=1)
            self.register_child(cls, f"cls{i}")
            self.register_child(box, f"box{i}")
            self.cls_heads.append(cls)
            self.box_heads.append(box)

    def hybrid_forward(self, F, x):
        x = self.base(x)
        anchors, cls_preds, box_preds = [], [], []
        for i, stage in enumerate(self.stages):
            x = stage(x)
            anchors.append(F.contrib.MultiBoxPrior(x, sizes=self._sizes[i],
                                                   ratios=self._ratios[i]))
            c = self.cls_heads[i](x)  # (B, K*(C+1), H, W)
            b = self.box_heads[i](x)  # (B, K*4, H, W)
            bsz = c.shape[0]
            cls_preds.append(c.transpose((0, 2, 3, 1)).reshape(
                (bsz, -1, self._num_classes + 1)))
            box_preds.append(b.transpose((0, 2, 3, 1)).reshape((bsz, -1)))
        return (F.concat(*anchors, dim=1), F.concat(*cls_preds, dim=1),
                F.concat(*box_preds, dim=1))

    def detect(self, x, nms_threshold=0.45, threshold=0.01, nms_topk=400):
        """Full inference: forward + softmax + decode + NMS → (B, N, 6)."""
        from .. import ndarray as F

        anchors, cls_preds, box_preds = self(x)
        cls_prob = F.softmax(cls_preds, axis=-1).transpose((0, 2, 1))
        return F.contrib.MultiBoxDetection(cls_prob, box_preds, anchors,
                                           nms_threshold=nms_threshold,
                                           threshold=threshold,
                                           nms_topk=nms_topk)


def _seq(*blocks):
    s = nn.HybridSequential()
    s.add(*blocks)
    return s


class SSDMultiBoxLoss:
    """cls CE + smooth-L1 box loss with hard-negative-free normalization
    (GluonCV SSDMultiBoxLoss counterpart)."""

    def __init__(self, negative_mining_ratio=3.0, lambd=1.0):
        self._ratio = negative_mining_ratio
        self._lambd = lambd

    def __call__(self, cls_preds, box_preds, cls_targets, box_targets, box_masks):
        from .. import ndarray as F
        from ..ndarray.ndarray import invoke_fn
        import jax
        import jax.numpy as jnp

        def pure(cp, bp, ct, bt, bm):
            logp = jax.nn.log_softmax(cp, axis=-1)
            ce = -jnp.take_along_axis(logp, ct.astype(jnp.int32)[..., None],
                                      axis=-1)[..., 0]
            pos = ct > 0
            num_pos = jnp.maximum(pos.sum(), 1).astype(cp.dtype)
            # hard negative mining: top (ratio * num_pos) negatives by loss
            neg_ce = jnp.where(pos, -jnp.inf, ce)
            k = jnp.minimum((self._ratio * pos.sum(axis=-1)).astype(jnp.int32),
                            ce.shape[-1] - 1)
            sorted_neg = -jnp.sort(-neg_ce, axis=-1)
            thresh = jnp.take_along_axis(sorted_neg,
                                         jnp.maximum(k - 1, 0)[:, None],
                                         axis=-1)
            hard_neg = (neg_ce >= thresh) & (k > 0)[:, None] & ~pos
            cls_loss = jnp.where(pos | hard_neg, ce, 0.0).sum() / num_pos
            diff = jnp.abs((bp - bt) * bm)
            sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
            box_loss = sl1.sum() / num_pos
            return cls_loss + self._lambd * box_loss

        return invoke_fn(pure, [cls_preds, box_preds, cls_targets, box_targets,
                                box_masks])


def ssd_300(num_classes=20, **kwargs):
    return SSD(num_classes=num_classes, **kwargs)
