"""Encoder-decoder transformer for NMT + beam search.

Reference counterpart: GluonNLP/Sockeye transformer NMT (external repos
driven through the mx API — SURVEY.md §2.5 config 4: label smoothing +
beam search over topk). Decoder blocks add causal self-attention and
cross-attention; beam search is a static-shape ``topk`` loop (XLA-friendly:
fixed max length, no dynamic compaction).
"""
from __future__ import annotations

import numpy as np

from ..gluon import nn
from ..gluon.block import HybridBlock
from .transformer import MultiHeadAttention, PositionwiseFFN

__all__ = ["TransformerDecoderCell", "Seq2SeqTransformer", "beam_search",
           "label_smoothing_loss"]


class CrossAttention(HybridBlock):
    """Q from decoder, K/V from encoder memory."""

    def __init__(self, units, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._heads = num_heads
        self.q_proj = nn.Dense(units, flatten=False, in_units=units,
                               prefix=self.prefix + "q_")
        self.kv_proj = nn.Dense(2 * units, flatten=False, in_units=units,
                                prefix=self.prefix + "kv_")
        self.proj = nn.Dense(units, flatten=False, in_units=units,
                             prefix=self.prefix + "proj_")
        self._dropout = dropout

    def hybrid_forward(self, F, x, memory):
        from ..ndarray.ndarray import invoke_fn
        from ..parallel.ring_attention import plain_attention

        b, sq, u = x.shape
        sk = memory.shape[1]
        h, d = self._heads, self._units // self._heads
        q = self.q_proj(x).reshape((b, sq, h, d)).transpose((0, 2, 1, 3))
        # split (not tensor indexing) keeps this F-generic: the same code
        # traces eagerly and symbolically (Symbol has no tensor indexing)
        kv = self.kv_proj(memory).reshape((b, sk, 2, h, d))
        k, v = F.split(kv, num_outputs=2, axis=2, squeeze_axis=True)
        k = k.transpose((0, 2, 1, 3))  # (B, H, Sk, D)
        v = v.transpose((0, 2, 1, 3))
        out = invoke_fn(lambda qq, kk, vv: plain_attention(qq, kk, vv),
                        [q, k, v])
        out = out.transpose((0, 2, 1, 3)).reshape((b, sq, u))
        out = self.proj(out)
        if self._dropout:
            out = F.Dropout(out, p=self._dropout)
        return out


class TransformerDecoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self.self_attn = MultiHeadAttention(units, num_heads, dropout=dropout,
                                            causal=True,
                                            prefix=self.prefix + "selfattn_")
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.cross_attn = CrossAttention(units, num_heads, dropout=dropout,
                                         prefix=self.prefix + "crossattn_")
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                   prefix=self.prefix + "ffn_")
        self.ln3 = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, memory):
        x = self.ln1(x + self.self_attn(x))
        x = self.ln2(x + self.cross_attn(x, memory))
        return self.ln3(x + self.ffn(x))


class Seq2SeqTransformer(HybridBlock):
    """Full encoder-decoder NMT model (gluon-nlp/Sockeye transformer class)."""

    def __init__(self, src_vocab=32000, tgt_vocab=32000, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8, max_length=512,
                 dropout=0.1, tie_embeddings=False, **kwargs):
        super().__init__(**kwargs)
        from .transformer import BERTEncoder

        self.src_embed = nn.Embedding(src_vocab, units,
                                      prefix=self.prefix + "src_embed_")
        self.tgt_embed = nn.Embedding(tgt_vocab, units,
                                      prefix=self.prefix + "tgt_embed_")
        self.encoder = BERTEncoder(units, hidden_size, num_layers, num_heads,
                                   max_length, dropout,
                                   prefix=self.prefix + "enc_")
        self.dec_pos = self.params.get("dec_position_weight",
                                       shape=(max_length, units), init="zeros")
        self.dec_cells = []
        for i in range(num_layers):
            cell = TransformerDecoderCell(units, hidden_size, num_heads, dropout,
                                          prefix=f"{self.prefix}dec{i}_")
            self.register_child(cell, f"dec{i}")
            self.dec_cells.append(cell)
        self.out_proj = nn.Dense(tgt_vocab, flatten=False, in_units=units,
                                 prefix=self.prefix + "out_")
        self._units = units
        self._dropout = dropout

    def encode(self, src):
        return self.encoder(self.src_embed(src))

    def decode(self, tgt, memory, dec_pos=None):
        """``dec_pos`` is the decoder position table: threaded through as a
        hybrid_forward param when tracing (symbolic or cached), fetched
        concretely when called standalone (beam search)."""
        from ..symbol.symbol import Symbol

        if isinstance(tgt, Symbol):
            from .. import symbol as F
        else:
            from .. import ndarray as F
        b, s = tgt.shape[0], tgt.shape[1]
        x = self.tgt_embed(tgt)
        w = dec_pos if dec_pos is not None else self.dec_pos.data()
        pos = F.slice_axis(w, axis=0, begin=0,
                           end=s).reshape((1, s, self._units))
        x = x + pos
        if self._dropout:
            x = F.Dropout(x, p=self._dropout)
        for cell in self.dec_cells:
            x = cell(x, memory)
        return self.out_proj(x)

    def hybrid_forward(self, F, src, tgt, dec_pos=None):
        memory = self.encode(src)
        return self.decode(tgt, memory, dec_pos)


def label_smoothing_loss(logits, labels, epsilon=0.1, ignore_index=None):
    """Smoothed CE (the reference NMT configs use make_loss + smoothing ops)."""
    from .. import ndarray as F
    from ..ndarray.ndarray import invoke_fn
    import jax.numpy as jnp

    def pure(lg, lb):
        import jax

        v = lg.shape[-1]
        logp = jax.nn.log_softmax(lg, axis=-1)
        oh = jnp.eye(v, dtype=lg.dtype)[lb.astype(jnp.int32)]
        smooth = oh * (1 - epsilon) + epsilon / v
        nll = -(smooth * logp).sum(-1)
        if ignore_index is not None:
            mask = (lb != ignore_index).astype(lg.dtype)
            return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        return nll.mean()

    return invoke_fn(pure, [logits, labels])


def beam_search(model: Seq2SeqTransformer, src, beam_size=4, max_length=30,
                bos=1, eos=2, alpha=0.6):
    """Static-shape beam search (reference: GluonNLP BeamSearchSampler over
    topk ops). Decodes greedily over a fixed max_length loop; returns
    (best_sequences (B, max_length), scores (B,))."""
    import jax.numpy as jnp
    import numpy as np_

    from .. import ndarray as F
    from ..ndarray import NDArray

    src_np = src if isinstance(src, NDArray) else NDArray(src)
    b = src_np.shape[0]
    memory = model.encode(src_np)  # (B, S, U)
    mem = memory._data
    mem_rep = jnp.repeat(mem, beam_size, axis=0)  # (B*K, S, U)

    seqs = np_.full((b * beam_size, max_length), eos, np_.int32)
    seqs[:, 0] = bos
    scores = np_.full((b, beam_size), -1e9, np_.float32)
    scores[:, 0] = 0.0  # only the first beam is live initially
    alive = np_.ones((b * beam_size,), bool)

    for t in range(1, max_length):
        logits = model.decode(NDArray(jnp.asarray(seqs[:, :t])),
                              NDArray(mem_rep))  # (B*K, t, V)
        logp = np_.array(F.log_softmax(logits[:, t - 1], axis=-1).asnumpy())
        v = logp.shape[-1]
        # dead beams only extend with eos at zero cost
        logp[~alive] = -1e9
        logp[~alive, eos] = 0.0
        total = scores.reshape(-1, 1) + logp  # (B*K, V)
        total = total.reshape(b, beam_size * v)
        topk_idx = np_.argsort(-total, axis=1)[:, :beam_size]
        topk_score = np_.take_along_axis(total, topk_idx, axis=1)
        beam_src = topk_idx // v
        token = (topk_idx % v).astype(np_.int32)
        new_seqs = np_.empty_like(seqs)
        for bi in range(b):
            for k in range(beam_size):
                parent = bi * beam_size + int(beam_src[bi, k])
                row = bi * beam_size + k
                new_seqs[row] = seqs[parent]
                new_seqs[row, t] = token[bi, k]
        seqs = new_seqs
        scores = topk_score
        alive = (seqs[:, t] != eos) & alive[
            (np_.arange(b)[:, None] * beam_size + beam_src).reshape(-1)]
        if not alive.any():
            break

    # length-normalized best beam
    lengths = (seqs != eos).sum(axis=1).reshape(b, beam_size)
    lp = ((5 + lengths) ** alpha) / ((5 + 1) ** alpha)
    final = scores / lp
    best = np_.argmax(final, axis=1)
    out = np_.stack([seqs[bi * beam_size + best[bi]] for bi in range(b)])
    return out, final[np_.arange(b), best]
