"""Executor — a bound, jit-compiled symbolic graph.

Reference: ``src/executor/graph_executor.cc`` (``GraphExecutor::SimpleBind/
Forward/Backward`` — TBV, SURVEY.md §2.1 L6b). TPU redesign: instead of
NNVM passes (PlanMemory, attach-op-execs) + engine pushes per node, the
whole graph evaluates as ONE pure function compiled by ``jax.jit``; XLA
does memory planning and fusion. Backward is ``jax.vjp`` of the same
function. BatchNorm moving stats thread through as explicit aux outputs
(the reference mutates them inside the kernel).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import obs
from .base import MXNetError
from .context import Context, current_context
from .ndarray import NDArray
from .ops import get_op
from .ops.registry import coerce_kwargs

__all__ = ["Executor"]


def _avals_sig(vals) -> tuple:
    return tuple((tuple(v.shape), str(v.dtype)) for v in vals)


def _build_graph_fn(sym, train: bool):
    """Compile the DAG into ``fn(arg_vals, aux_vals) -> (outputs, new_aux)``.

    Returns (arg_names, aux_names, fn, has_bn). RNG draws fold a per-call
    key via mxnet_tpu.random's trace scope (set by the caller when jitting).
    """
    nodes = sym._topo()
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    if sym._op == "_group":
        heads = [(s._base(), s._index) for s in sym._inputs]
    else:
        heads = [(sym._base(), sym._index)]
    n_heads_multi = []
    for base, index in heads:
        if index is None and base._op is not None and base._n_outputs() > 1:
            n_heads_multi.append((base, None))

    def fn(arg_vals: List, aux_vals: List):
        env: Dict[int, object] = {}
        args = dict(zip(arg_names, arg_vals))
        auxs = dict(zip(aux_names, aux_vals))
        new_aux = dict(auxs)
        from . import autograd

        old_train = autograd.set_training(train)
        try:
            for node in nodes:
                if node._op is None:
                    env[id(node)] = args[node._name] if node._name in args \
                        else auxs[node._name]
                    continue
                if node._op == "_group":
                    continue
                # invoke_fn nodes carry their OpDef inline (symbol.invoke_fn)
                opdef = getattr(node, "_opdef", None) or get_op(node._op)
                kwargs = coerce_kwargs({k: v for k, v in node._attrs.items()
                                        if not k.startswith("__")})
                in_vals = []
                for i in node._inputs:
                    v = env[id(i._base())]
                    if i._index is not None and isinstance(v, tuple):
                        v = v[i._index]
                    in_vals.append(v)
                if node._op == "BatchNorm" and train and \
                        not kwargs.get("use_global_stats", False):
                    kwargs["output_mean_var"] = True
                    out, bmean, bvar = opdef.fn(*in_vals, **kwargs)
                    mom = float(kwargs.get("momentum", 0.9))
                    # inputs 3,4 are moving_mean/moving_var variables
                    for slot, batch_stat in ((3, bmean), (4, bvar)):
                        vn = node._inputs[slot]._base()._name
                        if vn in new_aux:
                            new_aux[vn] = mom * new_aux[vn] + (1 - mom) * batch_stat
                    env[id(node)] = out
                else:
                    env[id(node)] = opdef.fn(*in_vals, **kwargs)
        finally:
            autograd.set_training(old_train)

        outs = []
        for base, index in heads:
            v = env[id(base)]
            if isinstance(v, tuple):
                if index is not None:
                    outs.append(v[index])
                else:
                    outs.extend(v)
            else:
                outs.append(v)
        return tuple(outs), tuple(new_aux[n] for n in aux_names)

    return arg_names, aux_names, fn, bool(aux_names)


class Executor:
    """Bound graph with argument/gradient/aux arrays (reference Executor)."""

    def __init__(self, symbol, ctx=None, grad_req="write", shapes=None,
                 args=None, args_grad=None, aux_states=None, lint=None):
        self._symbol = symbol
        self._ctx = Context(ctx) if ctx is not None else current_context()
        self._grad_req = grad_req
        self.outputs_nd: List[NDArray] = []
        self.lint_report = None

        # Pre-flight static analysis BEFORE any inference/compilation:
        # lint="error" rejects a bad graph with node attribution instead of
        # an opaque tracer exception; "warn" reports and continues.
        # Default comes from MXNET_GRAPH_LINT (off).
        if lint is None:
            import os

            lint = os.environ.get("MXNET_GRAPH_LINT", "off")
        if lint not in ("off", "warn", "error"):
            raise ValueError(f"lint must be 'off'|'warn'|'error', got {lint!r}")
        if lint != "off":
            known = {k: tuple(v) for k, v in (shapes or {}).items()}
            if not known and args is not None:
                named = args.items() if isinstance(args, dict) \
                    else zip(symbol.list_arguments(), args)
                known = {k: tuple(v.shape) if isinstance(v, NDArray)
                         else tuple(NDArray(v).shape) for k, v in named}
            from .analysis import GraphLinter

            self.lint_report = GraphLinter().lint(symbol, shapes=known)
            if lint == "error":
                self.lint_report.raise_if_errors()
            elif self.lint_report:
                import warnings

                warnings.warn("graph lint: " + self.lint_report.format(),
                              stacklevel=2)

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self._arg_names = arg_names
        self._aux_names = aux_names

        inferred: Dict[str, tuple] = {}
        if shapes:
            from .symbol.symbol import infer_shapes

            inferred, _outs = infer_shapes(symbol, {k: tuple(v)
                                                    for k, v in shapes.items()})
        self.arg_dict: Dict[str, NDArray] = {}
        if args is not None:
            if isinstance(args, dict):
                self.arg_dict = {k: NDArray(v) if not isinstance(v, NDArray) else v
                                 for k, v in args.items()}
            else:
                self.arg_dict = {n: v for n, v in zip(arg_names, args)}
        elif shapes:
            for n in arg_names:
                if n not in inferred:
                    raise MXNetError(f"simple_bind: missing shape for arg {n!r}")
                self.arg_dict[n] = NDArray(np.zeros(inferred[n], np.float32),
                                           ctx=self._ctx)
        self.aux_dict: Dict[str, NDArray] = {}
        if aux_states is not None:
            if isinstance(aux_states, dict):
                self.aux_dict = dict(aux_states)
            else:
                self.aux_dict = {n: v for n, v in zip(aux_names, aux_states)}
        else:
            for n in aux_names:
                shape = inferred.get(n)
                if shape is None and n in self.arg_dict:
                    shape = self.arg_dict[n].shape
                if shape is None:
                    shape = ()
                init = np.ones(shape, np.float32) if n.endswith("var") \
                    else np.zeros(shape, np.float32)
                self.aux_dict[n] = NDArray(init, ctx=self._ctx)

        if grad_req != "null":
            if isinstance(args_grad, dict):
                self.grad_dict = dict(args_grad)
            elif isinstance(args_grad, (list, tuple)):
                self.grad_dict = {n: g for n, g in zip(arg_names, args_grad)}
            else:
                self.grad_dict = {
                    n: NDArray(np.zeros(self.arg_dict[n].shape, np.float32),
                               ctx=self._ctx)
                    for n in arg_names if n in self.arg_dict}
        else:
            self.grad_dict = {}

        self._jit_cache: Dict = {}
        self._vjp = None
        self._last_inputs = None
        # device-plane program accounting (obs/device.py), populated only
        # while capture is active (zero-cost-when-off): one entry per
        # distinct (site, input signature) compile, carrying XLA
        # flops/bytes/HBM; the signature's AOT executable replaces the
        # jit wrapper for execution
        self.compile_log: List[dict] = []
        self._seen_sigs: set = set()
        self._aot: Dict = {}
        self._sig_cost: Dict = {}

    # ------------------------------------------------------------------
    @property
    def outputs(self):
        return self.outputs_nd

    def copy_params_from(self, arg_params, aux_params=None):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(NDArray(v)._data)
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._set_data(NDArray(v)._data)

    # ------------------------------------------------------------------
    def _device_account(self, site: str, jitted, call_args, sig):
        """Device-plane bookkeeping shared by forward and backward: on a
        signature's first sighting (and capture active) AOT-compile once —
        cost/memory analysis into ``compile_log``, the executable into the
        AOT cache. Returns ``(fn_to_call, is_compile)``."""
        is_compile = sig not in self._seen_sigs
        if is_compile:
            self._seen_sigs.add(sig)
            if obs.device.active():
                entry = {"site": site, "train": sig[1], "avals": sig[2]}
                compiled, cost = obs.device.capture(
                    jitted, call_args, site="executor", label=site)
                if compiled is not None:
                    self._aot[sig] = compiled
                if cost:
                    entry.update(cost)
                    self._sig_cost[sig] = cost
                self.compile_log.append(entry)
        return self._aot.get(sig, jitted), is_compile

    def _get_fn(self, train: bool):
        key = train
        if key not in self._jit_cache:
            arg_names, aux_names, fn, _ = _build_graph_fn(self._symbol, train)

            def wrapped(rng_key, arg_vals, aux_vals):
                import jax.random as jr

                from . import random as _random

                if hasattr(jr, "wrap_key_data") and \
                        getattr(rng_key, "dtype", None) == jnp.uint32:
                    rng_key = jr.wrap_key_data(rng_key)
                with _random.trace_key_scope(rng_key):
                    return fn(arg_vals, aux_vals)

            self._jit_cache[key] = (jax.jit(wrapped), arg_names, aux_names, fn)
        return self._jit_cache[key]

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(NDArray(v)._data)
            elif k in self.aux_dict:
                self.aux_dict[k]._set_data(NDArray(v)._data)
        jitted, arg_names, aux_names, raw_fn = self._get_fn(bool(is_train))
        arg_vals = [self.arg_dict[n]._data for n in arg_names]
        aux_vals = [self.aux_dict[n]._data for n in aux_names]
        from .chaos import nan as _nan_chaos

        if _nan_chaos.enabled():
            # deterministic NaN injection (MXNET_CHAOS_NAN) BEFORE the
            # last-inputs capture, so the health blame pass replays the
            # poisoned batch exactly as the compiled program saw it
            arg_vals = _nan_chaos.poison(arg_names, arg_vals)

        from . import random as _random
        import jax.random as jr

        key = _random.next_key()
        key_data = jr.key_data(key) if hasattr(jr, "key_data") else key
        from . import profiler as _profiler

        if _profiler.counting_dispatches():
            _profiler.count_dispatch("compiled")
        rec = obs.enabled()
        t0 = time.monotonic() if rec else 0.0
        # device-plane accounting only when capture is active (or produced
        # an AOT executable earlier): the disabled hot path must not pay
        # the per-call aval-signature build (zero-cost-when-off contract)
        fn, sig, is_compile = jitted, None, False
        if obs.device.active() or self._aot:
            sig = ("forward", bool(is_train), _avals_sig(arg_vals),
                   _avals_sig(aux_vals))
            fn, is_compile = self._device_account(
                "forward", jitted, (key_data, arg_vals, aux_vals), sig)
        with obs.trace.span("device.forward", train=bool(is_train),
                            compile=is_compile) as sp:
            outs, new_aux = fn(key_data, arg_vals, aux_vals)
            cost = self._sig_cost.get(sig) if rec and not is_compile \
                else None
            if cost:
                # block before timing: on async backends the call above
                # returns futures, and attributing MFU to dispatch latency
                # would be meaningless — accurate device timing costs the
                # overlap, the same NaiveEngine-style trade the profiler's
                # aggregate_stats makes (docs/OBSERVABILITY.md). Only paid
                # when there IS a cost record to attribute.
                jax.block_until_ready((outs, new_aux))
                obs.device.annotate_span(sp, "forward",
                                         time.monotonic() - t0, cost)
        if is_train and self._grad_req != "null":
            # backward replays the same RNG key → identical dropout masks
            self._last_inputs = (key_data, arg_vals, aux_vals, bool(is_train))
        else:
            self._last_inputs = None
        for n, v in zip(aux_names, new_aux):
            self.aux_dict[n]._set_data(v)
        self.outputs_nd = [NDArray(o) for o in outs]
        return self.outputs_nd

    def _get_grad_fn(self, train: bool):
        key = ("grad", train)
        if key not in self._jit_cache:
            arg_names, aux_names, fn, _ = _build_graph_fn(self._symbol, train)

            def grad_fn(rng_key, arg_vals, aux_vals, cots):
                import jax.random as jr

                from . import random as _random

                if hasattr(jr, "wrap_key_data") and \
                        getattr(rng_key, "dtype", None) == jnp.uint32:
                    rng_key = jr.wrap_key_data(rng_key)
                with _random.trace_key_scope(rng_key):
                    _outs, vjp_fn = jax.vjp(lambda a: fn(a, aux_vals)[0],
                                            arg_vals)
                    (grads,) = vjp_fn(cots)
                return grads

            self._jit_cache[key] = jax.jit(grad_fn)
        return self._jit_cache[key]

    def backward(self, out_grads=None):
        if self._last_inputs is None:
            raise MXNetError("backward() requires forward(is_train=True) and "
                             "grad_req != 'null'")
        key_data, arg_vals, aux_vals, train = self._last_inputs
        if out_grads is None:
            cot = tuple(jnp.ones(o.shape, o.dtype) for o in self.outputs_nd)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cot = tuple(NDArray(g)._data for g in out_grads)
        from . import profiler as _profiler

        if _profiler.counting_dispatches():
            _profiler.count_dispatch("compiled")
        grad_fn = self._get_grad_fn(train)
        rec = obs.enabled()
        t0 = time.monotonic() if rec else 0.0
        fn, sig, is_compile = grad_fn, None, False
        if obs.device.active() or self._aot:
            sig = ("backward", bool(train), _avals_sig(arg_vals),
                   _avals_sig(cot))
            fn, is_compile = self._device_account(
                "backward", grad_fn, (key_data, arg_vals, aux_vals, cot),
                sig)
        with obs.trace.span("device.backward", compile=is_compile) as sp:
            grads = fn(key_data, arg_vals, aux_vals, cot)
            cost = self._sig_cost.get(sig) if rec and not is_compile \
                else None
            if cost:
                jax.block_until_ready(grads)  # see forward: honest MFU
                obs.device.annotate_span(sp, "backward",
                                         time.monotonic() - t0, cost)
        for n, g in zip(self._arg_names, grads):
            if n in self.grad_dict and g is not None:
                if self._grad_req == "add":
                    self.grad_dict[n]._set_data(self.grad_dict[n]._data + g)
                else:
                    self.grad_dict[n]._set_data(g)
        return [self.grad_dict.get(n) for n in self._arg_names]

    def __repr__(self):
        return f"<Executor {self._symbol!r} args={len(self.arg_dict)}>"
