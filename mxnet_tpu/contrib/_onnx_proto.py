"""Minimal protobuf wire-format writer/reader for the ONNX schema subset.

The image has no ``onnx`` package (and nothing may be installed), but ONNX
is just protobuf — and the protobuf wire format is three primitives:
varints, fixed-width scalars, and length-delimited blobs. This module
hand-rolls exactly the ModelProto/GraphProto/NodeProto/TensorProto/
AttributeProto/ValueInfoProto subset mx2onnx/onnx2mx need, using the public
field numbers from onnx.proto3. The reader accepts both packed and
unpacked repeated scalars (proto3 parsers must — so do we); the writer
emits unpacked, which every conformant parser accepts.

Reference counterpart: python/mxnet/contrib/onnx/ builds the same messages
via the onnx package's generated classes (TBV — mount empty).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

# --- wire primitives -------------------------------------------------------


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # proto negative ints are 10-byte varints
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def field_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def field_bytes(field: int, data: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(data)) + data


def field_string(field: int, s: str) -> bytes:
    return field_bytes(field, s.encode("utf-8"))


def field_message(field: int, msg: bytes) -> bytes:
    return field_bytes(field, msg)


def field_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(value))


# --- reader ----------------------------------------------------------------


def parse_message(data: bytes) -> Dict[int, List]:
    """Parse one message into {field_number: [raw values]}.

    Varint fields → int; 32/64-bit → raw 4/8 bytes; length-delimited →
    bytes (caller interprets as submessage, string, or packed scalars).
    """
    out: Dict[int, List] = {}
    i = 0
    n = len(data)
    while i < n:
        key, i = _read_varint(data, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, i = _read_varint(data, i)
        elif wire == 1:
            val, i = data[i:i + 8], i + 8
        elif wire == 2:
            ln, i = _read_varint(data, i)
            val, i = data[i:i + ln], i + ln
        elif wire == 5:
            val, i = data[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(val)
    return out


def _read_varint(data: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        b = data[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def ints_of(vals: List) -> List[int]:
    """Repeated int field: list of varints and/or packed blobs."""
    out: List[int] = []
    for v in vals:
        if isinstance(v, int):
            out.append(_signed64(v))
        else:  # packed
            i = 0
            while i < len(v):
                x, i = _read_varint(v, i)
                out.append(_signed64(x))
    return out


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= 1 << 63 else v


def float_of(raw) -> float:
    return struct.unpack("<f", raw)[0] if isinstance(raw, bytes) else raw


def string_of(raw: bytes) -> str:
    return raw.decode("utf-8")
