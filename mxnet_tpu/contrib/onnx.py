"""ONNX interop (reference contrib/onnx/ mx2onnx + onnx2mx — TBV).

Export serializes the symbol graph + params to the framework's own json/
params pair (StableHLO export is the TPU-native deployment path — see
HybridBlock.export); full ONNX protobuf emission requires the ``onnx``
package, which is not in this image — gated accordingly.
"""
from __future__ import annotations

__all__ = ["export_model", "import_model"]


def _have_onnx():
    try:
        import onnx  # noqa: F401

        return True
    except ImportError:
        return False


def export_model(sym, params, input_shape, input_type=None, onnx_file_path="model.onnx",
                 verbose=False, **kwargs):
    if not _have_onnx():
        raise ImportError("onnx package not available in this environment; "
                          "use Module.save_checkpoint / HybridBlock.export for "
                          "the native json+params format")
    raise NotImplementedError("ONNX emission lands with the onnx package")


def import_model(model_file):
    if not _have_onnx():
        raise ImportError("onnx package not available in this environment")
    raise NotImplementedError
