"""ONNX interop: mx2onnx export + onnx2mx import, no ``onnx`` package.

Reference counterpart: ``python/mxnet/contrib/onnx/`` (mx2onnx/onnx2mx —
TBV, mount empty). The reference builds protobuf messages through the onnx
package's generated classes; this image cannot install it, so the wire
format is emitted/parsed directly by ``_onnx_proto`` (the format is three
primitives; the schema field numbers are public). Covered surface:
- CNN/MLP: Conv, Gemm(+Flatten), BatchNormalization, activations,
  pooling (incl. global), Softmax/LogSoftmax, elementwise/broadcast
  arithmetic, Concat, Dropout, Reshape, Transpose, Sum, Clip, LeakyRelu,
  Identity, Tile, Slice, Squeeze/Unsqueeze.
- word_lm family: Embedding→Cast+Gather, fused RNN (LSTM mode, any
  num_layers)→per-layer ONNX LSTM chain with cuDNN→ONNX gate reorder,
  FC(flatten=False)→Transpose+MatMul+Add.
- transformer family: dot/batch_dot→MatMul (±Transpose), last-axis
  LayerNorm→opset-9 ReduceMean/Sub/Sqrt decomposition, erf-gelu→Erf
  decomposition, Exp/Log/Sqrt/Erf, Pow.
Opset 9, fp32 tensors; RNN family covers forward and bidirectional.

``export_model`` and ``import_model`` round-trip through real ONNX bytes:
tests/test_onnx.py re-imports an exported ResNet-style graph and checks
executor outputs match to 1e-5; tests/test_onnx_models.py round-trips the
word_lm LSTM and an attention block, and imports a fixture whose bytes
were encoded INDEPENDENTLY of _onnx_proto (shared-misreading guard).
"""
from __future__ import annotations

import ast
from typing import Dict, List

import numpy as np

from . import _onnx_proto as P

__all__ = ["export_model", "import_model"]

# AttributeProto.type enum
_AT_FLOAT, _AT_INT, _AT_STRING, _AT_TENSOR = 1, 2, 3, 4
_AT_FLOATS, _AT_INTS, _AT_STRINGS = 6, 7, 8
_DT_FLOAT, _DT_INT64 = 1, 7


def _tuple(v, n=2):
    if isinstance(v, str):
        v = ast.literal_eval(v)
    if isinstance(v, (int, float)):
        v = (int(v),) * n
    return tuple(int(x) for x in v)


def _flag(v):
    return v in (True, 1, "1", "true", "True")


# --------------------------------------------------------------------------
# Attribute / tensor / node emitters
# --------------------------------------------------------------------------

def _attr_int(name, v):
    return P.field_message(5, P.field_string(1, name) + P.field_varint(3, v)
                           + P.field_varint(20, _AT_INT))


def _attr_float(name, v):
    return P.field_message(5, P.field_string(1, name)
                           + P.field_float(2, v) + P.field_varint(20, _AT_FLOAT))


def _attr_ints(name, vals):
    body = P.field_string(1, name)
    for v in vals:
        body += P.field_varint(8, v)
    return P.field_message(5, body + P.field_varint(20, _AT_INTS))


def _attr_str(name, s):
    return P.field_message(5, P.field_string(1, name) + P.field_string(4, s)
                           + P.field_varint(20, _AT_STRING))


def _tensor(name, arr):
    arr = np.ascontiguousarray(arr)
    if arr.dtype == np.int64:
        dt = _DT_INT64
    else:
        arr = arr.astype(np.float32)
        dt = _DT_FLOAT
    body = b""
    for d in arr.shape:
        body += P.field_varint(1, d)
    body += P.field_varint(2, dt)
    body += P.field_string(8, name)
    body += P.field_bytes(9, arr.tobytes())  # raw_data, little-endian
    return body


def _node(op_type, inputs, outputs, name, attrs=b""):
    body = b""
    for i in inputs:
        body += P.field_string(1, i)
    for o in outputs:
        body += P.field_string(2, o)
    body += P.field_string(3, name) + P.field_string(4, op_type) + attrs
    return P.field_message(1, body)  # GraphProto.node


def _value_info(name, shape, elem_type=_DT_FLOAT):
    dims = b""
    for d in shape:
        dims += P.field_message(1, P.field_varint(1, int(d)))
    ttype = P.field_varint(1, elem_type) + P.field_message(2, dims)
    return P.field_string(1, name) + P.field_message(2, P.field_message(1, ttype))


# --------------------------------------------------------------------------
# Export: mx Symbol graph -> ONNX GraphProto nodes
# --------------------------------------------------------------------------

_ACT_MAP = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "softrelu": "Softplus", "softsign": "Softsign"}
_ELEM_MAP = {"elemwise_add": "Add", "_plus": "Add", "broadcast_add": "Add",
             "elemwise_sub": "Sub", "_minus": "Sub", "broadcast_sub": "Sub",
             "elemwise_mul": "Mul", "_mul": "Mul", "broadcast_mul": "Mul",
             "elemwise_div": "Div", "_div": "Div", "broadcast_div": "Div"}


def _conv_attrs(a):
    kernel = _tuple(a.get("kernel", (1, 1)))
    stride = _tuple(a.get("stride", (1,) * len(kernel)), len(kernel))
    pad = _tuple(a.get("pad", (0,) * len(kernel)), len(kernel))
    dilate = _tuple(a.get("dilate", (1,) * len(kernel)), len(kernel))
    out = _attr_ints("kernel_shape", kernel) + _attr_ints("strides", stride)
    out += _attr_ints("pads", pad + pad) + _attr_ints("dilations", dilate)
    out += _attr_int("group", int(a.get("num_group", 1)))
    return out


# mx/cuDNN -> ONNX gate orders: LSTM [i,f,g,o]->[i,o,f,c], GRU
# [r,z,n]->[z,r,h] (both conventions use the cuDNN linear_before_reset
# recurrence our scan implements), vanilla: single gate.
_RNN_ONNX = {
    "lstm": ("LSTM", 4, (0, 3, 1, 2), (0, 2, 3, 1)),
    "gru": ("GRU", 3, (1, 0, 2), (1, 0, 2)),
    "rnn_tanh": ("RNN", 1, (0,), (0,)),
    "rnn_relu": ("RNN", 1, (0,), (0,)),
}


def _gate_reorder(mat, h, perm):
    """Permute stacked (g*h, ...) gate blocks between conventions."""
    blocks = [mat[i * h:(i + 1) * h] for i in range(len(perm))]
    return np.concatenate([blocks[j] for j in perm], axis=0)


def _export_node(node, in_names, out_name, params, extra_inits,
                 in_shapes=None):
    """Returns (onnx node bytes, handled: bool).

    in_shapes: per-input shapes when shape inference succeeded (None
    entries otherwise) — used for the opset-9 Softmax axis guard, RNN
    weight unpacking, and MatMul transpose perms.
    """
    op = node._op
    a = node._attrs
    nm = node._name
    in_rank = None
    if in_shapes and in_shapes[0] is not None:
        in_rank = len(in_shapes[0])
    if op == "Convolution":
        return _node("Conv", in_names, [out_name], nm, _conv_attrs(a)), True
    if op == "FullyConnected":
        flat_out = nm + "_flat"
        nodes = b""
        data_in = in_names[0]
        if not _flag(a.get("flatten", True)) and in_rank != 2:
            # ND input (e.g. the word_lm decoder over (T,N,H)): opset-9
            # Gemm is 2D-only, so emit Transpose(W) + MatMul (+ Add bias)
            wt = nm + "_wT"
            nodes += _node("Transpose", [in_names[1]], [wt], wt,
                           _attr_ints("perm", (1, 0)))
            mm = nm + "_mm" if len(in_names) > 2 else out_name
            nodes += _node("MatMul", [data_in, wt], [mm], nm + "_matmul")
            if len(in_names) > 2:
                nodes += _node("Add", [mm, in_names[2]], [out_name],
                               nm + "_bias")
            return nodes, True
        if _flag(a.get("flatten", True)):
            nodes += _node("Flatten", [in_names[0]], [flat_out],
                           nm + "_flatten", _attr_int("axis", 1))
            data_in = flat_out
        ins = [data_in] + in_names[1:]
        if len(ins) == 2:  # no_bias: opset-9 Gemm requires C — zeros
            zname = nm + "_zero_bias"
            num_hidden = int(a.get("num_hidden"))
            extra_inits.append((zname, np.zeros(num_hidden, np.float32)))
            ins.append(zname)
        nodes += _node("Gemm", ins, [out_name], nm, _attr_int("transB", 1))
        return nodes, True
    if op == "BatchNorm":
        # mxnet BatchNorm default eps is 1e-3 (ops/nn.py), not ONNX's 1e-5
        attrs = _attr_float("epsilon", float(a.get("eps", 1e-3)))
        attrs += _attr_float("momentum", float(a.get("momentum", 0.9)))
        return _node("BatchNormalization", in_names, [out_name], nm, attrs), True
    if op == "Activation":
        act = a.get("act_type", "relu")
        if act in _ACT_MAP:
            return _node(_ACT_MAP[act], in_names, [out_name], nm), True
        return b"", False
    if op in ("relu", "sigmoid", "tanh"):
        return _node(_ACT_MAP[op], in_names, [out_name], nm), True
    if op == "LeakyReLU":
        if a.get("act_type", "leaky") != "leaky":
            return b"", False
        return _node("LeakyRelu", in_names, [out_name], nm,
                     _attr_float("alpha", float(a.get("slope", 0.25)))), True
    if op == "Pooling":
        ptype = a.get("pool_type", "max")
        if _flag(a.get("global_pool", False)):
            op_t = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
            return _node(op_t, in_names, [out_name], nm), True
        kernel = _tuple(a.get("kernel", (1, 1)))
        # framework Pooling default stride is 1 (ops/nn.py), NOT kernel
        stride = _tuple(a.get("stride", (1,) * len(kernel)), len(kernel))
        pad = _tuple(a.get("pad", (0,) * len(kernel)), len(kernel))
        attrs = (_attr_ints("kernel_shape", kernel)
                 + _attr_ints("strides", stride)
                 + _attr_ints("pads", pad + pad))
        op_t = "MaxPool" if ptype == "max" else "AveragePool"
        if ptype == "avg":
            attrs += _attr_int(
                "count_include_pad",
                1 if _flag(a.get("count_include_pad", True)) else 0)
        return _node(op_t, in_names, [out_name], nm, attrs), True
    if op in ("softmax", "SoftmaxOutput", "SoftmaxActivation"):
        ins = in_names[:1]
        ax = int(a.get("axis", -1 if op == "softmax" else 1))
        # opset-9 Softmax coerces to 2D at `ax`: softmax over ALL trailing
        # dims, which equals mx single-axis softmax only when ax is the last
        # dim. Mirror the importer's guard — exporting anything else would
        # silently diverge on conformant runtimes (e.g. axis=1 on NCHW maps).
        last_ok = ax == -1 or (in_rank is not None and ax == in_rank - 1)
        if not last_ok:
            raise ValueError(
                f"mx2onnx: opset-9 Softmax with axis={ax} on a rank-"
                f"{in_rank if in_rank is not None else '?'} input uses "
                "coerce-to-2D semantics that diverge from single-axis "
                "softmax; only last-dim softmax exports faithfully")
        return _node("Softmax", ins, [out_name], nm, _attr_int("axis", ax)), True
    if op == "log_softmax":
        return _node("LogSoftmax", in_names, [out_name], nm,
                     _attr_int("axis", int(a.get("axis", -1)))), True
    if op in _ELEM_MAP:
        return _node(_ELEM_MAP[op], in_names, [out_name], nm), True
    if op == "Concat":
        ax = int(a.get("dim", a.get("axis", 1)))
        return _node("Concat", in_names, [out_name], nm,
                     _attr_int("axis", ax)), True
    if op == "Flatten":
        return _node("Flatten", in_names, [out_name], nm,
                     _attr_int("axis", 1)), True
    if op == "Dropout":
        return _node("Dropout", in_names[:1], [out_name], nm,
                     _attr_float("ratio", float(a.get("p", 0.5)))), True
    if op in ("Reshape", "reshape"):
        shape = _tuple(a.get("shape"), 1)
        sname = nm + "_shape"
        extra_inits.append((sname, np.asarray(shape, np.int64)))
        return _node("Reshape", [in_names[0], sname], [out_name], nm), True
    if op == "transpose":
        axes = a.get("axes", ())
        return _node("Transpose", in_names, [out_name], nm,
                     _attr_ints("perm", _tuple(axes, 1)) if axes else b""), True
    if op in ("add_n", "ElementWiseSum"):
        return _node("Sum", in_names, [out_name], nm), True
    if op == "mean" and not node._attrs.get("axis"):
        return b"", False
    if op == "clip":
        return _node("Clip", in_names, [out_name], nm,
                     _attr_float("min", float(a.get("a_min")))
                     + _attr_float("max", float(a.get("a_max")))), True
    if op == "identity":
        return _node("Identity", in_names, [out_name], nm), True
    if op == "Embedding":
        # mx Embedding takes float indices; ONNX Gather needs int64
        idx64 = nm + "_idx64"
        nodes = _node("Cast", [in_names[0]], [idx64], idx64,
                      _attr_int("to", 7))  # TensorProto.INT64
        nodes += _node("Gather", [in_names[1], idx64], [out_name], nm,
                       _attr_int("axis", 0))
        return nodes, True
    if op == "RNN":
        return _export_rnn(node, in_names, out_name, params, extra_inits,
                           in_shapes)
    if op in ("dot", "batch_dot"):
        ta = _flag(a.get("transpose_a", False))
        tb = _flag(a.get("transpose_b", False))
        nodes = b""
        names = list(in_names)
        for pos, t in ((0, ta), (1, tb)):
            if not t:
                continue
            shp = in_shapes[pos] if in_shapes else None
            rank = len(shp) if shp is not None else (2 if op == "dot" else 3)
            perm = tuple(range(rank - 2)) + (rank - 1, rank - 2)
            tnm = f"{nm}_in{pos}T"
            nodes += _node("Transpose", [names[pos]], [tnm], tnm,
                           _attr_ints("perm", perm))
            names[pos] = tnm
        nodes += _node("MatMul", names, [out_name], nm)
        return nodes, True
    if op == "LayerNorm":
        ax = int(a.get("axis", -1))
        rank = in_rank
        if not (ax == -1 or (rank is not None and ax == rank - 1)):
            raise ValueError(
                f"mx2onnx: LayerNorm axis={ax} export supports only the "
                "last axis (opset-9 decomposition reduces over -1)")
        eps_nm = nm + "_eps"
        extra_inits.append((eps_nm,
                            np.float32(a.get("eps", 1e-5)).reshape(())))
        x, g, b_ = in_names[0], in_names[1], in_names[2]
        # positive reduce axis when the rank is known — opset-9 Reduce ops
        # predate the negative-axes clarification
        red = [rank - 1] if rank is not None else [-1]
        n = lambda t: f"{nm}_{t}"  # noqa: E731
        nodes = _node("ReduceMean", [x], [n("m")], n("m"),
                      _attr_ints("axes", red) + _attr_int("keepdims", 1))
        nodes += _node("Sub", [x, n("m")], [n("d")], n("d"))
        nodes += _node("Mul", [n("d"), n("d")], [n("d2")], n("d2"))
        nodes += _node("ReduceMean", [n("d2")], [n("v")], n("v"),
                       _attr_ints("axes", red) + _attr_int("keepdims", 1))
        nodes += _node("Add", [n("v"), eps_nm], [n("ve")], n("ve"))
        nodes += _node("Sqrt", [n("ve")], [n("sd")], n("sd"))
        nodes += _node("Div", [n("d"), n("sd")], [n("q")], n("q"))
        nodes += _node("Mul", [n("q"), g], [n("sg")], n("sg"))
        nodes += _node("Add", [n("sg"), b_], [out_name], nm)
        return nodes, True
    if op == "gelu" and a.get("approximation", "erf") == "erf":
        # 0.5 * x * (1 + erf(x / sqrt(2))) — exact ops/elemwise.py form
        s2 = nm + "_sqrt2"
        half = nm + "_half"
        one = nm + "_one"
        extra_inits += [(s2, np.float32(1.4142135623730951).reshape(())),
                        (half, np.float32(0.5).reshape(())),
                        (one, np.float32(1.0).reshape(()))]
        x = in_names[0]
        n = lambda t: f"{nm}_{t}"  # noqa: E731
        nodes = _node("Div", [x, s2], [n("xs")], n("xs"))
        nodes += _node("Erf", [n("xs")], [n("e")], n("e"))
        nodes += _node("Add", [n("e"), one], [n("e1")], n("e1"))
        nodes += _node("Mul", [x, n("e1")], [n("xe")], n("xe"))
        nodes += _node("Mul", [n("xe"), half], [out_name], nm)
        return nodes, True
    if op in ("exp", "log", "sqrt", "erf"):
        return _node({"exp": "Exp", "log": "Log", "sqrt": "Sqrt",
                      "erf": "Erf"}[op], in_names, [out_name], nm), True
    if op == "squeeze":
        ax = a.get("axis")
        if ax is None:
            attrs = b""
        else:
            axes = [int(x) for x in _tuple(ax, 1)]
            if any(x < 0 for x in axes):
                if in_rank is None:
                    raise ValueError("mx2onnx: negative squeeze axis needs "
                                     "shape inference (opset-9 Squeeze "
                                     "requires non-negative axes)")
                axes = [x % in_rank for x in axes]
            attrs = _attr_ints("axes", axes)
        return _node("Squeeze", in_names, [out_name], nm, attrs), True
    if op == "expand_dims":
        ax = int(a.get("axis", 0))
        if ax < 0:
            if in_rank is None:
                raise ValueError("mx2onnx: negative expand_dims axis needs "
                                 "shape inference (opset-9 Unsqueeze "
                                 "requires non-negative axes)")
            ax %= in_rank + 1
        return _node("Unsqueeze", in_names, [out_name], nm,
                     _attr_ints("axes", [ax])), True
    if op == "tile":
        reps = _tuple(a.get("reps", a.get("repeats", ())), 1)
        rname = nm + "_reps"
        extra_inits.append((rname, np.asarray(reps, np.int64)))
        return _node("Tile", [in_names[0], rname], [out_name], nm), True
    if op == "slice_axis":
        ax = int(a.get("axis", 0))
        begin = int(a.get("begin", 0))
        end = a.get("end")
        end = 2 ** 31 - 1 if end in (None, "None") else int(end)
        return _node("Slice", in_names, [out_name], nm,
                     _attr_ints("axes", [ax]) + _attr_ints("starts", [begin])
                     + _attr_ints("ends", [end])), True
    return b"", False


def _attr_strs(name, vals):
    body = P.field_string(1, name)
    for v in vals:
        body += P.field_string(9, v)  # AttributeProto.strings = field 9
    return P.field_message(5, body + P.field_varint(20, _AT_STRINGS))


def _export_rnn(node, in_names, out_name, params, extra_inits, in_shapes):
    """mx fused RNN -> a chain of ONNX LSTM/GRU/RNN nodes, one per layer
    (the ONNX ops are single-layer). The cuDNN-canonical flat parameter
    vector (ops/rnn.py layout) unpacks into per-layer W/R/B with gate
    reorder; GRU exports linear_before_reset=1 (the cuDNN recurrence the
    scan implements). Dropout (`p`) is ignored — exported graphs are
    inference graphs, where it is inactive anyway."""
    a = node._attrs
    nm = node._name
    mode = a.get("mode", "rnn_tanh")
    onnx_op, g, perm, _ = _RNN_ONNX[mode]
    bidir = _flag(a.get("bidirectional", False))
    dirs = 2 if bidir else 1
    h = int(a.get("state_size"))
    L = int(a.get("num_layers", 1))
    pname = node._inputs[1]._base()._name
    pvec = params.get(pname)
    if pvec is None:
        raise ValueError(f"mx2onnx: RNN parameter vector {pname!r} must be "
                         "a stored parameter")
    if not in_shapes or in_shapes[0] is None:
        raise ValueError("mx2onnx: RNN export needs input shape inference")
    input_size = int(in_shapes[0][-1])
    pvec = np.asarray(pvec, np.float32).reshape(-1)
    # cuDNN-canonical layout: all (layer, direction) weights first, then
    # all biases in the same order (ops/rnn.py rnn_unpack_params)
    off = 0
    Ws, Rs, Bs = [], [], []
    for layer in range(L):
        isz = input_size if layer == 0 else h * dirs
        wd, rd = [], []
        for _d in range(dirs):
            wd.append(pvec[off:off + g * h * isz].reshape(g * h, isz))
            off += g * h * isz
            rd.append(pvec[off:off + g * h * h].reshape(g * h, h))
            off += g * h * h
        Ws.append(wd)
        Rs.append(rd)
    for layer in range(L):
        bd = []
        for _d in range(dirs):
            b_ih = pvec[off:off + g * h]
            off += g * h
            b_hh = pvec[off:off + g * h]
            off += g * h
            bd.append((b_ih, b_hh))
        Bs.append(bd)
    has_cell = mode == "lstm"
    attrs = _attr_int("hidden_size", h)
    if bidir:
        attrs += _attr_str("direction", "bidirectional")
    if mode == "gru":
        attrs += _attr_int("linear_before_reset", 1)
    elif mode == "rnn_relu":
        attrs += _attr_strs("activations", ["Relu"] * dirs)
    nodes = b""
    x_name = in_names[0]
    h0_name = in_names[2]
    c0_name = in_names[3] if has_cell and len(in_names) > 3 else None
    for layer in range(L):
        wn, rn, bn = (f"{nm}_W{layer}", f"{nm}_R{layer}", f"{nm}_B{layer}")
        extra_inits.append((wn, np.stack(
            [_gate_reorder(Ws[layer][d], h, perm) for d in range(dirs)])))
        extra_inits.append((rn, np.stack(
            [_gate_reorder(Rs[layer][d], h, perm) for d in range(dirs)])))
        extra_inits.append((bn, np.stack(
            [np.concatenate([_gate_reorder(Bs[layer][d][0], h, perm),
                             _gate_reorder(Bs[layer][d][1], h, perm)])
             for d in range(dirs)])))
        if L == 1:
            h0_l, c0_l = h0_name, c0_name
        else:
            sl = (_attr_ints("axes", [0])
                  + _attr_ints("starts", [layer * dirs])
                  + _attr_ints("ends", [(layer + 1) * dirs]))
            h0_l = f"{nm}_h0_{layer}"
            nodes += _node("Slice", [h0_name], [h0_l], h0_l, sl)
            c0_l = None
            if has_cell:
                c0_l = f"{nm}_c0_{layer}"
                nodes += _node("Slice", [c0_name], [c0_l], c0_l, sl)
        y4 = f"{nm}_l{layer}_y4"
        rnn_ins = [x_name, wn, rn, bn, "", h0_l]
        if has_cell:
            rnn_ins.append(c0_l)
        nodes += _node(onnx_op, rnn_ins, [y4], f"{nm}_l{layer}", attrs)
        y3 = out_name if layer == L - 1 else f"{nm}_l{layer}_y"
        if bidir:
            # ONNX Y (T, 2, N, h) -> mx (T, N, 2h): swap dir/batch axes,
            # then merge the direction axis into the feature dim
            yt = f"{nm}_l{layer}_yt"
            nodes += _node("Transpose", [y4], [yt], yt,
                           _attr_ints("perm", (0, 2, 1, 3)))
            shp = f"{nm}_l{layer}_yshape"
            extra_inits.append((shp, np.asarray([0, 0, 2 * h], np.int64)))
            nodes += _node("Reshape", [yt, shp], [y3], y3 + "_rs")
        else:
            # ONNX Y is (T, num_dir, N, h); drop the direction axis
            nodes += _node("Squeeze", [y4], [y3], y3 + "_sq",
                           _attr_ints("axes", [1]))
        x_name = y3
    return nodes, True


def export_model(sym, params, input_shape, input_type=None,
                 onnx_file_path="model.onnx", verbose=False, **kwargs):
    """Export a symbol + params to an ONNX file (reference mx2onnx API).

    input_shape: one shape tuple or a list of them (one per graph input).
    Returns onnx_file_path.
    """
    from ..ndarray import NDArray

    np_params = {}
    for k, v in dict(params or {}).items():
        k = k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k
        np_params[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)

    base = sym._base() if hasattr(sym, "_base") else sym
    topo = base._topo()
    # fix_gamma BatchNorms ignore their stored gamma (it is forced to 1):
    # override BEFORE initializers serialize, or the stale values ship
    for node in topo:
        if node._op == "BatchNorm" and _flag(node._attrs.get("fix_gamma",
                                                             True)):
            gname = node._inputs[1]._base()._name
            if gname in np_params:
                np_params[gname] = np.ones_like(np_params[gname])
    shapes = ([tuple(input_shape)] if isinstance(input_shape[0], int)
              else [tuple(s) for s in input_shape])

    # Per-node shape inference (for rank-dependent export guards). Build the
    # known-shape map the same way the export loop assigns graph inputs:
    # params from np_params, data inputs from `shapes` in topo order.
    known = {}
    si = 0
    for node in topo:
        if node._op is not None:
            continue
        if node._name in np_params:
            known[node._name] = np_params[node._name].shape
        else:
            known[node._name] = shapes[min(si, len(shapes) - 1)]
            si += 1
    try:
        from ..symbol.symbol import infer_node_shapes
        node_shapes = infer_node_shapes(base, known)
    except Exception:
        node_shapes = {}

    # params consumed ONLY as RNN packed-parameter vectors are replaced by
    # the repacked per-layer W/R/B initializers — writing the flat vector
    # too would double the RNN weight bytes and leave a dead arg_param
    replaced_params = set()
    for node in topo:
        if node._op == "RNN" and len(node._inputs) > 1:
            replaced_params.add(node._inputs[1]._base()._name)
    for node in topo:
        for pos, i in enumerate(node._inputs):
            if node._op == "RNN" and pos == 1:
                continue
            replaced_params.discard(i._base()._name)

    out_of: Dict[int, str] = {}
    nodes = b""
    graph_inputs: List[bytes] = []
    inits = b""
    extra_inits: List = []
    shape_i = 0
    for node in topo:
        if node._op is None:
            out_of[id(node)] = node._name
            if node._name in np_params:
                if node._name not in replaced_params:
                    inits += P.field_message(5, _tensor(node._name,
                                                        np_params[node._name]))
            else:
                shp = shapes[min(shape_i, len(shapes) - 1)]
                shape_i += 1
                graph_inputs.append(P.field_message(
                    11, _value_info(node._name, shp)))
            continue
        for i in node._inputs:
            if i._index:
                raise ValueError(
                    f"mx2onnx: {node._op!r} consumes output {i._index} of a "
                    "multi-output node — not supported")
        in_names = [out_of[id(i._base())] for i in node._inputs]
        out_name = node._name + "_out"
        in_shapes = []
        for i in node._inputs:
            s = node_shapes.get(id(i._base()))
            if isinstance(s, list) and i._index is not None:
                s = s[i._index]
            in_shapes.append(s if isinstance(s, tuple) else None)
        nb, ok = _export_node(node, in_names, out_name, np_params,
                              extra_inits, in_shapes=in_shapes)
        if not ok:
            raise ValueError(f"mx2onnx: op {node._op!r} has no ONNX mapping; "
                             "supported set is the model-zoo CNN/MLP family")
        nodes += nb
        out_of[id(node)] = out_name
    for name, arr in extra_inits:
        inits += P.field_message(5, _tensor(name, arr))

    final = out_of[id(topo[-1])]
    graph = (nodes + P.field_string(2, "mxnet_tpu_export") + inits
             + b"".join(graph_inputs)
             + P.field_message(12, _value_info(final, ())))
    model = (P.field_varint(1, 7)                       # ir_version 7
             + P.field_string(2, "mxnet_tpu")
             + P.field_message(7, graph)
             + P.field_message(8, P.field_varint(2, 9)))  # opset 9
    with open(onnx_file_path, "wb") as f:
        f.write(model)
    if verbose:
        print(f"exported {len(topo)} nodes -> {onnx_file_path}")
    return onnx_file_path


# --------------------------------------------------------------------------
# Import: ONNX bytes -> mx Symbol + params
# --------------------------------------------------------------------------

def _parse_tensor(raw):
    f = P.parse_message(raw)
    dims = P.ints_of(f.get(1, []))
    dtype = f.get(2, [1])[0]
    name = P.string_of(f[8][0])
    if 9 in f:
        buf = f[9][0]
        arr = np.frombuffer(buf, np.float32 if dtype == _DT_FLOAT
                            else np.int64).reshape(dims)
    elif dtype == _DT_FLOAT and 4 in f:
        arr = np.array([P.float_of(x) for x in f[4]],
                       np.float32).reshape(dims)
    elif dtype == _DT_INT64 and 7 in f:
        arr = np.array(P.ints_of(f[7]), np.int64).reshape(dims)
    else:
        raise ValueError(f"unsupported TensorProto encoding for {name}")
    return name, arr


def _import_onnx_rnn(op, ins, outs, a, name, inits, sym_of, S):
    """ONNX LSTM/GRU/RNN node -> mx fused RNN symbol. W/R/B initializers
    repack (gate reorder + per-direction flatten) into the cuDNN-canonical
    vector ops/rnn.py unpacks; forward and bidirectional forms supported,
    Y (the per-step output) must be the consumed leg. GRU requires
    linear_before_reset=1 — the default-0 ONNX recurrence differs from the
    cuDNN variant the scan implements."""
    direction = a.get("direction", "forward")
    direction = (direction.decode() if isinstance(direction, bytes)
                 else str(direction))
    if direction not in ("forward", "bidirectional"):
        raise ValueError(f"onnx2mx: {op} direction={direction!r} "
                         "unsupported (forward|bidirectional)")
    bidir = direction == "bidirectional"
    if a.get("clip") is not None:
        raise ValueError(f"onnx2mx: {op} cell clipping unsupported")
    acts = [x.decode() if isinstance(x, bytes) else str(x)
            for x in (a.get("activations") or [])]
    n_dir = 2 if bidir else 1
    if op == "LSTM":
        # spec: the activations list repeats per direction
        if acts and acts != ["Sigmoid", "Tanh", "Tanh"] * n_dir:
            raise ValueError(f"onnx2mx: LSTM activations {acts} differ "
                             "from the fixed cuDNN recurrence")
        if len(ins) > 7 and ins[7]:
            raise ValueError("onnx2mx: LSTM peephole input P unsupported")
        mode = "lstm"
    elif op == "GRU":
        if not int(a.get("linear_before_reset", 0)):
            raise ValueError(
                "onnx2mx: GRU with linear_before_reset=0 uses a recurrence "
                "the cuDNN-convention scan cannot reproduce")
        if acts and acts != ["Sigmoid", "Tanh"] * n_dir:
            raise ValueError(f"onnx2mx: GRU activations {acts} differ "
                             "from the fixed cuDNN recurrence")
        mode = "gru"
    else:
        if acts and (acts[0] not in ("Tanh", "Relu")
                     or acts != [acts[0]] * len(acts)
                     or len(acts) not in (0, n_dir)):
            raise ValueError(f"onnx2mx: RNN activations {acts} unsupported "
                             "(both directions must share Tanh or Relu)")
        mode = "rnn_relu" if acts and acts[0] == "Relu" else "rnn_tanh"
    _, g, _, unperm_order = _RNN_ONNX[mode]
    if len(ins) > 4 and ins[4]:
        raise ValueError(f"onnx2mx: {op} sequence_lens input unsupported")
    for pos in (1, 2):
        if ins[pos] not in inits:
            raise ValueError(f"onnx2mx: {op} W/R must be initializers")
    h = int(a.get("hidden_size"))
    W = np.asarray(inits.pop(ins[1]), np.float32)
    R = np.asarray(inits.pop(ins[2]), np.float32)
    dirs = n_dir
    if W.shape[0] != dirs:
        raise ValueError(f"onnx2mx: {op} W num_directions {W.shape[0]} "
                         f"does not match direction={direction!r}")
    if len(ins) > 3 and ins[3]:
        if ins[3] not in inits:
            raise ValueError(f"onnx2mx: {op} B must be an initializer "
                             "(computed/graph-input biases unsupported)")
        B = np.asarray(inits.pop(ins[3]), np.float32)
    else:
        B = np.zeros((dirs, 2 * g * h), np.float32)

    def unperm(mat):
        return _gate_reorder(mat, h, unperm_order)

    # cuDNN-canonical: weights for every direction first, then biases
    parts = []
    for d in range(dirs):
        parts += [unperm(W[d]).reshape(-1), unperm(R[d]).reshape(-1)]
    for d in range(dirs):
        parts += [unperm(B[d][:g * h]), unperm(B[d][g * h:])]
    flat = np.concatenate(parts)
    pname = name + "_rnn_params"
    inits[pname] = flat

    def default_state():
        # spec default is zeros with the INPUT's batch dim — build it from
        # X so the shape stays symbolic: (1, N, 1) zeros tiled out
        t0 = S.slice_axis(sym_of(ins[0]), axis=0, begin=0, end=1)
        z = S.mean(t0, axis=-1, keepdims=True) * 0.0
        return S.tile(z, reps=(dirs, 1, h))

    h0 = (sym_of(ins[5]) if len(ins) > 5 and ins[5] else default_state())
    rnn_args = [sym_of(ins[0]), S.Variable(pname), h0]
    if mode == "lstm":
        rnn_args.append(sym_of(ins[6]) if len(ins) > 6 and ins[6]
                        else default_state())
    rnn = S.RNN(*rnn_args, state_size=h, num_layers=1, mode=mode,
                bidirectional=bidir, name=name)
    if not bidir:
        # ONNX Y is (T, num_dir=1, N, h): restore the direction axis the
        # mx RNN output (T, N, h) lacks so downstream Squeeze/Slice fit
        return S.expand_dims(rnn, axis=1, name=name + "_y4")
    # mx (T, N, 2h) -> ONNX Y (T, 2, N, h)
    r4 = S.reshape(rnn, shape=(0, 0, 2, h), name=name + "_split")
    return S.transpose(r4, axes=(0, 2, 1, 3), name=name + "_y4")


def _parse_attrs(node_fields):
    attrs = {}
    for raw in node_fields.get(5, []):
        f = P.parse_message(raw)
        name = P.string_of(f[1][0])
        if 3 in f:
            attrs[name] = P.ints_of(f[3])[0]
        elif 2 in f:
            attrs[name] = P.float_of(f[2][0])
        elif 8 in f:
            attrs[name] = P.ints_of(f[8])
        elif 9 in f:  # strings (e.g. RNN activations)
            attrs[name] = [P.string_of(x) for x in f[9]]
        elif 4 in f:
            attrs[name] = P.string_of(f[4][0])
        elif 5 in f:
            attrs[name] = _parse_tensor(f[5][0])[1]
    return attrs


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params) (reference onnx2mx API)."""
    from .. import symbol as sym_mod
    from ..ndarray import array

    with open(model_file, "rb") as f:
        model = P.parse_message(f.read())
    graph = P.parse_message(model[7][0])
    opset = 9
    for raw in model.get(8, []):  # opset_import (default domain)
        f8 = P.parse_message(raw)
        if 1 not in f8 or P.string_of(f8[1][0]) in ("", "ai.onnx"):
            opset = P.ints_of(f8.get(2, [9]))[0]

    inits = {}
    for raw in graph.get(5, []):
        name, arr = _parse_tensor(raw)
        inits[name] = arr

    tensors: Dict[str, object] = {}
    aux_names = set()
    for raw in graph.get(11, []):  # graph inputs
        name = P.string_of(P.parse_message(raw)[1][0])
        if name not in inits:
            tensors[name] = sym_mod.Variable(name)

    auto_vars = set()  # names sym_of materialized out of thin air

    def sym_of(name):
        if name not in tensors:
            tensors[name] = sym_mod.Variable(name)
            auto_vars.add(name)
        return tensors[name]

    # Initializers consumed as Clip bounds: read WITHOUT popping (exporters
    # dedupe constants — one min/max tensor may feed many Clip nodes, e.g.
    # every ReLU6 in a MobileNet). Count total input-uses per name so bound
    # tensors are stripped from params only when nothing else consumes them.
    use_count: Dict[str, int] = {}
    for raw in graph.get(1, []):
        for x in P.parse_message(raw).get(1, []):
            nm_u = P.string_of(x)
            use_count[nm_u] = use_count.get(nm_u, 0) + 1
    bound_uses: Dict[str, int] = {}

    pending_flatten: Dict[str, str] = {}  # flatten_out -> flatten_in
    for raw in graph.get(1, []):
        f = P.parse_message(raw)
        ins = [P.string_of(x) for x in f.get(1, [])]
        outs = [P.string_of(x) for x in f.get(2, [])]
        name = P.string_of(f[3][0]) if 3 in f else outs[0]
        op = P.string_of(f[4][0])
        a = _parse_attrs(f)
        S = sym_mod

        def two(key, default):
            v = a.get(key, default)
            return tuple(int(x) for x in v)

        if op == "Conv":
            k = two("kernel_shape", (1, 1))
            pads = a.get("pads", [0] * (2 * len(k)))
            if list(pads[:len(k)]) != list(pads[len(k):]):
                raise ValueError(
                    f"onnx2mx: asymmetric Conv pads {pads} are not "
                    "supported (mx Convolution pads symmetrically)")
            no_bias = len(ins) == 2
            args = dict(kernel=k, stride=two("strides", (1,) * len(k)),
                        pad=tuple(int(x) for x in pads[:len(k)]),
                        dilate=two("dilations", (1,) * len(k)),
                        num_group=int(a.get("group", 1)),
                        num_filter=int(inits[ins[1]].shape[0]),
                        no_bias=no_bias, name=name)
            syms = [sym_of(x) for x in ins]
            out = S.Convolution(*syms, **args)
        elif op == "Gemm":
            if (int(a.get("transB", 0)) != 1
                    or float(a.get("alpha", 1.0)) != 1.0
                    or float(a.get("beta", 1.0)) != 1.0):
                raise ValueError(
                    "onnx2mx: only Gemm(transB=1, alpha=1, beta=1) — the "
                    "FullyConnected layout — is supported")
            data_name = ins[0]
            flatten = data_name in pending_flatten
            if flatten:
                data_name = pending_flatten[ins[0]]
            w = inits[ins[1]]
            # only OUR exporter's synthetic placeholder marks no_bias — a
            # genuinely all-zero bias in a third-party model must survive
            zero_bias = (len(ins) > 2 and ins[2] in inits
                         and ins[2].endswith("_zero_bias")
                         and not inits[ins[2]].any())
            syms = [sym_of(data_name), sym_of(ins[1])]
            no_bias = zero_bias or len(ins) <= 2
            if not no_bias:
                syms.append(sym_of(ins[2]))
            elif len(ins) > 2:
                inits.pop(ins[2], None)
            out = S.FullyConnected(*syms, num_hidden=int(w.shape[0]),
                                   flatten=flatten, no_bias=no_bias,
                                   name=name)
        elif op == "Flatten":
            # fold Flatten+Gemm back into FC(flatten=True); standalone
            # Flatten emitted for any other consumer below
            pending_flatten[outs[0]] = ins[0]
            tensors[outs[0]] = S.Flatten(sym_of(ins[0]), name=name)
            continue
        elif op == "BatchNormalization":
            syms_bn = [sym_of(ins[0]), sym_of(ins[1]), sym_of(ins[2])]
            for aux in ins[3:5]:
                aux_names.add(aux)
                if aux not in tensors:
                    tensors[aux] = S.Variable(aux, __aux__=True)
                syms_bn.append(tensors[aux])
            out = S.BatchNorm(*syms_bn,
                              eps=float(a.get("epsilon", 1e-5)),
                              momentum=float(a.get("momentum", 0.9)),
                              fix_gamma=False, name=name)
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Softsign"):
            act = {v: k for k, v in _ACT_MAP.items()}[op]
            out = S.Activation(sym_of(ins[0]), act_type=act, name=name)
        elif op == "LeakyRelu":
            out = S.LeakyReLU(sym_of(ins[0]), act_type="leaky",
                              slope=float(a.get("alpha", 0.01)), name=name)
        elif op in ("MaxPool", "AveragePool", "GlobalMaxPool",
                    "GlobalAveragePool"):
            ptype = "max" if "Max" in op else "avg"
            if op.startswith("Global"):
                out = S.Pooling(sym_of(ins[0]), global_pool=True,
                                pool_type=ptype, kernel=(1, 1), name=name)
            else:
                k = two("kernel_shape", (1, 1))
                pads = a.get("pads", [0] * (2 * len(k)))
                if list(pads[:len(k)]) != list(pads[len(k):]):
                    raise ValueError(
                        f"onnx2mx: asymmetric pooling pads {pads} are not "
                        "supported (mx Pooling pads symmetrically)")
                out = S.Pooling(sym_of(ins[0]), kernel=k,
                                stride=two("strides", (1,) * len(k)),
                                pad=tuple(int(x) for x in pads[:len(k)]),
                                pool_type=ptype,
                                count_include_pad=bool(
                                    a.get("count_include_pad", 0)),
                                name=name)
        elif op in ("Softmax", "LogSoftmax"):
            # opset >= 13: single-axis semantics, default axis -1 (exact
            # match to mx softmax). opset < 13: coerce-to-2D semantics —
            # exactly equivalent to single-axis only when the axis is the
            # last dim; axis=1 (the old default) coincides for 2D inputs,
            # which is all our own exporter emits it for. Anything else
            # cannot be imported faithfully — fail loudly.
            ax = int(a.get("axis", -1 if opset >= 13 else 1))
            if opset < 13 and ax not in (-1, 1):
                raise ValueError(
                    f"onnx2mx: opset-{opset} {op} with axis={ax} uses "
                    "coerce-to-2D semantics that single-axis softmax "
                    "cannot reproduce")
            fn = S.softmax if op == "Softmax" else S.log_softmax
            out = fn(sym_of(ins[0]), axis=ax, name=name)
        elif op in ("Add", "Sub", "Mul", "Div"):
            fn = {"Add": S.broadcast_add, "Sub": S.broadcast_sub,
                  "Mul": S.broadcast_mul, "Div": S.broadcast_div}[op]
            out = fn(sym_of(ins[0]), sym_of(ins[1]), name=name)
        elif op == "Concat":
            out = S.Concat(*[sym_of(x) for x in ins],
                           dim=int(a.get("axis", 1)), name=name)
        elif op == "Dropout":
            out = S.Dropout(sym_of(ins[0]), p=float(a.get("ratio", 0.5)),
                            name=name)
        elif op == "Reshape":
            shape = tuple(int(x) for x in inits.pop(ins[1]))
            out = S.reshape(sym_of(ins[0]), shape=shape, name=name)
        elif op == "Transpose":
            perm = a.get("perm")
            out = S.transpose(sym_of(ins[0]),
                              axes=tuple(perm) if perm else None, name=name)
        elif op == "Sum":
            out = sym_of(ins[0])
            for extra in ins[1:]:
                out = S.broadcast_add(out, sym_of(extra))
        elif op == "Clip":
            # opset <= 6 passes bounds as attributes; opset >= 11 as
            # optional inputs 1-2 (must be initializers here — a dynamic
            # bound has no mx.clip counterpart, so fail loudly).
            lo, hi = a.get("min"), a.get("max")
            if len(ins) > 1:
                def _bound(nm_):
                    if not nm_:
                        return None
                    if nm_ in inits:
                        bound_uses[nm_] = bound_uses.get(nm_, 0) + 1
                        return float(np.asarray(inits[nm_]).reshape(()))
                    raise ValueError(
                        "onnx2mx: Clip min/max passed as non-initializer "
                        "inputs (dynamic bounds) — unsupported")
                lo = _bound(ins[1])
                hi = _bound(ins[2]) if len(ins) > 2 else None
            out = S.clip(sym_of(ins[0]),
                         a_min=float(lo) if lo is not None else -3e38,
                         a_max=float(hi) if hi is not None else 3e38,
                         name=name)
        elif op == "Identity":
            out = sym_of(ins[0])
        elif op == "Cast":
            to = int(a.get("to", 1))
            dt = {1: "float32", 6: "int32", 7: "int64", 10: "float16",
                  11: "float64", 16: "bfloat16"}.get(to)
            if dt is None:
                raise ValueError(f"onnx2mx: Cast to dtype enum {to} "
                                 "unsupported")
            out = S.Cast(sym_of(ins[0]), dtype=dt, name=name)
        elif op == "Gather":
            ax = int(a.get("axis", 0))
            out = S.take(sym_of(ins[0]), sym_of(ins[1]), axis=ax, name=name)
        elif op == "MatMul":
            # rank is unknown at import: a 2D initializer operand means the
            # projection form (dot); otherwise assume batched 3D matmul
            if ins[1] in inits and inits[ins[1]].ndim == 2:
                out = S.dot(sym_of(ins[0]), sym_of(ins[1]), name=name)
            elif ins[0] in inits and inits[ins[0]].ndim == 2:
                out = S.dot(sym_of(ins[0]), sym_of(ins[1]), name=name)
            else:
                out = S.batch_dot(sym_of(ins[0]), sym_of(ins[1]), name=name)
        elif op in ("LSTM", "GRU", "RNN"):
            out = _import_onnx_rnn(op, ins, outs, a, name, inits, sym_of, S)
            tensors[outs[0]] = out
            continue
        elif op == "Squeeze":
            axes = a.get("axes")
            out = S.squeeze(sym_of(ins[0]),
                            axis=(tuple(int(x) for x in axes)
                                  if axes is not None else None), name=name)
        elif op == "Unsqueeze":
            axes = tuple(int(x) for x in a.get("axes", (0,)))
            out = sym_of(ins[0])
            for ax in sorted(axes):
                out = S.expand_dims(out, axis=ax)
        elif op == "Slice":
            axes = [int(x) for x in a.get("axes", ())]
            starts = [int(x) for x in a.get("starts", ())]
            ends = [int(x) for x in a.get("ends", ())]
            if len(ins) > 1:
                raise ValueError("onnx2mx: opset-10+ Slice with bound "
                                 "inputs is unsupported (attrs only)")
            out = sym_of(ins[0])
            for ax, b0, e0 in zip(axes or range(len(starts)), starts, ends):
                out = S.slice_axis(out, axis=ax, begin=b0,
                                   end=None if e0 >= 2 ** 31 - 1 else e0)
        elif op == "ReduceMean":
            axes = a.get("axes")
            kd = bool(int(a.get("keepdims", 1)))
            out = S.mean(sym_of(ins[0]),
                         axis=(tuple(int(x) for x in axes)
                               if axes is not None else None),
                         keepdims=kd, name=name)
        elif op in ("Sqrt", "Exp", "Log", "Erf"):
            fn = {"Sqrt": S.sqrt, "Exp": S.exp, "Log": S.log,
                  "Erf": S.erf}[op]
            out = fn(sym_of(ins[0]), name=name)
        elif op == "Pow":
            out = S.broadcast_power(sym_of(ins[0]), sym_of(ins[1]),
                                    name=name)
        elif op == "Tile":
            if ins[1] not in inits:
                raise ValueError("onnx2mx: Tile repeats must be an "
                                 "initializer (dynamic repeats unsupported)")
            # read WITHOUT popping — exporters dedupe constants, one reps
            # tensor may feed several Tiles (same rule as Clip bounds)
            bound_uses[ins[1]] = bound_uses.get(ins[1], 0) + 1
            reps = tuple(int(x) for x in inits[ins[1]])
            out = S.tile(sym_of(ins[0]), reps=reps, name=name)
        else:
            raise ValueError(f"onnx2mx: unsupported ONNX op {op!r}")
        tensors[outs[0]] = out

    for nm_b, n_bound in bound_uses.items():  # bounds-only tensors: not params
        if use_count.get(nm_b, 0) <= n_bound:
            inits.pop(nm_b, None)

    # Fail loudly on dangling references: a node consuming a tensor that is
    # neither a graph input, an initializer, nor another node's output
    # (e.g. an unsupported multi-output leg like LSTM Y_h) would otherwise
    # silently import as a free Variable
    graph_input_names = {P.string_of(P.parse_message(r)[1][0])
                         for r in graph.get(11, [])}
    dangling = auto_vars - set(inits) - graph_input_names - aux_names
    if dangling:
        raise ValueError(
            f"onnx2mx: graph references undeclared tensors {sorted(dangling)}"
            " — likely an unsupported multi-output leg of an imported node")

    final_out = P.string_of(P.parse_message(graph[12][0])[1][0])
    sym = tensors[final_out]
    arg_params = {k: array(v) for k, v in inits.items()
                  if k not in aux_names}
    aux_params = {k: array(v) for k, v in inits.items() if k in aux_names}
    return sym, arg_params, aux_params
