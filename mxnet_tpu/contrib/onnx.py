"""ONNX interop: mx2onnx export + onnx2mx import, no ``onnx`` package.

Reference counterpart: ``python/mxnet/contrib/onnx/`` (mx2onnx/onnx2mx —
TBV, mount empty). The reference builds protobuf messages through the onnx
package's generated classes; this image cannot install it, so the wire
format is emitted/parsed directly by ``_onnx_proto`` (the format is three
primitives; the schema field numbers are public). Covered surface: the
CNN/MLP op families the model zoo uses — Conv, Gemm(+Flatten),
BatchNormalization, activations, pooling (incl. global), Softmax/
LogSoftmax, elementwise/broadcast arithmetic, Concat, Dropout, Reshape,
Transpose, Sum, Clip, LeakyRelu, Identity. Opset 9, fp32 tensors.

``export_model`` and ``import_model`` round-trip through real ONNX bytes:
tests/test_onnx.py re-imports an exported ResNet-style graph and checks
executor outputs match to 1e-5.
"""
from __future__ import annotations

import ast
from typing import Dict, List

import numpy as np

from . import _onnx_proto as P

__all__ = ["export_model", "import_model"]

# AttributeProto.type enum
_AT_FLOAT, _AT_INT, _AT_STRING, _AT_TENSOR = 1, 2, 3, 4
_AT_FLOATS, _AT_INTS, _AT_STRINGS = 6, 7, 8
_DT_FLOAT, _DT_INT64 = 1, 7


def _tuple(v, n=2):
    if isinstance(v, str):
        v = ast.literal_eval(v)
    if isinstance(v, (int, float)):
        v = (int(v),) * n
    return tuple(int(x) for x in v)


def _flag(v):
    return v in (True, 1, "1", "true", "True")


# --------------------------------------------------------------------------
# Attribute / tensor / node emitters
# --------------------------------------------------------------------------

def _attr_int(name, v):
    return P.field_message(5, P.field_string(1, name) + P.field_varint(3, v)
                           + P.field_varint(20, _AT_INT))


def _attr_float(name, v):
    return P.field_message(5, P.field_string(1, name)
                           + P.field_float(2, v) + P.field_varint(20, _AT_FLOAT))


def _attr_ints(name, vals):
    body = P.field_string(1, name)
    for v in vals:
        body += P.field_varint(8, v)
    return P.field_message(5, body + P.field_varint(20, _AT_INTS))


def _attr_str(name, s):
    return P.field_message(5, P.field_string(1, name) + P.field_string(4, s)
                           + P.field_varint(20, _AT_STRING))


def _tensor(name, arr):
    arr = np.ascontiguousarray(arr)
    if arr.dtype == np.int64:
        dt = _DT_INT64
    else:
        arr = arr.astype(np.float32)
        dt = _DT_FLOAT
    body = b""
    for d in arr.shape:
        body += P.field_varint(1, d)
    body += P.field_varint(2, dt)
    body += P.field_string(8, name)
    body += P.field_bytes(9, arr.tobytes())  # raw_data, little-endian
    return body


def _node(op_type, inputs, outputs, name, attrs=b""):
    body = b""
    for i in inputs:
        body += P.field_string(1, i)
    for o in outputs:
        body += P.field_string(2, o)
    body += P.field_string(3, name) + P.field_string(4, op_type) + attrs
    return P.field_message(1, body)  # GraphProto.node


def _value_info(name, shape, elem_type=_DT_FLOAT):
    dims = b""
    for d in shape:
        dims += P.field_message(1, P.field_varint(1, int(d)))
    ttype = P.field_varint(1, elem_type) + P.field_message(2, dims)
    return P.field_string(1, name) + P.field_message(2, P.field_message(1, ttype))


# --------------------------------------------------------------------------
# Export: mx Symbol graph -> ONNX GraphProto nodes
# --------------------------------------------------------------------------

_ACT_MAP = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "softrelu": "Softplus", "softsign": "Softsign"}
_ELEM_MAP = {"elemwise_add": "Add", "_plus": "Add", "broadcast_add": "Add",
             "elemwise_sub": "Sub", "_minus": "Sub", "broadcast_sub": "Sub",
             "elemwise_mul": "Mul", "_mul": "Mul", "broadcast_mul": "Mul",
             "elemwise_div": "Div", "_div": "Div", "broadcast_div": "Div"}


def _conv_attrs(a):
    kernel = _tuple(a.get("kernel", (1, 1)))
    stride = _tuple(a.get("stride", (1,) * len(kernel)), len(kernel))
    pad = _tuple(a.get("pad", (0,) * len(kernel)), len(kernel))
    dilate = _tuple(a.get("dilate", (1,) * len(kernel)), len(kernel))
    out = _attr_ints("kernel_shape", kernel) + _attr_ints("strides", stride)
    out += _attr_ints("pads", pad + pad) + _attr_ints("dilations", dilate)
    out += _attr_int("group", int(a.get("num_group", 1)))
    return out


def _export_node(node, in_names, out_name, params, extra_inits, in_rank=None):
    """Returns (onnx node bytes, handled: bool).

    in_rank: rank of the node's first input when shape inference succeeded,
    else None — used to guard opset-9 coerce-to-2D Softmax semantics.
    """
    op = node._op
    a = node._attrs
    nm = node._name
    if op == "Convolution":
        return _node("Conv", in_names, [out_name], nm, _conv_attrs(a)), True
    if op == "FullyConnected":
        flat_out = nm + "_flat"
        nodes = b""
        data_in = in_names[0]
        if _flag(a.get("flatten", True)):
            nodes += _node("Flatten", [in_names[0]], [flat_out], nm + "_flatten",
                           _attr_int("axis", 1))
            data_in = flat_out
        ins = [data_in] + in_names[1:]
        if len(ins) == 2:  # no_bias: opset-9 Gemm requires C — zeros
            zname = nm + "_zero_bias"
            num_hidden = int(a.get("num_hidden"))
            extra_inits.append((zname, np.zeros(num_hidden, np.float32)))
            ins.append(zname)
        nodes += _node("Gemm", ins, [out_name], nm, _attr_int("transB", 1))
        return nodes, True
    if op == "BatchNorm":
        # mxnet BatchNorm default eps is 1e-3 (ops/nn.py), not ONNX's 1e-5
        attrs = _attr_float("epsilon", float(a.get("eps", 1e-3)))
        attrs += _attr_float("momentum", float(a.get("momentum", 0.9)))
        return _node("BatchNormalization", in_names, [out_name], nm, attrs), True
    if op == "Activation":
        act = a.get("act_type", "relu")
        if act in _ACT_MAP:
            return _node(_ACT_MAP[act], in_names, [out_name], nm), True
        return b"", False
    if op in ("relu", "sigmoid", "tanh"):
        return _node(_ACT_MAP[op], in_names, [out_name], nm), True
    if op == "LeakyReLU":
        if a.get("act_type", "leaky") != "leaky":
            return b"", False
        return _node("LeakyRelu", in_names, [out_name], nm,
                     _attr_float("alpha", float(a.get("slope", 0.25)))), True
    if op == "Pooling":
        ptype = a.get("pool_type", "max")
        if _flag(a.get("global_pool", False)):
            op_t = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
            return _node(op_t, in_names, [out_name], nm), True
        kernel = _tuple(a.get("kernel", (1, 1)))
        # framework Pooling default stride is 1 (ops/nn.py), NOT kernel
        stride = _tuple(a.get("stride", (1,) * len(kernel)), len(kernel))
        pad = _tuple(a.get("pad", (0,) * len(kernel)), len(kernel))
        attrs = (_attr_ints("kernel_shape", kernel)
                 + _attr_ints("strides", stride)
                 + _attr_ints("pads", pad + pad))
        op_t = "MaxPool" if ptype == "max" else "AveragePool"
        if ptype == "avg":
            attrs += _attr_int(
                "count_include_pad",
                1 if _flag(a.get("count_include_pad", True)) else 0)
        return _node(op_t, in_names, [out_name], nm, attrs), True
    if op in ("softmax", "SoftmaxOutput", "SoftmaxActivation"):
        ins = in_names[:1]
        ax = int(a.get("axis", -1 if op == "softmax" else 1))
        # opset-9 Softmax coerces to 2D at `ax`: softmax over ALL trailing
        # dims, which equals mx single-axis softmax only when ax is the last
        # dim. Mirror the importer's guard — exporting anything else would
        # silently diverge on conformant runtimes (e.g. axis=1 on NCHW maps).
        last_ok = ax == -1 or (in_rank is not None and ax == in_rank - 1)
        if not last_ok:
            raise ValueError(
                f"mx2onnx: opset-9 Softmax with axis={ax} on a rank-"
                f"{in_rank if in_rank is not None else '?'} input uses "
                "coerce-to-2D semantics that diverge from single-axis "
                "softmax; only last-dim softmax exports faithfully")
        return _node("Softmax", ins, [out_name], nm, _attr_int("axis", ax)), True
    if op == "log_softmax":
        return _node("LogSoftmax", in_names, [out_name], nm,
                     _attr_int("axis", int(a.get("axis", -1)))), True
    if op in _ELEM_MAP:
        return _node(_ELEM_MAP[op], in_names, [out_name], nm), True
    if op == "Concat":
        ax = int(a.get("dim", a.get("axis", 1)))
        return _node("Concat", in_names, [out_name], nm,
                     _attr_int("axis", ax)), True
    if op == "Flatten":
        return _node("Flatten", in_names, [out_name], nm,
                     _attr_int("axis", 1)), True
    if op == "Dropout":
        return _node("Dropout", in_names[:1], [out_name], nm,
                     _attr_float("ratio", float(a.get("p", 0.5)))), True
    if op in ("Reshape", "reshape"):
        shape = _tuple(a.get("shape"), 1)
        sname = nm + "_shape"
        extra_inits.append((sname, np.asarray(shape, np.int64)))
        return _node("Reshape", [in_names[0], sname], [out_name], nm), True
    if op == "transpose":
        axes = a.get("axes", ())
        return _node("Transpose", in_names, [out_name], nm,
                     _attr_ints("perm", _tuple(axes, 1)) if axes else b""), True
    if op in ("add_n", "ElementWiseSum"):
        return _node("Sum", in_names, [out_name], nm), True
    if op == "mean" and not node._attrs.get("axis"):
        return b"", False
    if op == "clip":
        return _node("Clip", in_names, [out_name], nm,
                     _attr_float("min", float(a.get("a_min")))
                     + _attr_float("max", float(a.get("a_max")))), True
    if op == "identity":
        return _node("Identity", in_names, [out_name], nm), True
    return b"", False


def export_model(sym, params, input_shape, input_type=None,
                 onnx_file_path="model.onnx", verbose=False, **kwargs):
    """Export a symbol + params to an ONNX file (reference mx2onnx API).

    input_shape: one shape tuple or a list of them (one per graph input).
    Returns onnx_file_path.
    """
    from ..ndarray import NDArray

    np_params = {}
    for k, v in dict(params or {}).items():
        k = k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k
        np_params[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)

    base = sym._base() if hasattr(sym, "_base") else sym
    topo = base._topo()
    # fix_gamma BatchNorms ignore their stored gamma (it is forced to 1):
    # override BEFORE initializers serialize, or the stale values ship
    for node in topo:
        if node._op == "BatchNorm" and _flag(node._attrs.get("fix_gamma",
                                                             True)):
            gname = node._inputs[1]._base()._name
            if gname in np_params:
                np_params[gname] = np.ones_like(np_params[gname])
    shapes = ([tuple(input_shape)] if isinstance(input_shape[0], int)
              else [tuple(s) for s in input_shape])

    # Per-node shape inference (for rank-dependent export guards). Build the
    # known-shape map the same way the export loop assigns graph inputs:
    # params from np_params, data inputs from `shapes` in topo order.
    known = {}
    si = 0
    for node in topo:
        if node._op is not None:
            continue
        if node._name in np_params:
            known[node._name] = np_params[node._name].shape
        else:
            known[node._name] = shapes[min(si, len(shapes) - 1)]
            si += 1
    try:
        from ..symbol.symbol import infer_node_shapes
        node_shapes = infer_node_shapes(base, known)
    except Exception:
        node_shapes = {}

    out_of: Dict[int, str] = {}
    nodes = b""
    graph_inputs: List[bytes] = []
    inits = b""
    extra_inits: List = []
    shape_i = 0
    for node in topo:
        if node._op is None:
            out_of[id(node)] = node._name
            if node._name in np_params:
                inits += P.field_message(5, _tensor(node._name,
                                                    np_params[node._name]))
            else:
                shp = shapes[min(shape_i, len(shapes) - 1)]
                shape_i += 1
                graph_inputs.append(P.field_message(
                    11, _value_info(node._name, shp)))
            continue
        for i in node._inputs:
            if i._index:
                raise ValueError(
                    f"mx2onnx: {node._op!r} consumes output {i._index} of a "
                    "multi-output node — not supported")
        in_names = [out_of[id(i._base())] for i in node._inputs]
        out_name = node._name + "_out"
        in_rank = None
        if node._inputs:
            s = node_shapes.get(id(node._inputs[0]._base()))
            if isinstance(s, tuple):
                in_rank = len(s)
        nb, ok = _export_node(node, in_names, out_name, np_params,
                              extra_inits, in_rank=in_rank)
        if not ok:
            raise ValueError(f"mx2onnx: op {node._op!r} has no ONNX mapping; "
                             "supported set is the model-zoo CNN/MLP family")
        nodes += nb
        out_of[id(node)] = out_name
    for name, arr in extra_inits:
        inits += P.field_message(5, _tensor(name, arr))

    final = out_of[id(topo[-1])]
    graph = (nodes + P.field_string(2, "mxnet_tpu_export") + inits
             + b"".join(graph_inputs)
             + P.field_message(12, _value_info(final, ())))
    model = (P.field_varint(1, 7)                       # ir_version 7
             + P.field_string(2, "mxnet_tpu")
             + P.field_message(7, graph)
             + P.field_message(8, P.field_varint(2, 9)))  # opset 9
    with open(onnx_file_path, "wb") as f:
        f.write(model)
    if verbose:
        print(f"exported {len(topo)} nodes -> {onnx_file_path}")
    return onnx_file_path


# --------------------------------------------------------------------------
# Import: ONNX bytes -> mx Symbol + params
# --------------------------------------------------------------------------

def _parse_tensor(raw):
    f = P.parse_message(raw)
    dims = P.ints_of(f.get(1, []))
    dtype = f.get(2, [1])[0]
    name = P.string_of(f[8][0])
    if 9 in f:
        buf = f[9][0]
        arr = np.frombuffer(buf, np.float32 if dtype == _DT_FLOAT
                            else np.int64).reshape(dims)
    elif dtype == _DT_FLOAT and 4 in f:
        arr = np.array([P.float_of(x) for x in f[4]],
                       np.float32).reshape(dims)
    elif dtype == _DT_INT64 and 7 in f:
        arr = np.array(P.ints_of(f[7]), np.int64).reshape(dims)
    else:
        raise ValueError(f"unsupported TensorProto encoding for {name}")
    return name, arr


def _parse_attrs(node_fields):
    attrs = {}
    for raw in node_fields.get(5, []):
        f = P.parse_message(raw)
        name = P.string_of(f[1][0])
        if 3 in f:
            attrs[name] = P.ints_of(f[3])[0]
        elif 2 in f:
            attrs[name] = P.float_of(f[2][0])
        elif 8 in f:
            attrs[name] = P.ints_of(f[8])
        elif 4 in f:
            attrs[name] = P.string_of(f[4][0])
        elif 5 in f:
            attrs[name] = _parse_tensor(f[5][0])[1]
    return attrs


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params) (reference onnx2mx API)."""
    from .. import symbol as sym_mod
    from ..ndarray import array

    with open(model_file, "rb") as f:
        model = P.parse_message(f.read())
    graph = P.parse_message(model[7][0])
    opset = 9
    for raw in model.get(8, []):  # opset_import (default domain)
        f8 = P.parse_message(raw)
        if 1 not in f8 or P.string_of(f8[1][0]) in ("", "ai.onnx"):
            opset = P.ints_of(f8.get(2, [9]))[0]

    inits = {}
    for raw in graph.get(5, []):
        name, arr = _parse_tensor(raw)
        inits[name] = arr

    tensors: Dict[str, object] = {}
    aux_names = set()
    for raw in graph.get(11, []):  # graph inputs
        name = P.string_of(P.parse_message(raw)[1][0])
        if name not in inits:
            tensors[name] = sym_mod.Variable(name)

    def sym_of(name):
        if name not in tensors:
            tensors[name] = sym_mod.Variable(name)
        return tensors[name]

    # Initializers consumed as Clip bounds: read WITHOUT popping (exporters
    # dedupe constants — one min/max tensor may feed many Clip nodes, e.g.
    # every ReLU6 in a MobileNet). Count total input-uses per name so bound
    # tensors are stripped from params only when nothing else consumes them.
    use_count: Dict[str, int] = {}
    for raw in graph.get(1, []):
        for x in P.parse_message(raw).get(1, []):
            nm_u = P.string_of(x)
            use_count[nm_u] = use_count.get(nm_u, 0) + 1
    bound_uses: Dict[str, int] = {}

    pending_flatten: Dict[str, str] = {}  # flatten_out -> flatten_in
    for raw in graph.get(1, []):
        f = P.parse_message(raw)
        ins = [P.string_of(x) for x in f.get(1, [])]
        outs = [P.string_of(x) for x in f.get(2, [])]
        name = P.string_of(f[3][0]) if 3 in f else outs[0]
        op = P.string_of(f[4][0])
        a = _parse_attrs(f)
        S = sym_mod

        def two(key, default):
            v = a.get(key, default)
            return tuple(int(x) for x in v)

        if op == "Conv":
            k = two("kernel_shape", (1, 1))
            pads = a.get("pads", [0] * (2 * len(k)))
            if list(pads[:len(k)]) != list(pads[len(k):]):
                raise ValueError(
                    f"onnx2mx: asymmetric Conv pads {pads} are not "
                    "supported (mx Convolution pads symmetrically)")
            no_bias = len(ins) == 2
            args = dict(kernel=k, stride=two("strides", (1,) * len(k)),
                        pad=tuple(int(x) for x in pads[:len(k)]),
                        dilate=two("dilations", (1,) * len(k)),
                        num_group=int(a.get("group", 1)),
                        num_filter=int(inits[ins[1]].shape[0]),
                        no_bias=no_bias, name=name)
            syms = [sym_of(x) for x in ins]
            out = S.Convolution(*syms, **args)
        elif op == "Gemm":
            if (int(a.get("transB", 0)) != 1
                    or float(a.get("alpha", 1.0)) != 1.0
                    or float(a.get("beta", 1.0)) != 1.0):
                raise ValueError(
                    "onnx2mx: only Gemm(transB=1, alpha=1, beta=1) — the "
                    "FullyConnected layout — is supported")
            data_name = ins[0]
            flatten = data_name in pending_flatten
            if flatten:
                data_name = pending_flatten[ins[0]]
            w = inits[ins[1]]
            # only OUR exporter's synthetic placeholder marks no_bias — a
            # genuinely all-zero bias in a third-party model must survive
            zero_bias = (len(ins) > 2 and ins[2] in inits
                         and ins[2].endswith("_zero_bias")
                         and not inits[ins[2]].any())
            syms = [sym_of(data_name), sym_of(ins[1])]
            no_bias = zero_bias or len(ins) <= 2
            if not no_bias:
                syms.append(sym_of(ins[2]))
            elif len(ins) > 2:
                inits.pop(ins[2], None)
            out = S.FullyConnected(*syms, num_hidden=int(w.shape[0]),
                                   flatten=flatten, no_bias=no_bias,
                                   name=name)
        elif op == "Flatten":
            # fold Flatten+Gemm back into FC(flatten=True); standalone
            # Flatten emitted for any other consumer below
            pending_flatten[outs[0]] = ins[0]
            tensors[outs[0]] = S.Flatten(sym_of(ins[0]), name=name)
            continue
        elif op == "BatchNormalization":
            syms_bn = [sym_of(ins[0]), sym_of(ins[1]), sym_of(ins[2])]
            for aux in ins[3:5]:
                aux_names.add(aux)
                if aux not in tensors:
                    tensors[aux] = S.Variable(aux, __aux__=True)
                syms_bn.append(tensors[aux])
            out = S.BatchNorm(*syms_bn,
                              eps=float(a.get("epsilon", 1e-5)),
                              momentum=float(a.get("momentum", 0.9)),
                              fix_gamma=False, name=name)
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Softsign"):
            act = {v: k for k, v in _ACT_MAP.items()}[op]
            out = S.Activation(sym_of(ins[0]), act_type=act, name=name)
        elif op == "LeakyRelu":
            out = S.LeakyReLU(sym_of(ins[0]), act_type="leaky",
                              slope=float(a.get("alpha", 0.01)), name=name)
        elif op in ("MaxPool", "AveragePool", "GlobalMaxPool",
                    "GlobalAveragePool"):
            ptype = "max" if "Max" in op else "avg"
            if op.startswith("Global"):
                out = S.Pooling(sym_of(ins[0]), global_pool=True,
                                pool_type=ptype, kernel=(1, 1), name=name)
            else:
                k = two("kernel_shape", (1, 1))
                pads = a.get("pads", [0] * (2 * len(k)))
                if list(pads[:len(k)]) != list(pads[len(k):]):
                    raise ValueError(
                        f"onnx2mx: asymmetric pooling pads {pads} are not "
                        "supported (mx Pooling pads symmetrically)")
                out = S.Pooling(sym_of(ins[0]), kernel=k,
                                stride=two("strides", (1,) * len(k)),
                                pad=tuple(int(x) for x in pads[:len(k)]),
                                pool_type=ptype,
                                count_include_pad=bool(
                                    a.get("count_include_pad", 0)),
                                name=name)
        elif op in ("Softmax", "LogSoftmax"):
            # opset >= 13: single-axis semantics, default axis -1 (exact
            # match to mx softmax). opset < 13: coerce-to-2D semantics —
            # exactly equivalent to single-axis only when the axis is the
            # last dim; axis=1 (the old default) coincides for 2D inputs,
            # which is all our own exporter emits it for. Anything else
            # cannot be imported faithfully — fail loudly.
            ax = int(a.get("axis", -1 if opset >= 13 else 1))
            if opset < 13 and ax not in (-1, 1):
                raise ValueError(
                    f"onnx2mx: opset-{opset} {op} with axis={ax} uses "
                    "coerce-to-2D semantics that single-axis softmax "
                    "cannot reproduce")
            fn = S.softmax if op == "Softmax" else S.log_softmax
            out = fn(sym_of(ins[0]), axis=ax, name=name)
        elif op in ("Add", "Sub", "Mul", "Div"):
            fn = {"Add": S.broadcast_add, "Sub": S.broadcast_sub,
                  "Mul": S.broadcast_mul, "Div": S.broadcast_div}[op]
            out = fn(sym_of(ins[0]), sym_of(ins[1]), name=name)
        elif op == "Concat":
            out = S.Concat(*[sym_of(x) for x in ins],
                           dim=int(a.get("axis", 1)), name=name)
        elif op == "Dropout":
            out = S.Dropout(sym_of(ins[0]), p=float(a.get("ratio", 0.5)),
                            name=name)
        elif op == "Reshape":
            shape = tuple(int(x) for x in inits.pop(ins[1]))
            out = S.reshape(sym_of(ins[0]), shape=shape, name=name)
        elif op == "Transpose":
            perm = a.get("perm")
            out = S.transpose(sym_of(ins[0]),
                              axes=tuple(perm) if perm else None, name=name)
        elif op == "Sum":
            out = sym_of(ins[0])
            for extra in ins[1:]:
                out = S.broadcast_add(out, sym_of(extra))
        elif op == "Clip":
            # opset <= 6 passes bounds as attributes; opset >= 11 as
            # optional inputs 1-2 (must be initializers here — a dynamic
            # bound has no mx.clip counterpart, so fail loudly).
            lo, hi = a.get("min"), a.get("max")
            if len(ins) > 1:
                def _bound(nm_):
                    if not nm_:
                        return None
                    if nm_ in inits:
                        bound_uses[nm_] = bound_uses.get(nm_, 0) + 1
                        return float(np.asarray(inits[nm_]).reshape(()))
                    raise ValueError(
                        "onnx2mx: Clip min/max passed as non-initializer "
                        "inputs (dynamic bounds) — unsupported")
                lo = _bound(ins[1])
                hi = _bound(ins[2]) if len(ins) > 2 else None
            out = S.clip(sym_of(ins[0]),
                         a_min=float(lo) if lo is not None else -3e38,
                         a_max=float(hi) if hi is not None else 3e38,
                         name=name)
        elif op == "Identity":
            out = sym_of(ins[0])
        else:
            raise ValueError(f"onnx2mx: unsupported ONNX op {op!r}")
        tensors[outs[0]] = out

    for nm_b, n_bound in bound_uses.items():  # bounds-only tensors: not params
        if use_count.get(nm_b, 0) <= n_bound:
            inits.pop(nm_b, None)

    final_out = P.string_of(P.parse_message(graph[12][0])[1][0])
    sym = tensors[final_out]
    arg_params = {k: array(v) for k, v in inits.items()
                  if k not in aux_names}
    aux_params = {k: array(v) for k, v in inits.items() if k in aux_names}
    return sym, arg_params, aux_params
