"""INT8 quantization shim (reference contrib/quantization.py — TBV).

The reference's INT8 path targets MKLDNN/TensorRT; TPU v5 has no INT8
inference path exposed through XLA, so calibration/quantization raise with
guidance (bf16 via mx.amp is the TPU reduced-precision path). API surface
kept for import parity.
"""
from __future__ import annotations

__all__ = ["quantize_model", "quantize_net", "quantize_graph"]

_MSG = ("INT8 quantization is not available in the TPU build; use "
        "mx.amp (bfloat16) for reduced-precision inference/training")


def quantize_model(*a, **kw):
    raise NotImplementedError(_MSG)


def quantize_net(*a, **kw):
    raise NotImplementedError(_MSG)


def quantize_graph(*a, **kw):
    raise NotImplementedError(_MSG)
