"""INT8 quantization (reference ``python/mxnet/contrib/quantization.py`` —
TBV).

``quantize_net`` is the Gluon API (reference 1.6+): calibrate a trained
HybridBlock's activation ranges, then swap Dense children for int8 twins
that quantize the input, run the MXU int8 op (ops/quantization.py:
``quantized_fully_connected``, int32 accumulation), and dequantize the
result. Unmatched layers stay f32 — the reference likewise quantizes a
subset of ops and stitches (de)quantize nodes around them.

``quantize_model`` is the reference's symbolic entry point: a graph
rewrite that replaces each calibrated FullyConnected/Convolution with the
explicit quantize_v2 → int8 MXU op → dequantize node trio and int8 weight
params. ``quantize_graph`` is the same rewrite without a calibration
dataset.
"""
from __future__ import annotations

import numpy as np

__all__ = ["quantize_model", "quantize_net", "quantize_graph",
           "SUPPORTED_CALIB_MODES"]

# ONE source of truth for calibration modes across every entry point
# (quantize_net / quantize_model / quantize_graph). 'entropy' (KL threshold
# search — the reference's *recommended* calibration) is recognized but
# unimplemented: it raises NotImplementedError naming the gap instead of a
# generic ValueError, so callers can tell "you typo'd" from "not built yet"
# (the gap is tracked as ROADMAP item 5).
SUPPORTED_CALIB_MODES = ("none", "naive")


def _check_calib_mode(calib_mode):
    """Structured calib_mode validation shared by every quantization entry
    point — quantize_net and quantize_model used to disagree on what an
    unsupported mode raised and which modes they listed."""
    if calib_mode in SUPPORTED_CALIB_MODES:
        return
    if calib_mode == "entropy":
        raise NotImplementedError(
            "calib_mode='entropy' (KL threshold search, the reference's "
            "recommended calibration) is not implemented yet — tracked as "
            f"ROADMAP item 5. Supported modes: {SUPPORTED_CALIB_MODES}")
    raise ValueError(
        f"calib_mode {calib_mode!r} is not supported; choose one of "
        f"{SUPPORTED_CALIB_MODES} ('entropy' is recognized but "
        "unimplemented — ROADMAP item 5)")


def _collect_ranges(net, calib_data, num_calib_batches=None):
    """Naive calibration: run forwards, record per-block input min/max."""
    import jax

    from .. import autograd
    from ..gluon import nn
    from ..ndarray import NDArray

    ranges = {}
    installed = []  # (block, hook) pairs for removal

    def make_hook(blk):
        def pre_hook(b, inputs):
            x = inputs[0]
            if isinstance(x, NDArray) and not isinstance(x._data,
                                                         jax.core.Tracer):
                a = x.asnumpy()
                lo, hi = float(a.min()), float(a.max())
                old = ranges.get(b.name)
                if old is None:
                    ranges[b.name] = [lo, hi]
                else:
                    old[0] = min(old[0], lo)
                    old[1] = max(old[1], hi)
        return pre_hook

    def walk(b):
        if isinstance(b, nn.Dense):
            h = make_hook(b)
            b.register_forward_pre_hook(h)
            installed.append((b, h))
        for c in b._children.values():
            walk(c)

    walk(net)
    try:
        with autograd.pause():
            n = 0
            for batch in calib_data:
                xs = batch.data if hasattr(batch, "data") else [batch]
                net(*(xs if isinstance(xs, (list, tuple)) else [xs]))
                n += 1
                if num_calib_batches is not None and n >= num_calib_batches:
                    break
    finally:
        for b, h in installed:
            b._forward_pre_hooks.remove(h)
    return ranges


class _QuantizedDense:
    """Callable twin of a calibrated Dense: int8 in/weights, int32 accum.

    ``in_range=None`` (calib_mode='none') quantizes the input against its
    runtime min/max each call — the reference's online mode.
    """

    def __init__(self, dense, in_range):
        from ..ndarray import array

        w = dense.weight.data().asnumpy()
        self._w_max = float(np.abs(w).max()) or 1.0
        scale = 127.0 / self._w_max
        self._wq = array(np.clip(np.round(w * scale), -127, 127)
                         .astype(np.int8))
        self._bias = (dense.bias.data()
                      if getattr(dense, "bias", None) is not None
                      and dense.bias._data is not None else None)
        self._in_range = in_range
        self._flatten = getattr(dense, "_flatten", True)
        self._act = getattr(dense, "_act", None)
        self.name = dense.name

    def __call__(self, x):
        from ..ndarray.ndarray import invoke_fn
        from ..ops.registry import get_op

        rng = self._in_range

        def pure(xd, wq, *maybe_bias):
            import jax.numpy as jnp

            if rng is not None:
                qx, mn_d, mx_d = get_op("_contrib_quantize_v2").fn(
                    xd, min_calib_range=rng[0], max_calib_range=rng[1])
            else:  # online min/max
                qx, mn_d, mx_d = get_op("_contrib_quantize_v2").fn(xd)
            mn_w = jnp.float32(-self._w_max).reshape(1)
            mx_w = jnp.float32(self._w_max).reshape(1)
            acc, mn_o, mx_o = get_op("_contrib_quantized_fully_connected").fn(
                qx, wq, None, mn_d, mx_d, mn_w, mx_w, no_bias=True,
                flatten=self._flatten)
            out = get_op("_contrib_dequantize").fn(acc, mn_o, mx_o)
            if maybe_bias:
                out = out + maybe_bias[0]
            if self._act is not None:
                out = get_op("Activation").fn(out, act_type=self._act)
            return out

        ins = [x, self._wq] + ([self._bias] if self._bias is not None else [])
        return invoke_fn(pure, ins)


class _CallableBlockShim:
    """Block-like wrapper so a _QuantizedDense slots into child traversal.

    Keeps the ORIGINAL Dense for everything but forward: checkpoints still
    save/load the f32 weights (so a fresh unquantized net can load them),
    hooks install on the original, params walk through it.
    """

    def __init__(self, q, original):
        self._q = q
        self._orig = original
        self.name = q.name + "_int8"
        self._children = {}
        self._reg_params = original._reg_params
        self._forward_hooks = original._forward_hooks
        self._forward_pre_hooks = original._forward_pre_hooks

    def __call__(self, x):
        for h in self._forward_pre_hooks:
            h(self, (x,))
        out = self._q(x)
        for h in self._forward_hooks:
            h(self, (x,), out)
        return out

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def hybridize(self, *a, **kw):
        pass

    def _iter_params(self):
        return self._orig._iter_params()

    def _cast_hook(self, dtype):
        pass

    def _collect_params_with_prefix(self, prefix=""):
        return self._orig._collect_params_with_prefix(prefix)


def quantize_net(network, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", num_calib_batches=None,
                 exclude_layers=None, **kwargs):
    """Quantize ``network``'s calibrated Dense layers to int8 in place and
    return it. ``network._quantized_layers`` lists what was swapped."""
    if quantized_dtype not in ("int8", "auto"):
        raise ValueError(f"quantized_dtype {quantized_dtype!r} not supported")
    _check_calib_mode(calib_mode)
    if calib_mode == "naive":
        if calib_data is None:
            raise ValueError("calib_mode='naive' needs calib_data")
        ranges = _collect_ranges(network, calib_data, num_calib_batches)
    else:
        ranges = {}
    exclude = set(exclude_layers or ())

    from ..gluon import nn

    replaced = []

    online = calib_mode == "none"

    def quantizable(c):
        return (isinstance(c, nn.Dense) and c.name not in exclude
                and (online or c.name in ranges))

    def walk(b):
        for attr, c in list(b._children.items()):
            if quantizable(c):
                rng = None if online else tuple(ranges[c.name])
                shim = _CallableBlockShim(_QuantizedDense(c, rng), c)
                replaced.append(c.name)
                b._children[attr] = shim
            else:
                walk(c)

    if quantizable(network):  # the net IS a single Dense: return its shim
        rng = None if online else tuple(ranges[network.name])
        shim = _CallableBlockShim(_QuantizedDense(network, rng), network)
        shim._quantized_layers = [network.name]
        return shim
    walk(network)
    if not replaced:
        import warnings

        warnings.warn(
            "quantize_net: no Dense layer was quantized — with "
            "calib_mode='naive' this usually means calibration saw no "
            "eager forwards (a hybridized net replays its compiled trace; "
            "call quantize_net BEFORE hybridize, or use calib_mode='none')")
    network._quantized_layers = sorted(replaced)
    return network


def _quantize_param(arr, name, qparams):
    """f32 param -> int8 twin + min/max range params (symmetric grid).
    Returns the three new param names."""
    a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
    m = float(np.abs(a).max()) or 1.0
    from ..ndarray import array

    qparams[name + "_quantize"] = array(
        np.clip(np.round(a * (127.0 / m)), -127, 127).astype(np.int8))
    qparams[name + "_min"] = array(np.array([-m], np.float32))
    qparams[name + "_max"] = array(np.array([m], np.float32))
    return name + "_quantize", name + "_min", name + "_max"


def _rewrite_quantized(sym, arg_params, excluded, ranges, online):
    """Graph rewrite: each quantizable FC/Conv node becomes the reference's
    explicit quantize_v2 -> int8 op -> dequantize chain (reference
    quantize_graph pass inserts the same node trio — TBV). Returns
    (new_sym, qarg_params)."""
    from .. import symbol as S
    from ..symbol.symbol import Symbol

    qarg = dict(arg_params)
    base = sym._base() if sym._op != "_group" else sym
    topo = base._topo()
    memo = {}
    # tied weights: quantize once, reuse the int8 twin for every consumer;
    # the f32 original is dropped only if no un-quantized node still needs it
    qweight_cache = {}
    consumed = set()

    def remap(inp):
        b = inp._base()
        new_b = memo[id(b)]
        if inp._index is not None:
            return new_b[inp._index]
        return new_b

    def quantizable(node):
        if node._op not in ("FullyConnected", "Convolution"):
            return False
        if node._name in excluded:
            return False
        if not online and node._name not in ranges:
            return False
        wvar = node._inputs[1]._base()
        return wvar._op is None and wvar._name in arg_params

    for node in topo:
        if node._op is None:
            memo[id(node)] = node
            continue
        new_ins = [remap(i) for i in node._inputs]
        if quantizable(node):
            a = node._attrs
            nm = node._name
            no_bias = str(a.get("no_bias", False)).lower() in ("1", "true")
            wname = node._inputs[1]._base()._name
            if wname in qweight_cache:
                wq, wmin, wmax = qweight_cache[wname]
            else:
                wq, wmin, wmax = _quantize_param(arg_params[wname], wname,
                                                 qarg)
                qweight_cache[wname] = (wq, wmin, wmax)
            consumed.add(wname)
            if online:
                dq = S._contrib_quantize_v2(new_ins[0], name=nm + "_quantize")
            else:
                lo, hi = ranges[nm]
                dq = S._contrib_quantize_v2(
                    new_ins[0], min_calib_range=float(lo),
                    max_calib_range=float(hi), name=nm + "_quantize")
            # int8 op runs bias-free; the f32 bias (kept at full precision,
            # matching the int32-accumulator exactness better than an int8
            # bias grid) is added after dequantize
            q_ins = [dq[0], S.Variable(wq), S.zeros((1,)),
                     dq[1], dq[2], S.Variable(wmin), S.Variable(wmax)]
            q_kwargs = {"no_bias": True}
            if node._op == "FullyConnected":
                qop = S._contrib_quantized_fully_connected
                q_kwargs["num_hidden"] = int(a.get("num_hidden", 1))
                q_kwargs["flatten"] = str(a.get("flatten", True)).lower() \
                    not in ("0", "false")
            else:
                qop = S._contrib_quantized_conv
                for key in ("kernel", "stride", "pad", "dilate"):
                    if key in a:
                        q_kwargs[key] = a[key]
                q_kwargs["num_filter"] = int(a.get("num_filter", 1))
                q_kwargs["num_group"] = int(a.get("num_group", 1))
            qnode = qop(*q_ins, name=nm + "_int8", **q_kwargs)
            deq = S._contrib_dequantize(qnode[0], qnode[1], qnode[2],
                                        name=nm + "_dequantize")
            if not no_bias and len(node._inputs) > 2:
                bias_sym = new_ins[2]
                if node._op == "Convolution":
                    bias_sym = S.reshape(bias_sym, shape=(1, -1, 1, 1),
                                         name=nm + "_bias_r")
                deq = S.broadcast_add(deq, bias_sym, name=nm + "_addbias")
            memo[id(node)] = deq._base()
        else:
            memo[id(node)] = Symbol(node._op, node._name, new_ins,
                                    node._attrs)
    out = memo[id(base)]
    if sym._index is not None:
        out = out[sym._index]
    # drop quantized f32 originals unless an un-quantized node still
    # references them (tied weight feeding e.g. an excluded layer)
    still_needed = set(out.list_arguments())
    for wname in consumed:
        if wname not in still_needed:
            qarg.pop(wname, None)
    return out, qarg


def _calibrate_ranges(sym, arg_params, aux_params, calib_data, data_names,
                      label_names, num_calib_examples, excluded):
    """Naive calibration: min/max of every quantizable node's data input,
    collected by evaluating a Group of those inputs over calib_data."""
    from .. import symbol as S_mod
    from ..ndarray import NDArray

    base = sym._base() if sym._op != "_group" else sym
    nodes = [n for n in base._topo()
             if n._op in ("FullyConnected", "Convolution")
             and n._name not in excluded]
    if not nodes:
        return {}
    group = S_mod.Group([n._inputs[0] for n in nodes])
    ranges = {}
    seen = 0
    execs = {}  # data-shape signature -> bound executor
    for batch in calib_data:
        xs = batch.data if hasattr(batch, "data") else [batch]
        xs = xs if isinstance(xs, (list, tuple)) else [xs]
        feed = dict(zip(data_names, xs))
        feed.update(arg_params)
        feed.update(aux_params or {})
        # bind once PER DATA SHAPE: the steady-state batches share one
        # executor, and a ragged final batch (num_calib_examples not a
        # multiple of the batch size) gets its own bind instead of a
        # mid-calibration shape-mismatch crash
        sig = tuple(tuple(x.shape) for x in xs)
        exe = execs.get(sig)
        if exe is None:
            exe = execs[sig] = group.simple_bind(
                grad_req="null", **{k: v.shape for k, v in feed.items()})
        outs = exe.forward(is_train=False, **feed)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        for n, o in zip(nodes, outs):
            a = o.asnumpy()
            lo, hi = float(a.min()), float(a.max())
            old = ranges.get(n._name)
            if old is None:
                ranges[n._name] = [lo, hi]
            else:
                old[0] = min(old[0], lo)
                old[1] = max(old[1], hi)
        seen += int(xs[0].shape[0])
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    return ranges


def quantize_model(sym, arg_params=None, aux_params=None,
                   data_names=("data",), label_names=("softmax_label",),
                   ctx=None, excluded_sym_names=None, calib_mode="none",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", **kwargs):
    """Reference symbolic INT8 entry point: rewrite ``sym`` so every
    calibrated FullyConnected/Convolution runs as the explicit
    quantize_v2 → int8 MXU op → dequantize chain, with int8 weight/bias
    params. Returns (qsym, qarg_params, aux_params).

    calib_mode: 'none' (online min/max per batch) or 'naive' (min/max over
    ``calib_data``). 'entropy' (KL threshold search) is not implemented —
    raises rather than silently degrading.
    """
    if quantized_dtype not in ("int8", "auto"):
        raise ValueError(f"quantized_dtype {quantized_dtype!r} not supported")
    _check_calib_mode(calib_mode)
    arg_params = dict(arg_params or {})
    aux_params = dict(aux_params or {})
    excluded = set(excluded_sym_names or ())
    if isinstance(data_names, str):
        data_names = (data_names,)
    if calib_mode == "naive":
        if calib_data is None:
            raise ValueError("calib_mode='naive' needs calib_data")
        ranges = _calibrate_ranges(sym, arg_params, aux_params, calib_data,
                                   data_names, label_names,
                                   num_calib_examples, excluded)
    else:
        ranges = {}
    qsym, qarg = _rewrite_quantized(sym, arg_params, excluded, ranges,
                                    online=(calib_mode == "none"))
    return qsym, qarg, aux_params


def quantize_graph(sym, arg_params=None, aux_params=None, ctx=None,
                   excluded_sym_names=None, calib_mode="none",
                   quantized_dtype="int8", **kwargs):
    """Reference quantize_graph: the same rewrite as quantize_model.
    ``calib_mode`` is honored ('naive' needs calib_data in kwargs;
    'entropy' raises, as in quantize_model). Returns
    (qsym, qarg_params, aux_params, collector) — the collector slot is
    None: calibration here runs through quantize_model's calib_data path
    rather than a separate layer-output collector object."""
    qsym, qarg, aux = quantize_model(
        sym, arg_params, aux_params, ctx=ctx,
        excluded_sym_names=excluded_sym_names, calib_mode=calib_mode,
        quantized_dtype=quantized_dtype, **kwargs)
    return qsym, qarg, aux, None
