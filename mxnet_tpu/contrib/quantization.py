"""INT8 quantization (reference ``python/mxnet/contrib/quantization.py`` —
TBV).

``quantize_net`` is the Gluon API (reference 1.6+): calibrate a trained
HybridBlock's activation ranges, then swap Dense children for int8 twins
that quantize the input, run the MXU int8 op (ops/quantization.py:
``quantized_fully_connected``, int32 accumulation), and dequantize the
result. Unmatched layers stay f32 — the reference likewise quantizes a
subset of ops and stitches (de)quantize nodes around them.

``quantize_model`` (the raw-Symbol API) is intentionally routed to
quantize_net; ``quantize_graph`` remains unsupported (no partition IR).
"""
from __future__ import annotations

import numpy as np

__all__ = ["quantize_model", "quantize_net", "quantize_graph"]


def _collect_ranges(net, calib_data, num_calib_batches=None):
    """Naive calibration: run forwards, record per-block input min/max."""
    import jax

    from .. import autograd
    from ..gluon import nn
    from ..ndarray import NDArray

    ranges = {}
    installed = []  # (block, hook) pairs for removal

    def make_hook(blk):
        def pre_hook(b, inputs):
            x = inputs[0]
            if isinstance(x, NDArray) and not isinstance(x._data,
                                                         jax.core.Tracer):
                a = x.asnumpy()
                lo, hi = float(a.min()), float(a.max())
                old = ranges.get(b.name)
                if old is None:
                    ranges[b.name] = [lo, hi]
                else:
                    old[0] = min(old[0], lo)
                    old[1] = max(old[1], hi)
        return pre_hook

    def walk(b):
        if isinstance(b, nn.Dense):
            h = make_hook(b)
            b.register_forward_pre_hook(h)
            installed.append((b, h))
        for c in b._children.values():
            walk(c)

    walk(net)
    try:
        with autograd.pause():
            n = 0
            for batch in calib_data:
                xs = batch.data if hasattr(batch, "data") else [batch]
                net(*(xs if isinstance(xs, (list, tuple)) else [xs]))
                n += 1
                if num_calib_batches is not None and n >= num_calib_batches:
                    break
    finally:
        for b, h in installed:
            b._forward_pre_hooks.remove(h)
    return ranges


class _QuantizedDense:
    """Callable twin of a calibrated Dense: int8 in/weights, int32 accum.

    ``in_range=None`` (calib_mode='none') quantizes the input against its
    runtime min/max each call — the reference's online mode.
    """

    def __init__(self, dense, in_range):
        from ..ndarray import array

        w = dense.weight.data().asnumpy()
        self._w_max = float(np.abs(w).max()) or 1.0
        scale = 127.0 / self._w_max
        self._wq = array(np.clip(np.round(w * scale), -127, 127)
                         .astype(np.int8))
        self._bias = (dense.bias.data()
                      if getattr(dense, "bias", None) is not None
                      and dense.bias._data is not None else None)
        self._in_range = in_range
        self._flatten = getattr(dense, "_flatten", True)
        self._act = getattr(dense, "_act", None)
        self.name = dense.name

    def __call__(self, x):
        from ..ndarray.ndarray import invoke_fn
        from ..ops.registry import get_op

        rng = self._in_range

        def pure(xd, wq, *maybe_bias):
            import jax.numpy as jnp

            if rng is not None:
                qx, mn_d, mx_d = get_op("_contrib_quantize_v2").fn(
                    xd, min_calib_range=rng[0], max_calib_range=rng[1])
            else:  # online min/max
                qx, mn_d, mx_d = get_op("_contrib_quantize_v2").fn(xd)
            mn_w = jnp.float32(-self._w_max).reshape(1)
            mx_w = jnp.float32(self._w_max).reshape(1)
            acc, mn_o, mx_o = get_op("_contrib_quantized_fully_connected").fn(
                qx, wq, None, mn_d, mx_d, mn_w, mx_w, no_bias=True,
                flatten=self._flatten)
            out = get_op("_contrib_dequantize").fn(acc, mn_o, mx_o)
            if maybe_bias:
                out = out + maybe_bias[0]
            if self._act is not None:
                out = get_op("Activation").fn(out, act_type=self._act)
            return out

        ins = [x, self._wq] + ([self._bias] if self._bias is not None else [])
        return invoke_fn(pure, ins)


class _CallableBlockShim:
    """Block-like wrapper so a _QuantizedDense slots into child traversal.

    Keeps the ORIGINAL Dense for everything but forward: checkpoints still
    save/load the f32 weights (so a fresh unquantized net can load them),
    hooks install on the original, params walk through it.
    """

    def __init__(self, q, original):
        self._q = q
        self._orig = original
        self.name = q.name + "_int8"
        self._children = {}
        self._reg_params = original._reg_params
        self._forward_hooks = original._forward_hooks
        self._forward_pre_hooks = original._forward_pre_hooks

    def __call__(self, x):
        for h in self._forward_pre_hooks:
            h(self, (x,))
        out = self._q(x)
        for h in self._forward_hooks:
            h(self, (x,), out)
        return out

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def hybridize(self, *a, **kw):
        pass

    def _iter_params(self):
        return self._orig._iter_params()

    def _cast_hook(self, dtype):
        pass

    def _collect_params_with_prefix(self, prefix=""):
        return self._orig._collect_params_with_prefix(prefix)


def quantize_net(network, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", num_calib_batches=None,
                 exclude_layers=None, **kwargs):
    """Quantize ``network``'s calibrated Dense layers to int8 in place and
    return it. ``network._quantized_layers`` lists what was swapped."""
    if quantized_dtype not in ("int8", "auto"):
        raise ValueError(f"quantized_dtype {quantized_dtype!r} not supported")
    if calib_mode not in ("naive", "none"):
        raise ValueError(f"calib_mode {calib_mode!r} not supported "
                         "(naive|none)")
    if calib_mode == "naive":
        if calib_data is None:
            raise ValueError("calib_mode='naive' needs calib_data")
        ranges = _collect_ranges(network, calib_data, num_calib_batches)
    else:
        ranges = {}
    exclude = set(exclude_layers or ())

    from ..gluon import nn

    replaced = []

    online = calib_mode == "none"

    def quantizable(c):
        return (isinstance(c, nn.Dense) and c.name not in exclude
                and (online or c.name in ranges))

    def walk(b):
        for attr, c in list(b._children.items()):
            if quantizable(c):
                rng = None if online else tuple(ranges[c.name])
                shim = _CallableBlockShim(_QuantizedDense(c, rng), c)
                replaced.append(c.name)
                b._children[attr] = shim
            else:
                walk(c)

    if quantizable(network):  # the net IS a single Dense: return its shim
        rng = None if online else tuple(ranges[network.name])
        shim = _CallableBlockShim(_QuantizedDense(network, rng), network)
        shim._quantized_layers = [network.name]
        return shim
    walk(network)
    if not replaced:
        import warnings

        warnings.warn(
            "quantize_net: no Dense layer was quantized — with "
            "calib_mode='naive' this usually means calibration saw no "
            "eager forwards (a hybridized net replays its compiled trace; "
            "call quantize_net BEFORE hybridize, or use calib_mode='none')")
    network._quantized_layers = sorted(replaced)
    return network


def quantize_model(sym, arg_params=None, aux_params=None, **kwargs):
    raise NotImplementedError(
        "quantize_model operates on raw Symbols; wrap the symbol in a "
        "SymbolBlock and use quantize_net")


def quantize_graph(*a, **kw):
    raise NotImplementedError(
        "graph-level quantization partitioning is not supported; use "
        "quantize_net")
