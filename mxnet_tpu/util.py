"""Misc utilities (reference python/mxnet/util.py — TBV)."""
from __future__ import annotations

import functools
import inspect

__all__ = ["use_np", "use_np_shape", "use_np_array", "is_np_array",
           "set_module", "makedirs", "get_gpu_count", "get_gpu_memory",
           "default_array"]

_np_array = False


def is_np_array():
    return _np_array


def use_np_shape(fn):
    return fn


def use_np_array(fn):
    return fn


def use_np(fn):
    return fn


def set_module(module):
    def deco(fn):
        fn.__module__ = module
        return fn

    return deco


def makedirs(d):
    import os

    os.makedirs(d, exist_ok=True)


def get_gpu_count():
    return 0


def get_gpu_memory(dev_id=0):
    raise RuntimeError("no CUDA GPUs in the TPU build")


def default_array(source, ctx=None, dtype=None):
    from .ndarray import array

    return array(source, ctx=ctx, dtype=dtype)
