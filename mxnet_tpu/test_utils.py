"""Test utilities — the central numeric fixture.

Reference: ``python/mxnet/test_utils.py`` (TBV — SURVEY.md §4 calls this "the
central fixture"): assert_almost_equal with per-dtype tolerances,
check_numeric_gradient (finite difference vs autograd), check_consistency
(cross-context comparison — here cpu vs tpu vs bf16), default_context.
"""
from __future__ import annotations

import numpy as np

from .context import Context, cpu, current_context
from .ndarray import NDArray, array
from .base import get_env

__all__ = ["list_gpus", "list_tpus",
           "default_context", "assert_almost_equal", "almost_equal", "same",
           "rand_ndarray", "rand_shape_nd", "check_numeric_gradient",
           "check_consistency", "check_grad_consistency", "max_rel_err"]

_DTOL = {
    np.dtype(np.float16): (1e-2, 1e-2),
    np.dtype(np.float32): (1e-4, 1e-5),
    np.dtype(np.float64): (1e-6, 1e-8),
}


def default_context() -> Context:
    """Env-switchable default test context (MXNET_TEST_DEFAULT_CTX=cpu|tpu)."""
    name = get_env("MXNET_TEST_DEFAULT_CTX", None)
    if name:
        dev, _, idx = name.partition(":")
        return Context(dev, int(idx or 0))
    return current_context()


def _np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def same(a, b) -> bool:
    return np.array_equal(_np(a), _np(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _np(a), _np(b)
    rt, at = _tols(a, b, rtol, atol)
    return np.allclose(a, b, rtol=rt, atol=at, equal_nan=equal_nan)


def _tols(a, b, rtol, atol):
    dt = np.promote_types(a.dtype, b.dtype) if a.dtype.kind == "f" else np.dtype(np.float32)
    drt, dat = _DTOL.get(np.dtype(dt), (1e-4, 1e-5))
    return rtol if rtol is not None else drt, atol if atol is not None else dat


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"), equal_nan=False):
    a_, b_ = _np(a), _np(b)
    rt, at = _tols(a_, b_, rtol, atol)
    if a_.shape != b_.shape:
        raise AssertionError(f"shape mismatch: {names[0]}{a_.shape} vs {names[1]}{b_.shape}")
    if not np.allclose(a_, b_, rtol=rt, atol=at, equal_nan=equal_nan):
        err = np.abs(a_.astype(np.float64) - b_.astype(np.float64))
        rel = err / (np.abs(b_.astype(np.float64)) + at)
        idx = np.unravel_index(np.argmax(rel), rel.shape)
        raise AssertionError(
            f"{names[0]} != {names[1]} (rtol={rt}, atol={at}): max abs err "
            f"{err.max():.3e}, max rel err {rel.max():.3e} at {idx}: "
            f"{a_[idx]!r} vs {b_[idx]!r}")


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None,
                 scale=1.0) -> NDArray:
    arr = (np.random.uniform(-scale, scale, size=shape)).astype(dtype or np.float32)
    return array(arr, ctx=ctx or default_context())


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-3):
    """Finite-difference check of autograd gradients.

    ``fn(*ndarrays) -> NDArray scalar-or-any`` is run under autograd.record;
    its sum is backprop'd and each input's .grad is compared against central
    differences. (Reference check_numeric_gradient semantics, adapted to a
    functional callable instead of a Symbol.)
    """
    from . import autograd

    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
        loss = out.sum()
    loss.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for i, x in enumerate(inputs):
        base = x.asnumpy().astype(np.float64)
        num = np.zeros_like(base)
        flat = base.reshape(-1)
        gflat = num.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(fn(*[array(base.reshape(x.shape).astype(x.dtype)) if k == i else inputs[k]
                            for k in range(len(inputs))]).sum().asscalar())
            flat[j] = orig - eps
            fm = float(fn(*[array(base.reshape(x.shape).astype(x.dtype)) if k == i else inputs[k]
                            for k in range(len(inputs))]).sum().asscalar())
            flat[j] = orig
            gflat[j] = (fp - fm) / (2 * eps)
        assert_almost_equal(analytic[i], num.astype(np.float32), rtol=rtol, atol=atol,
                            names=(f"autograd_grad[{i}]", f"numeric_grad[{i}]"))


def max_rel_err(a, b, atol=1e-8):
    """max |a-b| / (|b| + atol) — the error actually recorded by the
    consistency artifacts (a bare ok-boolean hides how close a pass was)."""
    a = _np(a).astype(np.float64)
    b = _np(b).astype(np.float64)
    if a.size == 0:
        return 0.0
    return float(np.max(np.abs(a - b) / (np.abs(b) + atol)))


def check_consistency(fn, inputs, ctx_list=None, dtypes=("float32",), rtol=None, atol=None):
    """Run ``fn`` across contexts/dtypes and cross-compare (reference
    check_consistency pattern — SURVEY.md §4 "the single most important
    idea"). Returns the worst observed max_rel_err across comparisons."""
    ctx_list = ctx_list or [cpu(), default_context()]
    ref = None
    worst = 0.0
    for ctx in ctx_list:
        for dt in dtypes:
            args = [array(_np(x), ctx=ctx, dtype=dt) for x in inputs]
            out = _np(fn(*args))
            if ref is None:
                ref = out
            else:
                rt = rtol if rtol is not None else (1e-2 if dt in ("float16", "bfloat16") else 1e-4)
                at = atol if atol is not None else (1e-2 if dt in ("float16", "bfloat16") else 1e-5)
                assert_almost_equal(out.astype(np.float32), ref.astype(np.float32),
                                    rtol=rt, atol=at, names=(f"{ctx}/{dt}", "ref"))
                worst = max(worst, max_rel_err(out, ref, atol=at))
    return worst


def check_grad_consistency(fn, inputs, ctx_list=None, dtype="float32",
                           rtol=None, atol=None, wrt=None):
    """Forward AND backward cross-context check (reference check_consistency
    runs both directions — tests/python/gpu/test_operator_gpu.py, TBV).

    ``fn(*ndarrays) -> NDArray`` runs under autograd.record on each context;
    a fixed linspace cotangent weights the output (catches permutation /
    sign bugs a plain sum() would mask), then every input gradient is
    cross-compared. ``wrt``: indices of differentiable inputs (default all).
    Returns worst max_rel_err over forward output + all gradients.
    """
    from . import autograd

    ctx_list = ctx_list or [cpu(), default_context()]
    rt = rtol if rtol is not None else (1e-2 if dtype in ("float16", "bfloat16") else 1e-3)
    at = atol if atol is not None else (1e-2 if dtype in ("float16", "bfloat16") else 1e-4)
    recs = []
    for ctx in ctx_list:
        args = [array(_np(x), ctx=ctx, dtype=dtype) for x in inputs]
        grad_idx = list(wrt) if wrt is not None else list(range(len(args)))
        for i in grad_idx:
            args[i].attach_grad()
        with autograd.record():
            out = fn(*args)
            if isinstance(out, (list, tuple)):
                out = out[0]
            cot = np.linspace(0.5, 1.5, int(np.prod(out.shape or (1,)))) \
                .reshape(out.shape).astype(np.float32)
            loss = (out.astype("float32") * array(cot, ctx=ctx)).sum()
        loss.backward()
        recs.append((_np(out),
                     [_np(args[i].grad) if args[i].grad is not None else None
                      for i in grad_idx]))
    ref_out, ref_grads = recs[0]
    worst = 0.0
    for j, (out, grads) in enumerate(recs[1:], start=1):
        assert_almost_equal(out.astype(np.float32), ref_out.astype(np.float32),
                            rtol=rt, atol=at,
                            names=(f"{ctx_list[j]}/fwd", "ref/fwd"))
        worst = max(worst, max_rel_err(out, ref_out, atol=at))
        for gi, (g, rg) in enumerate(zip(grads, ref_grads)):
            if (g is None) != (rg is None):
                raise AssertionError(
                    f"grad[{gi}] is {'None' if g is None else 'set'} on "
                    f"{ctx_list[j]} but {'None' if rg is None else 'set'} on "
                    f"{ctx_list[0]}")
            if g is None:
                continue
            assert_almost_equal(g.astype(np.float32), rg.astype(np.float32),
                                rtol=rt, atol=at,
                                names=(f"{ctx_list[j]}/grad[{gi}]",
                                       f"ref/grad[{gi}]"))
            worst = max(worst, max_rel_err(g, rg, atol=at))
    return worst


def list_gpus():
    """Reference helper: visible GPU ordinals (always [] on the TPU build)."""
    return []


def list_tpus():
    import jax

    try:
        return [d.id for d in jax.devices()
                if d.platform in ("tpu", "axon")]
    except RuntimeError:
        return []
