"""Graph-pass / subgraph-backend API.

Reference: ``src/operator/subgraph/`` (subgraph_property.h plugin API,
build_subgraph.cc partitioner, MKLDNN/TensorRT backends — TBV, SURVEY.md
§2.2 Subgraph row). TPU redesign: XLA already fuses and plans memory, so
partition-for-a-faster-engine is moot — what remains valuable are
ALGEBRAIC rewrites that XLA cannot do because they change the program
(e.g. folding inference BatchNorm into the preceding Convolution's
weights). Passes are registered by name and applied to Symbol graphs by
``optimize_symbol``; ``HybridBlock.optimize_for(backend)`` routes here for
Symbol-backed blocks.
"""
from __future__ import annotations

from typing import Callable, Dict

import numpy as np

__all__ = ["register_pass", "list_passes", "optimize_symbol", "fold_bn"]

_PASSES: Dict[str, Callable] = {}


def register_pass(name: str):
    def deco(fn):
        _PASSES[name] = fn
        return fn
    return deco


def list_passes():
    return sorted(_PASSES)


def optimize_symbol(symbol, backend, arg_params=None, aux_params=None):
    """Apply a registered pass: returns (new_symbol, new_args, new_aux).

    ``backend`` names a pass ("fold_bn") or the reference backend aliases
    ("MKLDNN"/"TensorRT"/"default"), which map to the standard inference
    rewrite set.
    """
    name = {"mkldnn": "fold_bn", "tensorrt": "fold_bn",
            "default": "fold_bn"}.get(str(backend).lower(), backend)
    if name not in _PASSES:
        raise ValueError(f"unknown subgraph backend/pass {backend!r}; "
                         f"registered: {list_passes()}")
    return _PASSES[name](symbol, dict(arg_params or {}), dict(aux_params or {}))


@register_pass("fold_bn")
def fold_bn(symbol, arg_params, aux_params):
    """Fold inference-mode BatchNorm into the preceding Convolution.

    BN(conv(x, W) + b) == conv(x, W') + b' with
        scale = gamma / sqrt(var + eps)
        W' = W * scale[:, None, None, None]
        b' = (b - mean) * scale + beta
    Only folds BN nodes whose data input is a Convolution with no other
    consumers (the reference partitioner's same constraint). Rebuilds the
    Symbol DAG directly (a proper graph pass, not a JSON round-trip).
    """
    from .symbol.symbol import Symbol, Variable

    nodes = symbol._topo()
    consumers: Dict[int, int] = {}
    for n in nodes:
        for i in n._inputs:
            b = i._base()
            consumers[id(b)] = consumers.get(id(b), 0) + 1

    new_args = dict(arg_params)
    new_aux = dict(aux_params)
    folded = []

    def _np(d):
        return d.asnumpy() if hasattr(d, "asnumpy") else np.asarray(d)

    memo: Dict[int, Symbol] = {}

    def rebuild(node):
        if node._index is not None:
            return rebuild(node._base())[node._index]
        if id(node) in memo:
            return memo[id(node)]
        new_ins = [rebuild(i) for i in node._inputs]
        result = None
        if node._op == "BatchNorm" and node._inputs:
            conv_orig = node._inputs[0]._base()
            if (conv_orig._op == "Convolution"
                    and consumers.get(id(conv_orig), 0) == 1
                    and len(node._inputs) >= 5):
                g_name = node._inputs[1]._base()._name
                b_name = node._inputs[2]._base()._name
                m_name = node._inputs[3]._base()._name
                v_name = node._inputs[4]._base()._name
                w_name = conv_orig._inputs[1]._base()._name
                attrs = dict(node._attrs)
                no_bias = str(conv_orig._attrs.get(
                    "no_bias", "False")).lower() in ("true", "1")
                if (w_name in new_args and g_name in new_args
                        and b_name in new_args and m_name in new_aux
                        and v_name in new_aux):
                    eps = float(attrs.get("eps", 1e-3))
                    fix_gamma = str(attrs.get("fix_gamma", "True")).lower() \
                        in ("true", "1")
                    gamma = _np(new_args[g_name]).astype(np.float64)
                    if fix_gamma:
                        gamma = np.ones_like(gamma)
                    beta = _np(new_args[b_name]).astype(np.float64)
                    mean = _np(new_aux[m_name]).astype(np.float64)
                    var = _np(new_aux[v_name]).astype(np.float64)
                    w = _np(new_args[w_name]).astype(np.float64)
                    scale = gamma / np.sqrt(var + eps)
                    if no_bias or len(conv_orig._inputs) < 3:
                        bias = np.zeros_like(mean)
                        bias_name = w_name.rsplit("_", 1)[0] + "_bias"
                    else:
                        bias_name = conv_orig._inputs[2]._base()._name
                        bias = _np(new_args[bias_name]).astype(np.float64)

                    from .ndarray import array as nd_array

                    new_args[w_name] = nd_array(
                        (w * scale.reshape(-1, 1, 1, 1)).astype(np.float32))
                    new_args[bias_name] = nd_array(
                        ((bias - mean) * scale + beta).astype(np.float32))
                    for nm in (g_name, b_name):
                        new_args.pop(nm, None)
                    for nm in (m_name, v_name):
                        new_aux.pop(nm, None)

                    conv_new_ins = rebuild(conv_orig)._inputs[:2] + \
                        [Variable(bias_name)]
                    conv_attrs = dict(conv_orig._attrs)
                    conv_attrs["no_bias"] = False
                    result = Symbol("Convolution", conv_orig._name,
                                    conv_new_ins, conv_attrs)
                    folded.append(node._name)
        if result is None:
            result = Symbol(node._op, node._name, new_ins, node._attrs)
        memo[id(node)] = result
        return result

    new_sym = rebuild(symbol)  # rebuild() dispatches on _index itself
    new_sym._folded_bn = folded
    return new_sym, new_args, new_aux
