"""``mx.attribute.AttrScope`` — scoped symbol attributes.

Reference: ``python/mxnet/attribute.py`` (TBV): symbols created inside the
scope inherit its attrs (the mechanism behind ``__ctx_group__`` model-parallel
placement and lr_mult annotations).
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current", "attr_scope"]


class _State(threading.local):
    def __init__(self):
        self.attrs = {}


_STATE = _State()


class AttrScope:
    def __init__(self, **attrs):
        for v in attrs.values():
            if not isinstance(v, str):
                raise ValueError("attributes must be strings")
        self._attrs = attrs

    def get(self, attrs=None):
        """Merge scope attrs into ``attrs`` (reference AttrScope.get)."""
        out = dict(_STATE.attrs)
        if attrs:
            out.update(attrs)
        return out

    def __enter__(self):
        self._saved = dict(_STATE.attrs)
        _STATE.attrs = {**_STATE.attrs, **self._attrs}
        return self

    def __exit__(self, *exc):
        _STATE.attrs = self._saved


def current():
    return dict(_STATE.attrs)


attr_scope = AttrScope
