"""Checkpoint save/load (reference python/mxnet/model.py — TBV SURVEY.md §5.4).

Naming convention matches the reference: ``prefix-symbol.json`` +
``prefix-%04d.params`` with ``arg:name`` / ``aux:name`` keyed NDArrays; the
params container is the reference binary NDArray format (see
``ndarray.save``).
"""
from __future__ import annotations

from typing import Dict, Tuple

from .ndarray import NDArray, load as nd_load, save as nd_save

__all__ = ["save_checkpoint", "load_checkpoint", "load_params"]


def save_checkpoint(prefix, epoch, symbol, arg_params: Dict[str, NDArray],
                    aux_params: Dict[str, NDArray], remove_amp_cast=True):
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd_save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch) -> Tuple[Dict[str, NDArray], Dict[str, NDArray]]:
    loaded = nd_load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    from . import symbol as sym_mod

    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
