"""Checkpoint save/load (reference python/mxnet/model.py — TBV SURVEY.md §5.4).

Naming convention matches the reference: ``prefix-symbol.json`` +
``prefix-%04d.params`` with ``arg:name`` / ``aux:name`` keyed NDArrays; the
params container is the reference binary NDArray format (see
``ndarray.save``).
"""
from __future__ import annotations

from typing import Dict, Tuple

from .ndarray import NDArray, load as nd_load, save as nd_save

__all__ = ["save_checkpoint", "load_checkpoint", "load_params", "FeedForward"]


def save_checkpoint(prefix, epoch, symbol, arg_params: Dict[str, NDArray],
                    aux_params: Dict[str, NDArray], remove_amp_cast=True):
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd_save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch) -> Tuple[Dict[str, NDArray], Dict[str, NDArray]]:
    loaded = nd_load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    from . import symbol as sym_mod

    symbol = sym_mod.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    # Normalize arg/aux placement to the RELOADED graph's view: a
    # checkpoint saved from a traced-gluon Module stores BatchNorm moving
    # stats under ``arg:`` (the trace makes them plain variables), while
    # load_json re-derives them as auxiliary states from the op registry —
    # without this re-split such stats would be silently dropped on bind.
    aux_names = set(symbol.list_auxiliary_states())
    merged = {**arg_params, **aux_params}
    arg_params = {k: v for k, v in merged.items() if k not in aux_names}
    aux_params = {k: v for k, v in merged.items() if k in aux_names}
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy pre-Module training API (reference mx.model.FeedForward —
    deprecated upstream in favor of Module; kept as a thin adapter over
    Module for script parity).

    Training through this adapter inherits Module's fused update path: all
    parameter updates per step run as ONE compiled program
    (optimizer/fused.py, docs/PERFORMANCE.md; ``MXNET_FUSED_UPDATE=0``
    restores the per-parameter eager loop)."""

    def __init__(self, symbol, ctx=None, num_epoch=None, optimizer="sgd",
                 initializer=None, arg_params=None, aux_params=None,
                 learning_rate=0.01, **kwargs):
        from .module import Module

        self.symbol = symbol
        self._ctx = ctx
        self._num_epoch = num_epoch
        self._optimizer = optimizer
        self._opt_kwargs = dict(kwargs)
        self._opt_kwargs["learning_rate"] = learning_rate
        self._initializer = initializer
        self.arg_params = arg_params
        self.aux_params = aux_params
        self._module = Module(symbol, context=ctx)
        self._fitted = False

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            batch_end_callback=None, epoch_end_callback=None, logger=None,
            checkpoint=None, resume="auto", **kwargs):
        """``checkpoint=`` (a directory or CheckpointManager) + the default
        ``resume="auto"`` give the legacy API the same crash-safe
        checkpointing contract as Module.fit (docs/ROBUSTNESS.md), and
        ``health=`` (forwarded through ``**kwargs``) the same divergence
        sentinel + auto-rollback (docs/OBSERVABILITY.md "Training
        health"). Passing an elastic ``kvstore=`` (a DistKVStore created
        under ``MXNET_ELASTIC=1``) through ``**kwargs`` likewise inherits
        the elastic-training plane — generation-scoped gradient sync,
        survivor shard recuts, checkpointed rejoin (docs/ROBUSTNESS.md
        "Elastic training")."""
        from .io import NDArrayIter

        del logger  # accepted for signature parity; Module logs via logging
        train = X if hasattr(X, "provide_data") else NDArrayIter(X, y, batch_size=128)
        self._module.fit(
            train, eval_data=eval_data, eval_metric=eval_metric,
            optimizer=self._optimizer, optimizer_params=self._opt_kwargs,
            initializer=self._initializer,
            arg_params=self.arg_params, aux_params=self.aux_params,
            num_epoch=self._num_epoch or 1,
            batch_end_callback=batch_end_callback,
            epoch_end_callback=epoch_end_callback,
            checkpoint=checkpoint, resume=resume, **kwargs)
        self.arg_params, self.aux_params = self._module.get_params()
        self._fitted = True
        return self

    def predict(self, X, num_batch=None):
        from .io import NDArrayIter

        it = X if hasattr(X, "provide_data") else NDArrayIter(X, batch_size=128)
        outs = self._module.predict(it, num_batch=num_batch)
        return outs.asnumpy() if hasattr(outs, "asnumpy") else outs

    def score(self, X, y=None, eval_metric="acc", num_batch=None):
        from .io import NDArrayIter

        it = X if hasattr(X, "provide_data") else NDArrayIter(X, y, batch_size=128)
        return self._module.score(it, eval_metric, num_batch=num_batch)

    def save(self, prefix, epoch=None):
        save_checkpoint(prefix, epoch if epoch is not None else 0,
                        self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        sym, arg, aux = load_checkpoint(prefix, epoch)
        return FeedForward(sym, ctx=ctx, arg_params=arg, aux_params=aux,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=1, **kwargs):
        m = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch, **kwargs)
        return m.fit(X, y)
