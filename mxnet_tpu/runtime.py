"""``mx.runtime`` — compiled-feature introspection.

Reference: ``src/libinfo.cc`` → ``mx.runtime.feature_list()`` (TBV —
SURVEY.md §5.6). Features reflect the TPU build: CUDA-family flags are
False, TPU/XLA capabilities are reported in their place.
"""
from __future__ import annotations

from collections import namedtuple

import jax

__all__ = ["Feature", "feature_list", "Features", "is_enabled",
           "EnvVar", "env_list", "env_doc"]

Feature = namedtuple("Feature", ["name", "enabled"])


def _detect():
    platforms = {d.platform for d in jax.devices()}
    feats = {
        "TPU": "tpu" in platforms or "axon" in platforms,
        "CPU": True,
        "CUDA": False,
        "CUDNN": False,
        "NCCL": False,
        "XLA": True,
        "PALLAS": True,
        "PJIT": True,
        "SHARD_MAP": True,
        "RING_ATTENTION": True,
        "BF16": True,
        "INT8": False,
        "OPENCV": False,
        "PIL": _has("PIL"),
        "DIST_KVSTORE": True,
        "PS_DIST_ASYNC": True,
        "SIGNAL_HANDLER": True,
        "PROFILER": True,
    }
    return feats


def _has(mod):
    try:
        __import__(mod)
        return True
    except ImportError:
        return False


def feature_list():
    return [Feature(k, v) for k, v in _detect().items()]


class Features(dict):
    def __init__(self):
        super().__init__({k: Feature(k, v) for k, v in _detect().items()})

    def is_enabled(self, name):
        return self.get(name, Feature(name, False)).enabled


def is_enabled(name):
    return Features().is_enabled(name)


# ---------------------------------------------------------------------------
# Environment-variable registry (reference: docs .../env_var.md — the
# documented MXNET_* surface, SURVEY.md §5.6; round-2 verdict flagged the
# missing systematic registry). Every env var the framework reads is
# declared here with its default and meaning; ``env_list()`` reports the
# registry with current values, the runtime analog of feature_list().
# ---------------------------------------------------------------------------

EnvVar = namedtuple("EnvVar", ["name", "default", "description", "current"])

_ENV_REGISTRY = {
    # core
    "MXNET_SEED": (None, "Global RNG seed (framework key stream + numpy init "
                         "stream; reference MXNET_SEED)."),
    "MXNET_ENGINE_TYPE": (None, "Set to 'NaiveEngine' for synchronous "
                                "execution (block after every op) — the "
                                "reference's serial debug engine."),
    "MX_SYNC": ("0", "1 = block_until_ready after every eager op (race "
                     "debugging; alias of NaiveEngine mode)."),
    "MXNET_MATMUL_PRECISION": ("highest", "XLA matmul precision for f32: "
                               "default|high|highest. bf16 inputs always "
                               "run single-pass MXU."),
    "MXNET_ATTENTION_IMPL": ("auto", "auto|plain|flash — fused-attention "
                             "dispatch policy (ops/attention.py)."),
    "MXNET_TEST_DEFAULT_CTX": (None, "Context for test_utils.default_context,"
                               " e.g. 'cpu' or 'tpu(0)'."),
    # read by tests/conftest.py, outside the linted package tree
    "MXNET_TEST_SEED": (None, "Per-test seed used by the test "  # lint: disable=env-registry-drift
                        "fixtures (reference with_seed())."),
    "MXNET_NO_NATIVE_BUILD": (None, "1 = never build/load the native C++ "
                              "components (PIL/python fallbacks)."),
    # platform / compile (mxnet_tpu/__init__.py, platform.py, executor.py)
    "MXNET_FORCE_PLATFORM": (None, "cpu|tpu — pin the jax backend at "
                             "import time (images that preload jax set "
                             "JAX_PLATFORMS too early for subprocesses)."),
    "MXNET_COMPILE_CACHE": ("1", "0 = disable the persistent XLA "
                            "compilation cache (keyed by HLO hash, so "
                            "code changes never serve stale binaries)."),
    "MXNET_COMPILE_CACHE_DIR": (None, "XLA compile-cache directory "
                                "(default ~/.cache/mxnet_tpu_jax)."),
    "MXNET_PLATFORM_TIMEOUT": ("90", "Accelerator-driver watchdog budget "
                               "(seconds); every driver call must return "
                               "or the tunnel is declared hung."),
    "MXNET_GRAPH_LINT": ("off", "off|warn|error — graph-lint severity "
                         "when an executor binds a symbolic graph."),
    "MXNET_NP_SILENT_FALLBACK": (None, "1 = silence the once-per-name "
                                 "warning when mxnet_tpu.numpy delegates "
                                 "an op to real numpy (host round-trip)."),
    "MXNET_FLASH_BLOCK_Q": (None, "Flash-attention Q block-length "
                            "override (default: tuned per backend)."),
    "MXNET_FLASH_BLOCK_K": (None, "Flash-attention K block-length "
                            "override."),
    "MXNET_FLASH_BWD": ("auto", "auto|flash|plain — flash-attention "
                        "backward-pass implementation."),
    "MXNET_FUSED_UPDATE": ("1", "0 = bypass the fused optimizer-update "
                           "engine and run the eager per-array oracle "
                           "(optimizer/fused.py)."),
    "MXNET_FUSED_DONATE": (None, "Override buffer donation in the fused "
                           "update engine (default: donate wherever "
                           "aliasing is safe)."),
    # telemetry core (obs/__init__.py, obs/trace.py, obs/context.py,
    # serve/fleet.py)
    "MXNET_OBS": (None, "1 = enable the telemetry plane at import "
                  "(metrics registry, tracer, exporters)."),
    "MXNET_OBS_JSONL": (None, "Telemetry JSONL stream path (implies "
                        "MXNET_OBS=1); %p expands to the pid at the "
                        "child's obs import."),
    "MXNET_OBS_DIR": (None, "Fleet supervisor: directory for per-replica "
                      "telemetry streams and blackbox bundles."),
    "MXNET_OBS_BUFFER": ("65536", "Tracer ring capacity (retained "
                         "spans)."),
    "MXNET_OBS_SAMPLE": (None, "Head-based sampling probability for new "
                         "trace roots, 0..1 (default 1.0; children "
                         "inherit the root's verdict)."),
    "MXNET_OBS_WIRE": ("1", "0 = never put trace context on the wire "
                       "(escape hatch for old peers)."),
    # sanitizers (tsan.py, copytrack.py — docs/ANALYSIS.md)
    "MXNET_TSAN": (None, "1 = enable the lock-order/stall sanitizer: "
                   "instrumented locks record acquisition order and a "
                   "watchdog flags cycles and stalls (tsan.py)."),
    "MXNET_TSAN_RAISE": (None, "1 = raise on a lock-order violation "
                         "instead of warning once per pair."),
    "MXNET_TSAN_STALL_S": ("20", "Stall-watchdog threshold (seconds a "
                           "lock may be held/waited before a report)."),
    "MXNET_COPYTRACK": (None, "1 = data-plane copy tracker: wire/batcher/"
                        "device choke points count wire.bytes_copied, "
                        "wire.serialize_calls and hotpath.host_syncs "
                        "(the dataplane lint's runtime twin — "
                        "analysis/dataplane.py; zero overhead when "
                        "off)."),
    # fault injection (chaos/ — docs/ROBUSTNESS.md)
    "MXNET_CHAOS_KILL": (None, "Chaos: SIGKILL this process at counted "
                         "guard-point hits, e.g. 'ckpt:pre_rename@3' "
                         "(chaos/proc.py; the fleet supervisor forwards "
                         "MXNET_CHAOS_KILL_REPLICA<i> to replica i)."),
    "MXNET_CHAOS_RPC": (None, "Chaos: drop/delay/duplicate PS RPCs at "
                        "exact occurrence counts, e.g. "
                        "'push_seq:drop_reply@1;pull:delay@2:0.5' "
                        "(chaos/rpc.py)."),
    "MXNET_CHAOS_TUNNEL_HANG": (None, "Chaos: hang named platform guard "
                                "points the way a dead accelerator "
                                "tunnel does ('*' = all; "
                                "chaos/platform.py)."),
    # device-plane observability (obs/device.py, docs/OBSERVABILITY.md)
    "MXNET_DEVICE_COST": (None, "1 = force XLA cost/memory capture at every "
                          "compile choke point (0 = veto); default follows "
                          "the obs telemetry flag."),
    "MXNET_DEVICE_PEAK_TFLOPS": (None, "Peak compute rate used by analytic "
                                 "MFU/roofline math (overrides the "
                                 "per-backend nominal default)."),
    "MXNET_DEVICE_PEAK_GBPS": (None, "Peak memory bandwidth for the "
                               "roofline balance point."),
    "MXNET_OBS_MEMORY": ("1", "0 = skip the per-batch device.live_bytes "
                         "sampling even with telemetry on."),
    "MXNET_DEVICE_LEAK_WINDOW": ("10", "Leak-detector sliding window "
                                 "(samples)."),
    "MXNET_DEVICE_LEAK_BYTES_PER_STEP": (str(1 << 20), "Leak-detector "
                                         "slope threshold (bytes/step)."),
    # training-health plane (obs/health.py, docs/OBSERVABILITY.md
    # "Training health")
    "MXNET_OBS_HEALTH": (None, "1 = force the training-health plane's "
                         "in-graph numerics stats on (0 = veto); default: "
                         "on while a HealthMonitor is attached to a "
                         "training loop."),
    "MXNET_OBS_HEALTH_EVERY": ("10", "Health sampling period K: the "
                               "sentinel fetches the device-resident "
                               "stats with one batched device_get every "
                               "K optimizer steps."),
    "MXNET_CHAOS_NAN": (None, "Chaos: poison a named tensor with NaN at "
                        "counted forward occurrences, e.g. 'data@5' "
                        "(chaos/nan.py — tests the breach/provenance/"
                        "rollback chain deterministically)."),
    # training-fleet telemetry plane (obs/fleetstats.py,
    # docs/OBSERVABILITY.md "Training-fleet telemetry")
    "MXNET_OBS_FLEET": (None, "0 = veto the training-fleet plane (per-rank "
                        "step-phase windows, heartbeat piggyback, "
                        "straggler detection) even with MXNET_OBS=1; it "
                        "is on by default whenever telemetry records."),
    "MXNET_OBS_FLEET_WINDOW": ("10", "Optimizer steps per accounting "
                               "window; windows seal at multiples of "
                               "this and ship on the next heartbeat."),
    "MXNET_OBS_FLEET_FACTOR": ("1.5", "Straggler threshold: a rank whose "
                               "own time (step minus reduce-wait) "
                               "exceeds the fleet median by this factor "
                               "is lagging."),
    "MXNET_OBS_FLEET_K": ("3", "Consecutive lagging windows before a "
                          "straggler verdict fires (and, symmetrically, "
                          "recovered windows before it clears)."),
    "MXNET_OBS_FLEET_SHIP_S": ("2", "Max seconds between heartbeat-"
                               "piggybacked telemetry ships when no new "
                               "window sealed (spans still flow)."),
    "MXNET_OBS_FLEET_MAX_SPANS": ("4096", "Newest spans kept per "
                                  "piggybacked ship (a stalled fleet "
                                  "cannot grow one heartbeat frame "
                                  "without bound)."),
    "MXNET_OBS_FLEET_HOT_KEYS": ("32", "Capacity of the PS server's "
                                 "bounded top-N hot-key table "
                                 "(space-saving admission)."),
    "MXNET_CHAOS_SLOW": (None, "Chaos: delay a named rank's step phase at "
                         "counted occurrences, e.g. '1:forward@5-40:0.25' "
                         "(chaos/slow.py — proves the straggler detector "
                         "flags the injected rank AND phase). The seconds "
                         "field also takes a 'base+step' ramp, e.g. "
                         "'1:forward@5-40:0.1+0.02' — a WORSENING "
                         "straggler, for proving staleness-widening "
                         "policies against deterioration."),
    # black-box plane (obs/tail.py, obs/profile.py, obs/blackbox.py —
    # docs/OBSERVABILITY.md "Tail sampling" / "Continuous profiling" /
    # "Flight recorder")
    "MXNET_OBS_TAIL": (None, "1 = tail-based trace retention: every "
                       "request's spans record into a pending buffer and "
                       "the keep-or-drop decision moves to root-span "
                       "close (latency/outcome/budget policy) instead of "
                       "the head-sampling coin flip."),
    "MXNET_OBS_TAIL_SLOW_MS": ("250", "Root latency at or above this is "
                               "'interesting' — retained while the "
                               "token-bucket budget has tokens."),
    "MXNET_OBS_TAIL_BUDGET": ("20", "Token-bucket refill rate: "
                              "interesting-trace retentions per second "
                              "(burst = 2x)."),
    "MXNET_OBS_TAIL_BASELINE": ("0.01", "Uniform keep probability applied "
                                "regardless of policy — budget exhaustion "
                                "degrades to baseline sampling, never to "
                                "zero."),
    "MXNET_OBS_TAIL_TRACES": ("512", "Max traces pending a verdict "
                              "(oldest evicted past it)."),
    "MXNET_OBS_TAIL_SPANS": ("256", "Max held spans per pending trace."),
    "MXNET_OBS_TAIL_HOLD_S": ("20", "Replica-side hold window: pending "
                              "spans past it expire if no verdict "
                              "arrived over the telemetry plane."),
    "MXNET_OBS_PROF": (None, "1 = start the continuous sampling profiler "
                       "at import (sys._current_frames stack samples, "
                       "phase-tagged, collapsed-stack + chrome-trace "
                       "exports)."),
    "MXNET_OBS_PROF_HZ": ("67", "Profiler sampling rate (Hz). Deliberately "
                          "off the 10ms-timer beat so periodic work "
                          "cannot hide between ticks."),
    "MXNET_OBS_PROF_DEPTH": ("48", "Max folded-stack depth (innermost "
                             "frames win)."),
    "MXNET_OBS_PROF_BUFFER": ("65536", "Raw sample ring capacity (the "
                              "flight recorder's profiler slice)."),
    "MXNET_OBS_BLACKBOX": (None, "1 = arm the crash flight recorder: an "
                           "always-on ring of recent spans/metrics/"
                           "profiler stacks dumped as a bundle on fatal "
                           "signals, deadlock watchdog, SLO/health "
                           "breaches, or OP_DUMP."),
    "MXNET_OBS_BLACKBOX_DIR": (None, "Bundle directory (setting it also "
                               "arms the recorder); the periodic "
                               "blackbox-<pid>-last.json flush lands "
                               "here — the SIGKILL artifact."),
    "MXNET_OBS_BLACKBOX_EVENTS": ("4096", "Flight-recorder ring capacity "
                                  "(most recent telemetry events)."),
    "MXNET_OBS_BLACKBOX_FLUSH_S": ("2", "Periodic last-bundle rewrite "
                                   "interval; a SIGKILL leaves a bundle "
                                   "at most this stale."),
    "MXNET_OBS_BLACKBOX_COOLDOWN_S": ("30", "Min seconds between automatic "
                                      "dumps (a breach storm must not "
                                      "turn the recorder into the "
                                      "outage)."),
    "MXNET_OBS_BLACKBOX_PROF_S": ("10", "Seconds of profiler samples a "
                                  "bundle embeds (a bounded slice of the "
                                  "ring, not all ~16 min of it)."),
    # persistent AOT program cache (mxnet_tpu/progcache.py,
    # docs/PERFORMANCE.md "Program cache and cold start")
    "MXNET_PROGCACHE": (None, "1 = arm the persistent AOT program cache "
                        "at the default dir (~/.cache/mxnet_tpu/"
                        "progcache); 0 = veto even with a dir set. "
                        "Serve-bucket and fused-update programs warm "
                        "across processes by deserializing the stored "
                        "executable (same machine code — bitwise) instead "
                        "of recompiling."),
    "MXNET_PROGCACHE_DIR": (None, "Program-cache directory (setting it "
                            "arms the cache). Inherited by ProcReplica "
                            "children, so autoscale scale-out and "
                            "restart-after-SIGKILL warm from disk; a "
                            "stale/foreign/corrupt entry is a counted "
                            "reject that degrades to a plain compile."),
    "MXNET_PROGCACHE_KEEP": ("128", "Keep-last-N GC bound: most recently "
                             "USED entries kept (reads touch mtime), "
                             "older ones dropped after each write."),
    "MXNET_SERVE_WARMUP_THREADS": (None, "Thread-pool width for "
                                   "InferenceEngine.warmup's concurrent "
                                   "per-bucket compiles (default "
                                   "min(buckets, cores); 1 = serial)."),
    # autoregressive decode engine (serve/decode.py, docs/SERVING.md
    # "Autoregressive decode")
    "MXNET_DECODE_SLOTS": ("8", "Decode-step batch width: concurrent "
                           "generations per replica. Fixed at engine "
                           "construction — the step is ONE compiled "
                           "program, idle slots park on the scratch "
                           "page."),
    "MXNET_DECODE_PAGE_SIZE": ("16", "KV-cache page size in tokens. "
                               "Every prompt bucket is a multiple of it, "
                               "so prefill scatters whole pages."),
    "MXNET_DECODE_PAGES": ("64", "KV page-pool capacity (page 0 is the "
                           "reserved scratch page, so usable pages are "
                           "N-1). Sizing: slots × ceil(max_tokens/"
                           "page_size) covers worst-case residency."),
    "MXNET_DECODE_MAX_NEW": ("64", "Default max new tokens per "
                             "generation when the request does not cap "
                             "it."),
    "MXNET_DECODE_TIMEOUT": ("30.0", "Default per-generation deadline "
                             "seconds when the request carries none — "
                             "an abandoned stream can hold KV pages at "
                             "most this long."),
    "MXNET_DECODE_ATTN": ("auto", "Paged decode-attention backend: "
                          "auto (Pallas on TPU, XLA gather elsewhere), "
                          "pallas, or xla."),
    # distributed (DMLC_* names kept for launcher compat)
    "DMLC_ROLE": (None, "worker|server|scheduler — set by tools/launch.py."),
    "DMLC_PS_ROOT_URI": (None, "Coordinator/PS host (reference ps-lite env)."),
    "DMLC_PS_ROOT_PORT": (None, "Coordinator/PS port."),
    "DMLC_NUM_WORKER": ("1", "World size for dist kvstores."),
    "DMLC_WORKER_ID": ("0", "This worker's rank."),
    "MXNET_COORDINATOR": (None, "host:port for jax.distributed.initialize "
                          "(overrides DMLC_PS_ROOT_URI/PORT)."),
    "MXNET_NUM_WORKER": ("1", "Alias of DMLC_NUM_WORKER."),
    "MXNET_WORKER_ID": ("0", "Alias of DMLC_WORKER_ID."),
    "MXNET_PS_ADDR": (None, "dist_async parameter-server host (falls back "
                      "to DMLC_PS_ROOT_URI)."),
    "MXNET_PS_PORT": ("9091", "dist_async parameter-server port."),
    "MXNET_PS_PLATFORM": ("cpu", "jax platform for the standalone PS "
                          "server process (weights are host-resident; "
                          "cpu is the right default)."),
    "MXNET_SERVE_PLATFORM": (None, "jax platform pin for a serve replica "
                             "process (the PS server's MXNET_PS_PLATFORM "
                             "idiom; unset = jax's own default)."),
    # elastic training (docs/ROBUSTNESS.md "Elastic training")
    "MXNET_ELASTIC": (None, "1 = elastic dist_sync: reductions ride the PS "
                      "wire scoped to the live membership generation; a "
                      "dead worker releases barriers over survivors, a "
                      "restarted one rejoins from the shared checkpoint "
                      "(kvstore/elastic.py; launch.py -e)."),
    "MXNET_ELASTIC_HEARTBEAT_S": ("0.5", "Worker heartbeat interval; also "
                                  "the PS liveness-monitor sweep period."),
    "MXNET_ELASTIC_MISS_K": ("4", "Missed heartbeats before the PS "
                             "declares a worker dead and bumps the "
                             "membership generation."),
    "MXNET_ELASTIC_JOIN_TIMEOUT_S": ("600", "Max wait for a quarantined "
                                     "rejoiner's epoch-boundary "
                                     "activation (and epoch rendezvous)."),
    "MXNET_ELASTIC_REDUCE_TIMEOUT_S": ("120", "Generation-scoped reduce "
                                       "wait bound (carried in the "
                                       "request; the server answers "
                                       "before the socket gives up)."),
    "MXNET_ELASTIC_ALLOW_STALE_REJOIN": (None, "1 = let a rejoiner whose "
                                         "newest shared checkpoint lags "
                                         "the fleet's epoch proceed "
                                         "anyway (ranks then train "
                                         "DIVERGENT models — fit raises "
                                         "by default)."),
    # bounded-staleness async training (docs/ROBUSTNESS.md "Asynchronous
    # training")
    "MXNET_ASYNC_STALENESS": (None, "Bounded-staleness async training: a "
                              "worker more than this many steps ahead of "
                              "the fleet's committed-clock floor blocks "
                              "at pull (stale-synchronous-parallel; "
                              "launch.py --async-staleness). Unset = "
                              "classic unbounded dist_async."),
    "MXNET_ASYNC_WIDEN": ("2", "Steps added to the staleness bound each "
                          "time the straggler policy widens it for a "
                          "compute-blamed rank (on_straggler actuation)."),
    "MXNET_ASYNC_MAX_STALENESS": ("16", "Hard cap on the effective "
                                  "staleness bound (base + policy "
                                  "widening can never exceed it)."),
    "MXNET_ASYNC_LR_COMP": ("1", "0 = disable worker-side staleness-aware "
                            "lr compensation (gradients scaled by "
                            "1/(1+lag) vs the fleet's max committed "
                            "clock)."),
    "MXNET_ASYNC_GROUP": (None, "Hierarchical reduction group size for "
                          "elastic dist_sync (>1 = group-local scoped "
                          "sum, leaders-only cross-group sum, group "
                          "broadcast — the reduce plane stops being "
                          "all-to-one). Unset/0 = flat reduce."),
    "MXNET_PS_SNAPSHOT_DIR": (None, "PS durable-state directory: atomic+"
                              "CRC snapshots + push WAL; warm restart "
                              "resumes from the newest valid snapshot "
                              "with the seq-dedup table intact."),
    "MXNET_PS_SNAPSHOT_PERIOD_S": ("5", "Seconds between periodic PS "
                                   "snapshots (0 = only INIT/SET_OPT/"
                                   "shutdown snapshots)."),
    "MXNET_PS_WAL_FSYNC": ("1", "0 = skip the fsync-per-acked-push in the "
                           "PS write-ahead log (faster; a power loss may "
                           "then drop the tail — a plain SIGKILL "
                           "usually cannot)."),
    "MXNET_PS_IDLE_PING_S": (None, "Idle threshold (seconds) after which "
                             "the PS client pings before reusing a "
                             "connection (half-open detection; needs a "
                             "python server — elastic sessions default "
                             "to 30)."),
}


def env_list():
    """All registered env vars with defaults, docs, and current values."""
    import os

    return [EnvVar(k, d, doc, os.environ.get(k)) for k, (d, doc)
            in sorted(_ENV_REGISTRY.items())]


def env_doc(name):
    d, doc = _ENV_REGISTRY[name]
    return f"{name} (default {d!r}): {doc}"
