"""``mx.runtime`` — compiled-feature introspection.

Reference: ``src/libinfo.cc`` → ``mx.runtime.feature_list()`` (TBV —
SURVEY.md §5.6). Features reflect the TPU build: CUDA-family flags are
False, TPU/XLA capabilities are reported in their place.
"""
from __future__ import annotations

from collections import namedtuple

import jax

__all__ = ["Feature", "feature_list", "Features", "is_enabled"]

Feature = namedtuple("Feature", ["name", "enabled"])


def _detect():
    platforms = {d.platform for d in jax.devices()}
    feats = {
        "TPU": "tpu" in platforms or "axon" in platforms,
        "CPU": True,
        "CUDA": False,
        "CUDNN": False,
        "NCCL": False,
        "XLA": True,
        "PALLAS": True,
        "PJIT": True,
        "SHARD_MAP": True,
        "RING_ATTENTION": True,
        "BF16": True,
        "INT8": False,
        "OPENCV": False,
        "PIL": _has("PIL"),
        "DIST_KVSTORE": True,
        "PS_DIST_ASYNC": True,
        "SIGNAL_HANDLER": True,
        "PROFILER": True,
    }
    return feats


def _has(mod):
    try:
        __import__(mod)
        return True
    except ImportError:
        return False


def feature_list():
    return [Feature(k, v) for k, v in _detect().items()]


class Features(dict):
    def __init__(self):
        super().__init__({k: Feature(k, v) for k, v in _detect().items()})

    def is_enabled(self, name):
        return self.get(name, Feature(name, False)).enabled


def is_enabled(name):
    return Features().is_enabled(name)
