"""``mx.viz`` — network visualization (reference
``python/mxnet/visualization.py`` — TBV: print_summary + plot_network).

``print_summary`` is fully supported (text). ``plot_network`` returns a
graphviz Digraph when the optional ``graphviz`` package exists, else raises
ImportError with guidance (graphviz is not in the TPU image).
"""
from __future__ import annotations

import json

__all__ = ["print_summary", "plot_network"]


def _sym_nodes(symbol):
    conf = json.loads(symbol.tojson())
    return conf["nodes"], conf.get("heads", [])


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Layer-table summary of a Symbol graph (reference mx.viz.print_summary)."""
    nodes, _ = _sym_nodes(symbol)
    shapes = {}
    if shape is not None:
        arg_shapes, out_shapes, _aux = symbol.infer_shape(**shape)
        arg_names = symbol.list_arguments()
        shapes = dict(zip(arg_names, arg_shapes or []))
    positions = [int(line_length * p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def line(fields):
        row = ""
        for f, p in zip(fields, positions):
            row = (row + str(f))[:p].ljust(p)
        return row

    out = ["_" * line_length, line(header), "=" * line_length]
    total = 0
    for n in nodes:
        if n["op"] == "null":
            name = n["name"]
            cnt = 0
            shp = shapes.get(name, "")
            if name in shapes:
                cnt = 1
                for s in shapes[name]:
                    cnt *= s
            if any(name.endswith(sfx) for sfx in
                   ("weight", "bias", "gamma", "beta", "mean", "var")):
                total += cnt
                out.append(line([f"{name} (Parameter)", shp, cnt, ""]))
            continue
        prevs = ",".join(nodes[i[0]]["name"] for i in n["inputs"][:2])
        out.append(line([f"{n['name']} ({n['op']})", "", 0, prevs]))
    out.append("=" * line_length)
    out.append(f"Total params: {total}")
    out.append("_" * line_length)
    print("\n".join(out))
    return "\n".join(out)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz digraph of the Symbol graph (reference plot_network)."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError(
            "plot_network needs the optional 'graphviz' package (not in the "
            "TPU image); use mx.viz.print_summary for a text summary") from e
    nodes, _ = _sym_nodes(symbol)
    dot = Digraph(name=title, format=save_format)
    for i, n in enumerate(nodes):
        if n["op"] == "null" and hide_weights and n["name"].rsplit("_", 1)[-1] in (
                "weight", "bias", "gamma", "beta", "mean", "var"):
            continue
        dot.node(str(i), f"{n['name']}\n{n['op']}" if n["op"] != "null"
                 else n["name"])
    for i, n in enumerate(nodes):
        for (src, _o, _v) in n.get("inputs", []):
            s = nodes[src]
            if s["op"] == "null" and hide_weights and \
                    s["name"].rsplit("_", 1)[-1] in (
                        "weight", "bias", "gamma", "beta", "mean", "var"):
                continue
            dot.edge(str(src), str(i))
    return dot
