"""``mx.viz`` — network visualization (reference
``python/mxnet/visualization.py`` — TBV: print_summary + plot_network).

``print_summary`` is fully supported (text). ``plot_network`` returns a
graphviz Digraph when the optional ``graphviz`` package exists, else raises
ImportError with guidance (graphviz is not in the TPU image).
"""
from __future__ import annotations

import json

__all__ = ["print_summary", "plot_network"]


def _sym_nodes(symbol):
    conf = json.loads(symbol.tojson())
    return conf["nodes"], conf.get("heads", [])


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Layer-table summary of a Symbol graph (reference mx.viz.print_summary).

    Shapes come from the same analysis engine the linter and
    ``Symbol.infer_shape`` use (``analysis/shape_infer.py``), so the table,
    the lint report, and bind-time errors always agree — including per-op
    output shapes, which the reference table also shows.
    """
    topo = symbol._topo()
    node_shapes = {}
    shapes = {}
    if shape is not None:
        from .analysis.shape_infer import infer_graph

        res = infer_graph(symbol, {k: tuple(v) for k, v in shape.items()})
        shapes = res.shapes
        node_shapes = {id(n): res.node_out.get(id(n)) for n in topo}
    positions = [int(line_length * p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def line(fields):
        row = ""
        for f, p in zip(fields, positions):
            row = (row + str(f))[:p].ljust(p)
        return row

    out = ["_" * line_length, line(header), "=" * line_length]
    total = 0
    for n in topo:
        if n._op is None:
            name = n._name
            cnt = 0
            shp = shapes.get(name, "")
            if name in shapes:
                cnt = 1
                for s in shapes[name]:
                    cnt *= s
            if any(name.endswith(sfx) for sfx in
                   ("weight", "bias", "gamma", "beta", "mean", "var")):
                total += cnt
                out.append(line([f"{name} (Parameter)", shp, cnt, ""]))
            continue
        if n._op == "_group":
            continue
        out_shp = node_shapes.get(id(n), "")
        if isinstance(out_shp, list):
            out_shp = ", ".join(str(s) for s in out_shp)
        prevs = ",".join(i._base()._name for i in n._inputs[:2])
        out.append(line([f"{n._name} ({n._op})", out_shp or "", 0, prevs]))
    out.append("=" * line_length)
    out.append(f"Total params: {total}")
    out.append("_" * line_length)
    print("\n".join(out))
    return "\n".join(out)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz digraph of the Symbol graph (reference plot_network)."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError(
            "plot_network needs the optional 'graphviz' package (not in the "
            "TPU image); use mx.viz.print_summary for a text summary") from e
    nodes, _ = _sym_nodes(symbol)
    dot = Digraph(name=title, format=save_format)
    for i, n in enumerate(nodes):
        if n["op"] == "null" and hide_weights and n["name"].rsplit("_", 1)[-1] in (
                "weight", "bias", "gamma", "beta", "mean", "var"):
            continue
        dot.node(str(i), f"{n['name']}\n{n['op']}" if n["op"] != "null"
                 else n["name"])
    for i, n in enumerate(nodes):
        for (src, _o, _v) in n.get("inputs", []):
            s = nodes[src]
            if s["op"] == "null" and hide_weights and \
                    s["name"].rsplit("_", 1)[-1] in (
                        "weight", "bias", "gamma", "beta", "mean", "var"):
                continue
            dot.edge(str(src), str(i))
    return dot
