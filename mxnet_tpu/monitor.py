"""``mx.monitor.Monitor`` — tap intermediate outputs during training.

Reference: ``python/mxnet/monitor.py`` (executor output callback — TBV,
SURVEY.md §5.5). Here the tap installs over Executor forward results and
Gluon forward hooks — and, unlike round 2, it works **inside jitted
programs**: when a hook fires during tracing (hybridize / CachedOp), the
stat is computed in-graph and shipped out through ``jax.debug.callback``,
so every compiled replay still reports; activation gating happens at
runtime inside the callback.

Monitor is now a thin adapter over the training-health plane
(obs/health.py): ``toc()`` moves every watched stat to host through the
plane's ONE shared batched-fetch primitive (no private stat-fetch path to
drift), and scalar stats land in the ``health.monitor.<tensor>`` gauges —
tensor health reads beside loss/grad-norm telemetry in one registry
(docs/OBSERVABILITY.md "Training health").
"""
from __future__ import annotations

import logging
import re

import numpy as np

from .ndarray import NDArray

__all__ = ["Monitor"]


def _default_stat(x):
    """abs().mean() expressed over NDArray ops so it traces under jit (the
    round-2 version called asnumpy(), which explodes on tracers)."""
    if isinstance(x, NDArray):
        return x.abs().mean()
    return np.abs(np.asarray(x)).mean()


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = interval
        self.stat_func = stat_func or _default_stat
        self.pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue = []
        self._gluon_handles = []

    # -- symbolic path ---------------------------------------------------
    def install(self, exe):
        """Attach to an Executor: stats collected from outputs each toc."""
        exe._monitor = self
        return exe

    def install_gluon(self, block):
        """Attach forward hooks to every child of a Gluon block."""

        def hook(blk, inputs, output):
            import jax

            name = blk.name
            if not self.pattern.match(name):
                return
            outs = output if isinstance(output, (list, tuple)) else [output]
            for i, o in enumerate(outs):
                if not isinstance(o, NDArray):
                    continue
                tag = f"{name}_output{i}"
                if isinstance(o._data, jax.core.Tracer):
                    # tracing (CachedOp/jit): compute the stat in-graph and
                    # emit it at every replay; gate on self.activated at
                    # RUNTIME (trace-time gating would bake the decision in).
                    # NOTE: this bakes the stat + a host callback into the
                    # compiled program for its lifetime — uninstall_gluon()
                    # and re-hybridize to drop the overhead.
                    try:
                        s = self.stat_func(o)
                    except Exception:
                        # custom stat funcs that need concrete values
                        # (asnumpy etc.) cannot tap inside jit — skip this
                        # layer rather than poison the trace
                        import warnings

                        warnings.warn(
                            f"Monitor: stat_func is not traceable; {tag} "
                            "not monitored inside the jitted program")
                        continue
                    val = s._data if isinstance(s, NDArray) else s

                    def emit(v, _tag=tag):
                        if self.activated:
                            self.queue.append((self.step, _tag, np.asarray(v)))

                    jax.debug.callback(emit, val)
                elif self.activated:
                    # stats stay device-resident here; toc() fetches every
                    # pending stat with ONE batched jax.device_get instead
                    # of one blocking asnumpy per tensor per batch
                    self.queue.append((self.step, tag, self.stat_func(o)))

        def walk(b):
            b.register_forward_hook(hook)
            self._gluon_handles.append((b, hook))
            for c in b._children.values():
                walk(c)

        walk(block)
        return block

    def uninstall_gluon(self):
        """Remove installed hooks. A net hybridized while monitored keeps
        the baked-in taps until its CachedOp re-traces (call hybridize()
        again to force that)."""
        for b, h in self._gluon_handles:
            try:
                b._forward_hooks.remove(h)
            except (ValueError, AttributeError):
                pass
        self._gluon_handles = []

    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []
        self.step += 1

    def toc(self, exe=None):
        import jax

        # flush in-flight debug callbacks before draining the queue — on an
        # async backend a compiled replay's emits may still be in transit
        jax.effects_barrier()
        if not self.activated:
            return []
        if exe is not None:
            for name, out in zip(exe._symbol.list_outputs(), exe.outputs):
                if self.pattern.match(name):
                    self.queue.append((self.step, name, self.stat_func(out)))
        self.activated = False
        res = list(self.queue)
        self.queue = []
        # ONE device→host transfer for ALL watched stats through the
        # health plane's shared batched-fetch (obs/health.py — the same
        # primitive the sentinel's sampled step uses; Monitor keeps no
        # private stat-fetch path)
        from .obs import health as _health

        device_idx = [i for i, (_, _, v) in enumerate(res)
                      if isinstance(v, NDArray)]
        if device_idx:
            fetched = _health.batched_fetch([res[i][2] for i in device_idx])
            for i, val in zip(device_idx, fetched):
                step, tag, _ = res[i]
                res[i] = (step, tag, np.asarray(val))
        if self.sort:
            res.sort(key=lambda t: t[1])
        # scalar stats land in the health plane's gauges, so tensor health
        # reads beside loss/grad-norm telemetry (docs/OBSERVABILITY.md)
        from . import obs

        if obs.enabled():
            for step, tag, val in res:
                arr = np.asarray(val)
                if arr.size == 1:
                    obs.set_gauge("health.monitor." + tag,
                                  float(arr.reshape(())[()]))
        return res

    def toc_print(self, exe=None):
        for step, name, value in self.toc(exe):
            logging.info("Batch: %7d %30s %s", step, name, value)
