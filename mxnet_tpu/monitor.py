"""``mx.monitor.Monitor`` — tap intermediate outputs during training.

Reference: ``python/mxnet/monitor.py`` (executor output callback — TBV,
SURVEY.md §5.5). Here the tap installs over Executor forward results and
Gluon forward hooks — and, unlike round 2, it works **inside jitted
programs**: when a hook fires during tracing (hybridize / CachedOp), the
stat is computed in-graph and shipped out through ``jax.debug.callback``,
so every compiled replay still reports; activation gating happens at
runtime inside the callback.
"""
from __future__ import annotations

import logging
import re

import numpy as np

from .ndarray import NDArray

__all__ = ["Monitor"]


def _default_stat(x):
    """abs().mean() expressed over NDArray ops so it traces under jit (the
    round-2 version called asnumpy(), which explodes on tracers)."""
    if isinstance(x, NDArray):
        return x.abs().mean()
    return np.abs(np.asarray(x)).mean()


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = interval
        self.stat_func = stat_func or _default_stat
        self.pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue = []
        self._gluon_handles = []

    # -- symbolic path ---------------------------------------------------
    def install(self, exe):
        """Attach to an Executor: stats collected from outputs each toc."""
        exe._monitor = self
        return exe

    def install_gluon(self, block):
        """Attach forward hooks to every child of a Gluon block."""

        def hook(blk, inputs, output):
            import jax

            name = blk.name
            if not self.pattern.match(name):
                return
            outs = output if isinstance(output, (list, tuple)) else [output]
            for i, o in enumerate(outs):
                if not isinstance(o, NDArray):
                    continue
                tag = f"{name}_output{i}"
                if isinstance(o._data, jax.core.Tracer):
                    # tracing (CachedOp/jit): compute the stat in-graph and
                    # emit it at every replay; gate on self.activated at
                    # RUNTIME (trace-time gating would bake the decision in)
                    s = self.stat_func(o)
                    val = s._data if isinstance(s, NDArray) else s

                    def emit(v, _tag=tag):
                        if self.activated:
                            self.queue.append((self.step, _tag, np.asarray(v)))

                    jax.debug.callback(emit, val)
                elif self.activated:
                    s = self.stat_func(o)
                    if isinstance(s, NDArray):
                        s = s.asnumpy()
                    self.queue.append((self.step, tag, s))

        def walk(b):
            b.register_forward_hook(hook)
            for c in b._children.values():
                walk(c)

        walk(block)
        return block

    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []
        self.step += 1

    def toc(self, exe=None):
        import jax

        # flush in-flight debug callbacks before draining the queue — on an
        # async backend a compiled replay's emits may still be in transit
        jax.effects_barrier()
        if not self.activated:
            return []
        if exe is not None:
            for name, out in zip(exe._symbol.list_outputs(), exe.outputs):
                if self.pattern.match(name):
                    s = self.stat_func(out)
                    if isinstance(s, NDArray):
                        s = s.asnumpy()
                    self.queue.append((self.step, name, s))
        self.activated = False
        res = list(self.queue)
        if self.sort:
            res.sort(key=lambda t: t[1])
        self.queue = []
        return res

    def toc_print(self, exe=None):
        for step, name, value in self.toc(exe):
            logging.info("Batch: %7d %30s %s", step, name, value)
