"""``mx.monitor.Monitor`` — tap intermediate outputs during training.

Reference: ``python/mxnet/monitor.py`` (executor output callback — TBV,
SURVEY.md §5.5). Here the tap installs over Executor forward results and
Gluon forward hooks.
"""
from __future__ import annotations

import logging
import re

import numpy as np

from .ndarray import NDArray

__all__ = ["Monitor"]


def _default_stat(x: np.ndarray):
    return np.abs(x).mean()


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = interval
        self.stat_func = stat_func or (lambda x: _default_stat(x.asnumpy()
                                                               if isinstance(x, NDArray)
                                                               else np.asarray(x)))
        self.pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue = []
        self._gluon_handles = []

    # -- symbolic path ---------------------------------------------------
    def install(self, exe):
        """Attach to an Executor: stats collected from outputs each toc."""
        exe._monitor = self
        return exe

    def install_gluon(self, block):
        """Attach forward hooks to every child of a Gluon block."""

        def hook(blk, inputs, output):
            if not self.activated:
                return
            name = blk.name
            if self.pattern.match(name):
                outs = output if isinstance(output, (list, tuple)) else [output]
                for i, o in enumerate(outs):
                    if isinstance(o, NDArray):
                        self.queue.append((self.step, f"{name}_output{i}",
                                           self.stat_func(o)))

        def walk(b):
            b.register_forward_hook(hook)
            for c in b._children.values():
                walk(c)

        walk(block)
        return block

    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []
        self.step += 1

    def toc(self, exe=None):
        if not self.activated:
            return []
        if exe is not None:
            for name, out in zip(exe._symbol.list_outputs(), exe.outputs):
                if self.pattern.match(name):
                    self.queue.append((self.step, name, self.stat_func(out)))
        self.activated = False
        res = list(self.queue)
        if self.sort:
            res.sort(key=lambda t: t[1])
        self.queue = []
        return res

    def toc_print(self, exe=None):
        for step, name, value in self.toc(exe):
            logging.info("Batch: %7d %30s %s", step, name, value)
