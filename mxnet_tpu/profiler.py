"""``mx.profiler`` over jax.profiler.

Reference: ``src/profiler/`` + ``python/mxnet/profiler.py`` (TBV —
SURVEY.md §5.1). The reference hooks the engine and dumps chrome-trace
JSON; here XLA's profiler produces an XPlane/perfetto trace (viewable in
TensorBoard/Perfetto, superset of the chrome-trace view). Per-op
attribution inside jitted programs comes from ``named_scope`` annotations
(``mx.profiler.scope``).
"""
from __future__ import annotations

import contextlib
import os
import warnings

import jax

__all__ = ["set_config", "set_state", "dump", "dumps", "pause", "resume",
           "scope", "Profiler", "DispatchCounts", "count_dispatches",
           "count_dispatch", "counting_dispatches"]

_config = {"filename": "profile.json", "profile_all": False, "aggregate_stats": False}
_state = {"running": False, "dir": None}

# ---------------------------------------------------------------------------
# Aggregate per-op statistics (reference MXAggregateProfileStatsPrint /
# src/profiler/aggregate_stats.cc — TBV). The engine hook becomes a timing
# wrapper at the eager dispatch choke point (ndarray.invoke): active only
# while the profiler runs with aggregate_stats=True, because accurate
# per-op timing must block on the async dispatch (NaiveEngine-style).
# ---------------------------------------------------------------------------

_agg: dict = {}


def aggregate_active() -> bool:
    return _state["running"] and bool(_config.get("aggregate_stats"))


# ---------------------------------------------------------------------------
# Dispatch counting — the honest "how many compiled device programs did this
# step execute" metric behind tools/profile_step.py and the perf tests.
# Hook points: ndarray.invoke (each eager op is one compiled execution),
# the fused update engine, Executor forward/backward, CachedOp calls, and
# NDArray.asnumpy (device→host transfers).  Works on any backend, CPU
# included — it counts dispatches, not device time.
#
# Storage is the obs metrics registry (``dispatch.*`` counters): every
# count_dispatch() call feeds the registry, and a count_dispatches() region
# is a before/after delta over those counters.  ONE choke point feeds both
# the region view and the global metrics, so the two cannot drift
# (docs/OBSERVABILITY.md).  Counting activates when a region is open OR
# when obs telemetry is enabled; otherwise the call-site guard
# (counting_dispatches()) keeps the hot path a no-op, exactly as before.
# ---------------------------------------------------------------------------

from . import obs as _obs

_DISPATCH_KINDS = ("compiled", "eager_ops", "h2d", "d2h")


class DispatchCounts:
    """Counters for one measured region."""

    __slots__ = ("compiled", "eager_ops", "h2d", "d2h")

    def __init__(self):
        self.compiled = 0   # jit-compiled program executions (engine/executor)
        self.eager_ops = 0  # eager op dispatches (each is a compiled program)
        self.h2d = 0        # host→device transfers
        self.d2h = 0        # device→host transfers (asnumpy/asscalar)

    @property
    def total_compiled(self):
        return self.compiled + self.eager_ops

    def as_dict(self):
        return {"compiled_calls": self.compiled, "eager_ops": self.eager_ops,
                "total_compiled": self.total_compiled,
                "h2d_transfers": self.h2d, "d2h_transfers": self.d2h}

    def __repr__(self):
        return f"DispatchCounts({self.as_dict()})"


_open_regions = 0  # count_dispatches() nesting depth


def counting_dispatches() -> bool:
    """Call-site guard: True while a count_dispatches() region is open or
    obs telemetry is enabled (the registry then accumulates globally)."""
    return _open_regions > 0 or _obs.enabled()


def count_dispatch(kind: str, n: int = 1) -> None:
    _obs.metrics.registry.counter("dispatch." + kind).inc(n)


def _dispatch_totals() -> dict:
    reg = _obs.metrics.registry
    return {k: reg.counter("dispatch." + k).value for k in _DISPATCH_KINDS}


@contextlib.contextmanager
def count_dispatches():
    """Count compiled executions / transfers in a region::

        with profiler.count_dispatches() as c:
            trainer.step(batch_size)
        assert c.total_compiled <= 2

    The yielded counts are finalized when the region exits (they are a
    delta over the registry's ``dispatch.*`` counters).
    """
    global _open_regions
    c = DispatchCounts()
    before = _dispatch_totals()
    _open_regions += 1
    try:
        yield c
    finally:
        _open_regions -= 1
        after = _dispatch_totals()
        for k in _DISPATCH_KINDS:
            setattr(c, k, after[k] - before[k])


def record_op(name: str, seconds: float) -> None:
    ent = _agg.get(name)
    if ent is None:
        _agg[name] = [1, seconds, seconds, seconds]
    else:
        ent[0] += 1
        ent[1] += seconds
        ent[2] = min(ent[2], seconds)
        ent[3] = max(ent[3], seconds)


def reset_stats() -> None:
    _agg.clear()


def set_config(**kwargs):
    """profile_{all,symbolic,imperative,memory,api}=..., filename=... —
    reference kwargs accepted; XLA traces everything on the device timeline."""
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    """Start/stop the XLA trace. Idempotent both ways: a second "run" (or
    a "run" racing a trace some other code started directly through
    ``jax.profiler``) must never surface JAX's deep "trace already
    started" RuntimeError to a training loop — we adopt the active trace
    instead. Start/stop land as tagged obs events so profiler windows are
    visible inside the span timeline (docs/OBSERVABILITY.md)."""
    if state in ("run", 1):
        if _state["running"]:
            return  # double start: the window is already open
        logdir = _config.get("filename", "profile.json")
        trace_dir = logdir if os.path.isdir(logdir) else \
            (os.path.splitext(logdir)[0] + "_trace")
        os.makedirs(trace_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(trace_dir)
        except RuntimeError as e:
            # adopt ONLY the double-start case; any other RuntimeError is
            # a genuine failure the caller must see (masking it would
            # report a phantom profile window)
            if "already" not in str(e).lower():
                raise
            warnings.warn(f"jax profiler already tracing ({e}); adopting "
                          "the active trace window", stacklevel=2)
        _state.update(running=True, dir=trace_dir)
        _obs.event("profiler.start_trace", dir=trace_dir)
    elif state in ("stop", 0):
        if not _state["running"]:
            return  # double stop: nothing open
        try:
            jax.profiler.stop_trace()
        except RuntimeError as e:  # jax's trace died under us — still ours
            warnings.warn(f"jax profiler stop: {e}", stacklevel=2)
        _state["running"] = False
        _obs.event("profiler.stop_trace", dir=_state.get("dir"))
    else:
        raise ValueError(f"invalid profiler state {state!r}")


def pause(profile_process="worker"):
    if _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False


resume = None  # set below


def _resume(profile_process="worker"):
    set_state("run")


resume = _resume


def dump(finished=True, profile_process="worker"):
    """Finish tracing; the trace directory holds the XPlane/perfetto dump."""
    set_state("stop")
    return _state.get("dir")


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Aggregate per-op stats table (reference `profiler.dumps()` /
    MXAggregateProfileStatsPrint analog) + the trace dir pointer."""
    lines = [f"profiler trace dir: {_state.get('dir')}"]
    if _agg:
        key_idx = {"total": 1, "count": 0, "min": 2, "max": 3,
                   "avg": None}.get(sort_by, 1)
        items = list(_agg.items())
        if key_idx is None:
            items.sort(key=lambda kv: kv[1][1] / kv[1][0], reverse=not ascending)
        else:
            items.sort(key=lambda kv: kv[1][key_idx], reverse=not ascending)
        lines.append("")
        lines.append("Profile Statistics (eager op dispatch):")
        lines.append(f"{'Name':<32}{'Count':>8}{'Total(ms)':>12}"
                     f"{'Min(ms)':>10}{'Max(ms)':>10}{'Avg(ms)':>10}")
        for name, (cnt, tot, mn, mx) in items:
            lines.append(f"{name:<32}{cnt:>8}{tot * 1e3:>12.3f}"
                         f"{mn * 1e3:>10.3f}{mx * 1e3:>10.3f}"
                         f"{tot / cnt * 1e3:>10.3f}")
    if reset:
        reset_stats()
    return "\n".join(lines)


@contextlib.contextmanager
def scope(name: str):
    """Named sub-scope for per-op attribution inside jit (reference profiler
    scopes / operator names in the engine timeline)."""
    with jax.named_scope(name):
        yield


class Profiler:
    """Context manager: profile a region."""

    def __init__(self, filename="profile", **kwargs):
        set_config(filename=filename, **kwargs)

    def __enter__(self):
        set_state("run")
        return self

    def __exit__(self, *a):
        dump()
