"""``mx.profiler`` over jax.profiler.

Reference: ``src/profiler/`` + ``python/mxnet/profiler.py`` (TBV —
SURVEY.md §5.1). The reference hooks the engine and dumps chrome-trace
JSON; here XLA's profiler produces an XPlane/perfetto trace (viewable in
TensorBoard/Perfetto, superset of the chrome-trace view). Per-op
attribution inside jitted programs comes from ``named_scope`` annotations
(``mx.profiler.scope``).
"""
from __future__ import annotations

import contextlib
import os
import warnings

import jax

__all__ = ["set_config", "set_state", "dump", "dumps", "pause", "resume",
           "scope", "Profiler"]

_config = {"filename": "profile.json", "profile_all": False, "aggregate_stats": False}
_state = {"running": False, "dir": None}

# ---------------------------------------------------------------------------
# Aggregate per-op statistics (reference MXAggregateProfileStatsPrint /
# src/profiler/aggregate_stats.cc — TBV). The engine hook becomes a timing
# wrapper at the eager dispatch choke point (ndarray.invoke): active only
# while the profiler runs with aggregate_stats=True, because accurate
# per-op timing must block on the async dispatch (NaiveEngine-style).
# ---------------------------------------------------------------------------

_agg: dict = {}


def aggregate_active() -> bool:
    return _state["running"] and bool(_config.get("aggregate_stats"))


def record_op(name: str, seconds: float) -> None:
    ent = _agg.get(name)
    if ent is None:
        _agg[name] = [1, seconds, seconds, seconds]
    else:
        ent[0] += 1
        ent[1] += seconds
        ent[2] = min(ent[2], seconds)
        ent[3] = max(ent[3], seconds)


def reset_stats() -> None:
    _agg.clear()


def set_config(**kwargs):
    """profile_{all,symbolic,imperative,memory,api}=..., filename=... —
    reference kwargs accepted; XLA traces everything on the device timeline."""
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    if state in ("run", 1):
        if not _state["running"]:
            logdir = _config.get("filename", "profile.json")
            trace_dir = logdir if os.path.isdir(logdir) else \
                (os.path.splitext(logdir)[0] + "_trace")
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            _state.update(running=True, dir=trace_dir)
    elif state in ("stop", 0):
        if _state["running"]:
            jax.profiler.stop_trace()
            _state["running"] = False
    else:
        raise ValueError(f"invalid profiler state {state!r}")


def pause(profile_process="worker"):
    if _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False


resume = None  # set below


def _resume(profile_process="worker"):
    set_state("run")


resume = _resume


def dump(finished=True, profile_process="worker"):
    """Finish tracing; the trace directory holds the XPlane/perfetto dump."""
    set_state("stop")
    return _state.get("dir")


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    """Aggregate per-op stats table (reference `profiler.dumps()` /
    MXAggregateProfileStatsPrint analog) + the trace dir pointer."""
    lines = [f"profiler trace dir: {_state.get('dir')}"]
    if _agg:
        key_idx = {"total": 1, "count": 0, "min": 2, "max": 3,
                   "avg": None}.get(sort_by, 1)
        items = list(_agg.items())
        if key_idx is None:
            items.sort(key=lambda kv: kv[1][1] / kv[1][0], reverse=not ascending)
        else:
            items.sort(key=lambda kv: kv[1][key_idx], reverse=not ascending)
        lines.append("")
        lines.append("Profile Statistics (eager op dispatch):")
        lines.append(f"{'Name':<32}{'Count':>8}{'Total(ms)':>12}"
                     f"{'Min(ms)':>10}{'Max(ms)':>10}{'Avg(ms)':>10}")
        for name, (cnt, tot, mn, mx) in items:
            lines.append(f"{name:<32}{cnt:>8}{tot * 1e3:>12.3f}"
                         f"{mn * 1e3:>10.3f}{mx * 1e3:>10.3f}"
                         f"{tot / cnt * 1e3:>10.3f}")
    if reset:
        reset_stats()
    return "\n".join(lines)


@contextlib.contextmanager
def scope(name: str):
    """Named sub-scope for per-op attribution inside jit (reference profiler
    scopes / operator names in the engine timeline)."""
    with jax.named_scope(name):
        yield


class Profiler:
    """Context manager: profile a region."""

    def __init__(self, filename="profile", **kwargs):
        set_config(filename=filename, **kwargs)

    def __enter__(self):
        set_state("run")
        return self

    def __exit__(self, *a):
        dump()
