"""``mx.profiler`` over jax.profiler.

Reference: ``src/profiler/`` + ``python/mxnet/profiler.py`` (TBV —
SURVEY.md §5.1). The reference hooks the engine and dumps chrome-trace
JSON; here XLA's profiler produces an XPlane/perfetto trace (viewable in
TensorBoard/Perfetto, superset of the chrome-trace view). Per-op
attribution inside jitted programs comes from ``named_scope`` annotations
(``mx.profiler.scope``).
"""
from __future__ import annotations

import contextlib
import os
import warnings

import jax

__all__ = ["set_config", "set_state", "dump", "dumps", "pause", "resume",
           "scope", "Profiler"]

_config = {"filename": "profile.json", "profile_all": False, "aggregate_stats": False}
_state = {"running": False, "dir": None}


def set_config(**kwargs):
    """profile_{all,symbolic,imperative,memory,api}=..., filename=... —
    reference kwargs accepted; XLA traces everything on the device timeline."""
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    if state in ("run", 1):
        if not _state["running"]:
            logdir = _config.get("filename", "profile.json")
            trace_dir = logdir if os.path.isdir(logdir) else \
                (os.path.splitext(logdir)[0] + "_trace")
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            _state.update(running=True, dir=trace_dir)
    elif state in ("stop", 0):
        if _state["running"]:
            jax.profiler.stop_trace()
            _state["running"] = False
    else:
        raise ValueError(f"invalid profiler state {state!r}")


def pause(profile_process="worker"):
    if _state["running"]:
        jax.profiler.stop_trace()
        _state["running"] = False


resume = None  # set below


def _resume(profile_process="worker"):
    set_state("run")


resume = _resume


def dump(finished=True, profile_process="worker"):
    """Finish tracing; the trace directory holds the XPlane/perfetto dump."""
    set_state("stop")
    return _state.get("dir")


def dumps(reset=False, format="table", sort_by="total", ascending=False):
    return f"profiler trace dir: {_state.get('dir')}"


@contextlib.contextmanager
def scope(name: str):
    """Named sub-scope for per-op attribution inside jit (reference profiler
    scopes / operator names in the engine timeline)."""
    with jax.named_scope(name):
        yield


class Profiler:
    """Context manager: profile a region."""

    def __init__(self, filename="profile", **kwargs):
        set_config(filename=filename, **kwargs)

    def __enter__(self):
        set_state("run")
        return self

    def __exit__(self, *a):
        dump()
