"""``mx.sym.contrib`` — contrib ops in the symbolic frontend (reference
python/mxnet/symbol/contrib.py; SSD symbol code calls
``sym.contrib.MultiBoxPrior`` etc.)."""
from __future__ import annotations

from ..ops import has_op
from .symbol import _make_symbol_op


def __getattr__(name: str):
    for cand in (f"_contrib_{name}", name):
        if has_op(cand):
            fn = _make_symbol_op(cand)
            globals()[name] = fn
            return fn
    raise AttributeError(f"no contrib symbol operator {name!r}")
