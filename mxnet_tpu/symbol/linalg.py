"""``mx.sym.linalg`` — linear-algebra ops in the symbolic frontend
(reference python/mxnet/symbol/linalg.py)."""
from __future__ import annotations

from ..ops import has_op
from .symbol import _make_symbol_op


def __getattr__(name: str):
    for cand in (f"_linalg_{name}", f"linalg_{name}", name):
        if has_op(cand):
            fn = _make_symbol_op(cand)
            globals()[name] = fn
            return fn
    raise AttributeError(f"no linalg symbol operator {name!r}")
