"""``mx.sym`` — the symbolic (lazy graph) frontend.

Reference: ``python/mxnet/symbol/`` over NNVM (SURVEY.md §2.1 L5, §2.3).
"""
from .symbol import (Symbol, Variable, var, Group, load, load_json,  # noqa: F401
                     zeros, ones, invoke_fn)

from ..ops import get_op, has_op, list_ops
from .symbol import _make_symbol_op


def __getattr__(name):
    if name in ("contrib", "image", "random", "linalg"):
        import importlib

        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    if has_op(name):
        fn = _make_symbol_op(name)
        globals()[name] = fn
        return fn
    raise AttributeError(f"module 'mxnet_tpu.symbol' has no attribute {name!r}")


def __dir__():
    return sorted(set(list(globals()) + list_ops()))
