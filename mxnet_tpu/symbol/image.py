"""``mx.sym.image`` — image ops in the symbolic frontend (reference
python/mxnet/symbol/image.py over the ``_image_*`` registry names)."""
from __future__ import annotations

from ..ops import has_op
from .symbol import _make_symbol_op


def __getattr__(name: str):
    cand = f"_image_{name}"
    if has_op(cand):
        fn = _make_symbol_op(cand)
        globals()[name] = fn
        return fn
    raise AttributeError(f"no image symbol operator {name!r}")
