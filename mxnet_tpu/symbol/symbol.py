"""Symbol — lazy computation graph.

Reference: ``python/mxnet/symbol/symbol.py`` + ``nnvm::Symbol/Graph``
(TBV — SURVEY.md §2.1 L5). TPU redesign: the graph is a plain Python DAG;
"binding" compiles it through ``jax.jit`` (the executor), replacing NNVM's
pass pipeline (InferShape/PlanMemory/…) with XLA's — shape inference is
``jax.eval_shape`` over the same pure op functions the imperative API uses.

Missing tensor inputs auto-create Variables named ``{name}_{arg}`` (the
reference's behavior that makes ``Module.init_params`` work); ``moving_*``
args become auxiliary states.
"""
from __future__ import annotations

import inspect
import json
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..ops import get_op, has_op
from ..ops.registry import OpDef, coerce_kwargs

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json", "zeros",
           "ones", "invoke_fn"]

# argument names treated as tensor inputs when inferring op signatures
_TENSOR_ARGS = {
    "data", "weight", "bias", "gamma", "beta", "moving_mean", "moving_var",
    "running_mean", "running_var", "lhs", "rhs", "condition", "x", "y",
    "label", "grad", "indices", "index", "parameters", "state", "state_cell",
    "sequence_length", "mean", "var", "mom", "a", "b", "loss", "value",
    "mask", "anchors", "cls_pred", "loc_pred",
}
# inputs that are auxiliary (not trained, updated by forward)
_AUX_ARGS = {"moving_mean", "moving_var", "running_mean", "running_var"}


class _NameManager(threading.local):
    def __init__(self):
        self.counters: Dict[str, int] = {}

    def get(self, hint: str) -> str:
        n = self.counters.get(hint, 0)
        self.counters[hint] = n + 1
        return f"{hint}{n}"


_NAMES = _NameManager()


def op_input_names(opdef: OpDef) -> List[str]:
    """Tensor-input argument names of an op, in signature order.

    ``ndarray_inputs="*"`` marks a variadic op (``def op(*data, ...)``):
    positional symbols fill the slots, so the single placeholder name is
    only used when an input must be auto-created.
    """
    if opdef.ndarray_inputs == "*":
        return ["data"]
    if opdef.ndarray_inputs:
        return list(opdef.ndarray_inputs)
    names = []
    try:
        sig = inspect.signature(opdef.fn)
    except (ValueError, TypeError):
        return ["data"]
    for p in sig.parameters.values():
        if p.kind not in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY):
            break
        if p.default is inspect.Parameter.empty or p.name in _TENSOR_ARGS:
            names.append(p.name)
        else:
            break
    return names or ["data"]


class Symbol:
    """One graph node (possibly multi-output); ``_index`` selects an output."""

    def __init__(self, op: Optional[str], name: str, inputs: List["Symbol"],
                 attrs: Dict[str, Any], index: Optional[int] = None):
        self._op = op          # None => variable
        self._name = name
        self._inputs = inputs
        self._attrs = dict(attrs)
        self._index = index

    # ------------------------------------------------------------- naming
    @property
    def name(self):
        if self._index is not None:
            return f"{self._name}_output{self._index}"
        return self._name

    def attr(self, key):
        v = self._attrs.get(key)
        if v is None:  # AttrScope-injected attrs are dunder-keyed
            v = self._attrs.get(f"__{key}__")
        return v

    def list_attr(self):
        return {k: str(v) for k, v in self._attrs.items()}

    # ---------------------------------------------------------- traversal
    def _topo(self) -> List["Symbol"]:
        seen: Dict[int, Symbol] = {}
        order: List[Symbol] = []

        def visit(node: "Symbol"):
            base = node._base()
            if id(base) in seen:
                return
            seen[id(base)] = base
            for i in base._inputs:
                visit(i)
            order.append(base)

        visit(self)
        return order

    def _base(self) -> "Symbol":
        return self if self._index is None else self._inputs[0]

    def get_internals(self) -> "Symbol":
        return Group(self._topo())

    def list_arguments(self) -> List[str]:
        return [n._name for n in self._topo()
                if n._op is None and not n._attrs.get("__aux__")]

    def list_auxiliary_states(self) -> List[str]:
        return [n._name for n in self._topo()
                if n._op is None and n._attrs.get("__aux__")]

    def list_inputs(self) -> List[str]:
        return [n._name for n in self._topo() if n._op is None]

    def list_outputs(self) -> List[str]:
        if self._op == "_group":
            out = []
            for s in self._inputs:
                out.extend(s.list_outputs())
            return out
        if self._index is not None:
            return [self.name]
        n = self._n_outputs()
        if n == 1:
            return [f"{self._name}_output"]
        return [f"{self._name}_output{i}" for i in range(n)]

    def _n_outputs(self) -> int:
        if self._op is None:
            return 1
        if self._op == "_group":
            return len(self.list_outputs())
        if self._index is not None:
            return 1
        opdef = getattr(self, "_opdef", None) or get_op(self._op)
        try:
            return opdef.n_out(coerce_kwargs(dict(self._attrs))) or 1
        except Exception:
            return 1

    @property
    def num_outputs(self):
        return self._n_outputs()

    def __getitem__(self, idx):
        if isinstance(idx, str):
            names = self.list_outputs()
            idx = names.index(idx)
        if self._op == "_group":
            return self._inputs[idx]
        if self._n_outputs() == 1 and idx == 0:
            return self
        return Symbol(self._op, self._name, [self], {}, index=idx)

    def __iter__(self):
        return iter(self[i] for i in range(len(self.list_outputs())))

    def __len__(self):
        return len(self.list_outputs())

    @property
    def outputs(self):
        return [self[i] for i in range(len(self.list_outputs()))]

    # ---------------------------------------------------------- arithmetic
    def _binop(self, op, other, swap=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if swap else (self, other)
            return _apply_op(op, [a, b], {})
        scalar_ops = {"broadcast_add": "_plus_scalar", "broadcast_sub":
                      "_rminus_scalar" if swap else "_minus_scalar",
                      "broadcast_mul": "_mul_scalar",
                      "broadcast_div": "_rdiv_scalar" if swap else "_div_scalar",
                      "broadcast_power": "_rpower_scalar" if swap else "_power_scalar"}
        sop = scalar_ops.get(op)
        if sop and has_op(sop):
            return _apply_op(sop, [self], {"scalar": other})
        raise TypeError(f"unsupported operand for {op}: {type(other)}")

    def __add__(self, o):
        return self._binop("broadcast_add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop("broadcast_sub", o)

    def __rsub__(self, o):
        return self._binop("broadcast_sub", o, swap=True)

    def __mul__(self, o):
        return self._binop("broadcast_mul", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop("broadcast_div", o)

    def __rtruediv__(self, o):
        return self._binop("broadcast_div", o, swap=True)

    def __pow__(self, o):
        return self._binop("broadcast_power", o)

    def __neg__(self):
        return self._binop("broadcast_mul", -1.0)

    # ---------------------------------------------------------- inference
    @property
    def shape(self) -> tuple:
        """Static output shape of this node, via the shared analysis engine.

        Works when every upstream Variable carries a shape hint
        (``Variable(name, shape=...)``) or is an auto-shaped parameter —
        which makes shape-inspecting ``hybrid_forward`` code (``b, s, u =
        x.shape``) traceable symbolically, like concrete shapes inside a
        jax trace. Raises a node-attributed
        :class:`~mxnet_tpu.base.GraphAnalysisError` when under-hinted.
        """
        from ..analysis.shape_infer import infer_graph

        res = infer_graph(self, {}, collect=False, use_hint_cache=True)
        base = self._base()
        s = res.node_out.get(id(base))
        if isinstance(s, list):
            s = s[self._index or 0]
        if s is None:
            from ..base import GraphAnalysisError

            raise GraphAnalysisError(
                f"shape of {self.name!r} is not statically known; give the "
                "input Variables shape hints (Variable(name, shape=...))",
                node=self.name, op=self._op, rule_id="missing-shape")
        return tuple(s)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    # ------------------------------------------------------ method aliases
    # (mirror NDArray's method surface so F-generic hybrid_forward code —
    # x.reshape(...), x.transpose(...) — traces symbolically too)
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _apply_op("reshape", [self], {"shape": shape, **kwargs})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _apply_op("transpose", [self], {"axes": axes or None})

    @property
    def T(self):
        return self.transpose()

    def astype(self, dtype):
        return _apply_op("cast", [self], {"dtype": str(np.dtype(dtype))})

    def flatten(self):
        return _apply_op("Flatten", [self], {})

    def expand_dims(self, axis):
        return _apply_op("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return _apply_op("squeeze", [self], {"axis": axis})

    def slice_axis(self, axis, begin, end):
        return _apply_op("slice_axis", [self],
                         {"axis": axis, "begin": begin, "end": end})

    def sum(self, axis=None, keepdims=False, **kw):
        return _apply_op("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False, **kw):
        return _apply_op("mean", [self], {"axis": axis, "keepdims": keepdims})

    def infer_shape(self, **kwargs):
        """Returns (arg_shapes, out_shapes, aux_shapes) (reference API).
        Parameter shapes are derived from data shapes like the reference's
        InferShape pass (src/nnvm shape inference — TBV)."""
        shapes, out_shapes = infer_shapes(self, kwargs)
        args = self.list_arguments()
        auxs = self.list_auxiliary_states()
        return ([shapes[a] for a in args], out_shapes,
                [shapes[a] for a in auxs])

    def infer_type(self, **kwargs):
        """Returns (arg_types, out_types, aux_types). With enough shape
        hints (Variable(shape=...) or prior infer), dtypes come from the
        same eval_shape engine as infer_shape; otherwise the reference
        default (everything float32) is reported. Failures raise a
        node-attributed :class:`~mxnet_tpu.base.GraphAnalysisError`."""
        args = self.list_arguments()
        auxs = self.list_auxiliary_states()
        try:
            from ..analysis.shape_infer import infer_graph

            res = infer_graph(self, {}, known_dtypes=kwargs or None)
            if all(d is not None for d in res.out_dtypes) and \
                    all(a in res.dtypes for a in args):
                np_t = lambda d: np.dtype(d).type  # noqa: E731
                return ([np_t(res.dtypes[a]) for a in args],
                        [np_t(d) for d in res.out_dtypes],
                        [np_t(res.dtypes[a]) for a in auxs])
        except ValueError as e:
            # not enough shape hints -> reference default; a real graph
            # inconsistency (shape-mismatch) propagates with attribution
            if getattr(e, "rule_id", None) not in (None, "missing-shape"):
                raise
        return ([np.float32] * len(args),
                [np.float32] * len(self.list_outputs()),
                [np.float32] * len(auxs))

    # ----------------------------------------------------------- analysis
    def lint(self, shapes: Optional[Dict[str, tuple]] = None, passes=None,
             **shape_kwargs):
        """Run the static analyzer over this graph (no compilation).

        Returns an :class:`mxnet_tpu.analysis.Report`. Pass input shapes
        (``sym.lint(data=(2, 3, 32, 32))``) to enable the shape/dtype
        pre-flight; without them only structural passes run. ``passes``
        selects a subset by name (see ``mxnet_tpu.analysis.list_passes``).
        """
        from ..analysis import GraphLinter

        return GraphLinter(passes=passes).lint(self, shapes=shapes,
                                               **shape_kwargs)

    # ---------------------------------------------------------- execution
    def simple_bind(self, ctx=None, grad_req="write", lint=None, **shapes):
        from ..executor import Executor

        return Executor(self, ctx=ctx, grad_req=grad_req, shapes=shapes,
                        lint=lint)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, lint=None, **kwargs):
        from ..executor import Executor

        return Executor(self, ctx=ctx, grad_req=grad_req, args=args,
                        args_grad=args_grad, aux_states=aux_states,
                        lint=lint)

    def optimize_for(self, backend, args=None, aux=None, **kwargs):
        """Apply a registered graph pass (reference Symbol.optimize_for /
        subgraph backend API): returns the rewritten Symbol; updated params
        are available on ._optimized_args/._optimized_aux."""
        from ..subgraph import optimize_symbol

        new_sym, new_args, new_aux = optimize_symbol(self, backend, args, aux)
        new_sym._optimized_args = new_args
        new_sym._optimized_aux = new_aux
        return new_sym

    def eval(self, ctx=None, **kwargs):
        exe = self.simple_bind(ctx=ctx, grad_req="null",
                               **{k: v.shape for k, v in kwargs.items()})
        return exe.forward(is_train=False, **kwargs)

    # ------------------------------------------------------------- persist
    def tojson(self) -> str:
        nodes = self._topo()
        idx = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            out_nodes.append({
                "op": "null" if n._op is None else n._op,
                "name": n._name,
                "attrs": {k: str(v) for k, v in n._attrs.items()
                          if not k.startswith("__")},
                "inputs": [[idx[id(i._base())], i._index or 0, 0]
                           for i in n._inputs],
            })
        if self._op == "_group":
            heads = []
            for s in self._inputs:
                heads.append([idx[id(s._base())], s._index or 0, 0])
        else:
            heads = [[idx[id(self._base())], self._index or 0, 0]]
        arg_nodes = [i for i, n in enumerate(nodes) if n._op is None]
        return json.dumps({"nodes": out_nodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": list(range(len(nodes) + 1)),
                           "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10900]}}, indent=2)

    def save(self, fname: str):
        from ..checkpoint.atomic import atomic_write_bytes

        # atomic: checkpoints pair this JSON with .params — a crash must
        # not leave a truncated graph next to valid weights
        atomic_write_bytes(fname, self.tojson().encode("utf-8"))

    def __repr__(self):
        if self._op is None:
            return f"<Symbol {self._name}>"
        return f"<Symbol {self._op}:{self.name}>"


# ---------------------------------------------------------------------------


def _scoped_name(name: Optional[str], hint: str) -> str:
    """Resolve a node name through mx.name scopes (NameManager/Prefix),
    falling back to the module-global counter."""
    if name:
        return name
    from .. import name as name_mod

    mgr = name_mod._STATE.current
    if mgr is not None:
        return mgr.get(None, hint)
    return _NAMES.get(hint)


def _scope_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    """Merge mx.attribute.AttrScope attrs in, dunder-keyed so the executor
    never passes them as op kwargs (read back via Symbol.attr)."""
    from .. import attribute

    cur = attribute.current()
    if not cur:
        return attrs
    merged = {f"__{k}__": v for k, v in cur.items()}
    merged.update(attrs)
    return merged


def _apply_op(op_name: str, sym_inputs: List[Symbol], attrs: Dict[str, Any],
              name: Optional[str] = None) -> Symbol:
    node = Symbol(op_name, _scoped_name(name, op_name.lower().lstrip("_")),
                  sym_inputs, _scope_attrs(attrs))
    return node


def _make_symbol_op(op_name: str):
    opdef = get_op(op_name)

    def sym_op(*args, name=None, attr=None, **kwargs):
        input_names = op_input_names(opdef)
        inputs: List[Optional[Symbol]] = []
        rest = list(args)
        # positional symbols fill input slots in order
        while rest and isinstance(rest[0], Symbol):
            inputs.append(rest.pop(0))
        if rest:
            raise TypeError(f"{op_name}: unexpected positional args {rest}")
        # keyword symbols fill by name
        by_name = {}
        for k in list(kwargs):
            if isinstance(kwargs[k], Symbol):
                by_name[k] = kwargs.pop(k)
        node_name = _scoped_name(name, op_name.lower().lstrip("_"))
        full_inputs: List[Symbol] = list(inputs)
        no_bias = str(kwargs.get("no_bias", False)).lower() == "true"
        if len(inputs) < len(input_names) and (inputs or by_name):
            for i, in_name in enumerate(input_names):
                if i < len(inputs):
                    continue
                if in_name in by_name:
                    full_inputs.append(by_name.pop(in_name))
                else:
                    if in_name == "bias" and no_bias:
                        continue
                    aux = in_name in _AUX_ARGS
                    full_inputs.append(Variable(f"{node_name}_{in_name}",
                                                __aux__=aux))
        if by_name:
            raise TypeError(f"{op_name}: unknown symbol kwargs {list(by_name)}")
        return _apply_op(op_name, full_inputs, kwargs, name=node_name)

    sym_op.__name__ = op_name
    sym_op.__doc__ = (opdef.fn.__doc__ or "") + f"\n\n(symbolic op {op_name!r})"
    return sym_op


def invoke_fn(fn, inputs: Sequence[Symbol], kwargs=None,
              num_outputs=1, name=None) -> Symbol:
    """Symbolic counterpart of ``ndarray.invoke_fn``: splice an ad-hoc pure
    function into the graph as one node.

    The node carries its :class:`OpDef` inline (``_opdef``) instead of a
    registry name, so the executor and the shape pre-flight evaluate it
    like any other op. Such graphs are in-memory only: ``tojson()`` emits
    the ``_invoke_fn`` placeholder, which cannot be loaded back.
    """
    node = _apply_op("_invoke_fn", list(inputs), dict(kwargs or {}),
                     name=name)
    node._opdef = OpDef("_invoke_fn", fn, num_outputs=num_outputs,
                        ndarray_inputs="*")
    return node


def Variable(name: str, shape=None, dtype=None, init=None, **attrs) -> Symbol:
    a = dict(attrs)
    if shape is not None:
        a["__shape__"] = tuple(shape)
    if dtype is not None:
        a["__dtype__"] = str(dtype)
    if init is not None:
        a["__init__"] = init
    return Symbol(None, name, [], _scope_attrs(a))


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    return Symbol("_group", "group", list(symbols), {})


def zeros(shape, dtype="float32", name=None):
    return _apply_op("_zeros", [], {"shape": tuple(shape), "dtype": dtype},
                     name=name)


def ones(shape, dtype="float32", name=None):
    return _apply_op("_ones", [], {"shape": tuple(shape), "dtype": dtype},
                     name=name)


def load_json(s: str) -> Symbol:
    d = json.loads(s)
    nodes: List[Symbol] = []
    for nd_ in d["nodes"]:
        if nd_["op"] == "null":
            attrs = coerce_kwargs(nd_.get("attrs", nd_.get("param", {})))
            sym = Symbol(None, nd_["name"], [], attrs)
        else:
            ins = []
            for (nid, out_idx, _v) in nd_["inputs"]:
                src = nodes[nid]
                ins.append(src if out_idx == 0 else src[out_idx])
            attrs = coerce_kwargs(nd_.get("attrs", nd_.get("param", {})))
            sym = Symbol(nd_["op"], nd_["name"], ins, attrs)
            # Auxness is DERIVED, not serialized (tojson drops internal
            # "__" attrs, and the reference json carries none either —
            # graph.cc re-derives aux states from the op registry's
            # mutable inputs): a variable feeding an op slot named in
            # _AUX_ARGS (moving_mean/moving_var/...) is an aux state.
            # Without this, a BatchNorm checkpoint reloads its moving
            # stats as plain (zero-initialized) arguments — a silent
            # eval-accuracy bug.
            try:
                slots = op_input_names(get_op(nd_["op"]))
            except Exception:  # unknown/variadic op: nothing to derive
                slots = []
            for inp, slot in zip(ins, slots):
                base = inp._base()
                if base._op is None and slot in _AUX_ARGS:
                    base._attrs["__aux__"] = True
        nodes.append(sym)
    heads = [nodes[h[0]] if h[1] == 0 else nodes[h[0]][h[1]]
             for h in d["heads"]]
    if len(heads) == 1:
        return heads[0]
    return Group(heads)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


# ---------------------------------------------------------------------------
# shape inference
# ---------------------------------------------------------------------------

def _param_shape_rules(op: str, in_shape: tuple, kwargs: Dict[str, Any],
                       arg: str) -> Optional[tuple]:
    """Shape of an auto-created parameter from the primary input's shape —
    mirrors each reference op's InferShape (src/operator/** — TBV)."""
    k = kwargs
    if op == "FullyConnected":
        nh = int(k["num_hidden"])
        flatten = k.get("flatten", True)
        in_units = int(np.prod(in_shape[1:])) if flatten else in_shape[-1]
        return {"weight": (nh, in_units), "bias": (nh,)}.get(arg)
    if op in ("Convolution", "Deconvolution"):
        nf = int(k["num_filter"])
        kern = tuple(k.get("kernel", ()))
        ng = int(k.get("num_group", 1))
        c = in_shape[1]
        if op == "Convolution":
            w = (nf, c // ng) + kern
        else:
            w = (c, nf // ng) + kern
        return {"weight": w, "bias": (nf,)}.get(arg)
    if op in ("BatchNorm", "InstanceNorm"):
        axis = int(k.get("axis", 1))
        return (in_shape[axis],)
    if op == "LayerNorm":
        axis = int(k.get("axis", -1)) % len(in_shape)
        return (in_shape[axis],)
    if op == "GroupNorm":
        return (in_shape[1],)
    if op == "Embedding":
        return (int(k["input_dim"]), int(k["output_dim"]))
    if op == "LeakyReLU" and arg == "gamma":
        return (in_shape[1] if len(in_shape) > 1 else in_shape[0],)
    if op in ("SoftmaxOutput", "Softmax", "SVMOutput") and arg == "label":
        multi = str(k.get("multi_output", False)).lower() == "true" or \
            k.get("multi_output") is True
        if multi:
            return (in_shape[0],) + tuple(in_shape[2:])
        return (in_shape[0],)
    if op.endswith("RegressionOutput") and arg == "label":
        return tuple(in_shape)
    if op == "RNN":
        from ..ops.rnn import rnn_param_size

        h = int(k["state_size"])
        L = int(k["num_layers"])
        bi = str(k.get("bidirectional", False)).lower() == "true" or \
            k.get("bidirectional") is True
        dirs = 2 if bi else 1
        if arg == "parameters":
            return (rnn_param_size(k["mode"], in_shape[2], h, L, bi),)
        if arg in ("state", "state_cell"):
            return (L * dirs, in_shape[1], h)
    return None


def infer_shapes(sym: Symbol, known: Dict[str, tuple]):
    """Topo-order forward shape inference. Returns (all_input_shapes,
    out_shapes). Auto-created params get shapes from op rules; other node
    outputs via jax.eval_shape of the same pure op functions."""
    shapes, out_shapes, _ = _infer_shapes_full(sym, known)
    return shapes, out_shapes


def infer_node_shapes(sym: Symbol, known: Dict[str, tuple]):
    """Per-node output shapes keyed by id(node) (used by export helpers
    that need intermediate ranks, e.g. the ONNX Softmax axis guard)."""
    return _infer_shapes_full(sym, known)[2]


def _infer_shapes_full(sym: Symbol, known: Dict[str, tuple]):
    """Delegates to the shared analysis engine (analysis/shape_infer.py) so
    infer_shape, the lint pre-flight, and print_summary can never disagree.
    Failures raise a node-attributed GraphAnalysisError (a ValueError
    subclass, so pre-existing handlers keep working)."""
    from ..analysis.shape_infer import infer_graph

    res = infer_graph(sym, known, collect=False)
    return res.shapes, res.out_shapes, res.node_out
