"""``mx.sym.random`` — random ops in the symbolic frontend (reference
python/mxnet/symbol/random.py over the ``_random_*``/``_sample_*`` names)."""
from __future__ import annotations

from ..ops import has_op
from .symbol import _make_symbol_op


def __getattr__(name: str):
    for cand in (f"_random_{name}", f"_sample_{name}", name):
        if has_op(cand):
            fn = _make_symbol_op(cand)
            globals()[name] = fn
            return fn
    raise AttributeError(f"no random symbol operator {name!r}")
