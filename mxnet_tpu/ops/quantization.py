"""INT8 quantization operators.

Reference: ``src/operator/quantization/`` (quantize/quantize_v2/dequantize/
requantize/quantized_conv/quantized_fully_connected + calibration — TBV,
SURVEY.md §2.2 Quantization row; round 2 shipped a raise-only stub).

TPU redesign: symmetric int8 with per-tensor scales. The MXU consumes int8
operand pairs natively (XLA lowers ``lax.dot_general(preferred_element_type=
int32)``), so quantized_conv / quantized_fc accumulate in int32 exactly like
the reference's GPU int8 path, and the (value ↔ scale) bookkeeping rides as
the reference's (min_range, max_range) output pair.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register

__all__ = []


def _range_scale(min_r, max_r, bits=8):
    """Symmetric scale mapping [-m, m] → int8 (reference quantize's
    ``MaxAbs(min_range, max_range)`` convention)."""
    m = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    return jnp.where(m > 0, 127.0 / m, 1.0)


@register("_contrib_quantize", aliases=["quantize"], num_outputs=3,
          differentiable=False, ndarray_inputs=['data', 'min_range', 'max_range'])
def _quantize(data, min_range, max_range, out_type="int8"):
    """f32 → int8 against a given calibration range. Returns
    (quantized, min_output, max_output)."""
    scale = _range_scale(min_range.reshape(()), max_range.reshape(()))
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    m = 127.0 / scale
    return q, -m.reshape(1), m.reshape(1)


def _q_v2_n_out(kwargs):
    return 3


@register("_contrib_quantize_v2", aliases=["quantize_v2"],
          num_outputs=_q_v2_n_out, differentiable=False, ndarray_inputs=['data'])
def _quantize_v2(data, out_type="int8", min_calib_range=None,
                 max_calib_range=None):
    """Like quantize, but the range comes from calibration kwargs or, when
    absent, from the data itself (the reference's online min/max mode)."""
    if min_calib_range is not None and max_calib_range is not None:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    else:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    scale = _range_scale(mn, mx)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    m = 127.0 / scale
    return q, -m.reshape(1), m.reshape(1)


@register("_contrib_dequantize", aliases=["dequantize"], differentiable=False, ndarray_inputs=['data', 'min_range', 'max_range'])
def _dequantize(data, min_range, max_range, out_type="float32"):
    """(min_range, max_range) give the real value of the integer dtype's
    extremes — 127 for int8 inputs, 2^31-1 for the int32 accumulators the
    quantized conv/fc ops emit."""
    m = jnp.maximum(jnp.abs(min_range.reshape(())),
                    jnp.abs(max_range.reshape(())))
    qmax = 127.0 if data.dtype == jnp.int8 else 2.0 ** 31 - 1
    return data.astype(jnp.float32) * (m / qmax)


@register("_contrib_requantize", aliases=["requantize"], num_outputs=3,
          differentiable=False, ndarray_inputs=['data', 'min_range', 'max_range'])
def _requantize(data, min_range, max_range, min_calib_range=None,
                max_calib_range=None, out_type="int8"):
    """int32 accumulator → int8. min/max_range describe the int32 value
    scale (the product scale from quantized_conv/fc)."""
    real = data.astype(jnp.float32) * (
        jnp.maximum(jnp.abs(min_range.reshape(())),
                    jnp.abs(max_range.reshape(()))) / (2.0 ** 31 - 1))
    if min_calib_range is not None and max_calib_range is not None:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    else:
        mn = jnp.min(real)
        mx = jnp.max(real)
    scale = _range_scale(mn, mx)
    q = jnp.clip(jnp.round(real * scale), -127, 127).astype(jnp.int8)
    m = 127.0 / scale
    return q, -m.reshape(1), m.reshape(1)


def _int32_range(min_a, max_a, min_b, max_b):
    """Value range of the int32 accumulator expressed in real units —
    the reference's quantized op (min_out, max_out) convention."""
    ma = jnp.maximum(jnp.abs(min_a.reshape(())), jnp.abs(max_a.reshape(())))
    mb = jnp.maximum(jnp.abs(min_b.reshape(())), jnp.abs(max_b.reshape(())))
    m = ma * mb / (127.0 * 127.0) * (2.0 ** 31 - 1)
    return -m.reshape(1), m.reshape(1)


@register("_contrib_quantized_fully_connected",
          aliases=["quantized_fully_connected"], num_outputs=3,
          differentiable=False, ndarray_inputs=['data', 'weight', 'bias', 'min_data', 'max_data', 'min_weight', 'max_weight'])
def _quantized_fc(data, weight, bias, min_data, max_data, min_weight,
                  max_weight, min_bias=None, max_bias=None, num_hidden=1,
                  no_bias=False, flatten=True):
    """int8 data (B, K) × int8 weight (N, K) → int32 (B, N) on the MXU."""
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    acc = lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    if bias is not None and not no_bias:
        # bias arrives int8 with its own scale; rescale to the accumulator's
        # (data_scale * weight_scale) grid, matching the reference
        sd = _range_scale(min_data.reshape(()), max_data.reshape(()))
        sw = _range_scale(min_weight.reshape(()), max_weight.reshape(()))
        sb = _range_scale(min_bias.reshape(()), max_bias.reshape(()))
        bias_acc = jnp.round(bias.astype(jnp.float32) / sb * (sd * sw))
        acc = acc + bias_acc.astype(jnp.int32)
    mn, mx = _int32_range(min_data, max_data, min_weight, max_weight)
    return acc, mn, mx


@register("_contrib_quantized_conv", aliases=["quantized_conv"],
          num_outputs=3, differentiable=False, ndarray_inputs=['data', 'weight', 'bias', 'min_data', 'max_data', 'min_weight', 'max_weight'])
def _quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                    max_weight, min_bias=None, max_bias=None, kernel=(1, 1),
                    stride=(1, 1), pad=(0, 0), dilate=(1, 1), num_filter=1,
                    num_group=1, no_bias=False, layout="NCHW"):
    """int8 NCHW conv with int32 accumulation."""
    sh = stride if isinstance(stride, (tuple, list)) else (stride, stride)
    ph = pad if isinstance(pad, (tuple, list)) else (pad, pad)
    dh = dilate if isinstance(dilate, (tuple, list)) else (dilate, dilate)
    acc = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8), tuple(sh),
        [(ph[0], ph[0]), (ph[1], ph[1])], rhs_dilation=tuple(dh),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=int(num_group),
        preferred_element_type=jnp.int32)
    if bias is not None and not no_bias:
        sd = _range_scale(min_data.reshape(()), max_data.reshape(()))
        sw = _range_scale(min_weight.reshape(()), max_weight.reshape(()))
        sb = _range_scale(min_bias.reshape(()), max_bias.reshape(()))
        bias_acc = jnp.round(bias.astype(jnp.float32) / sb * (sd * sw))
        acc = acc + bias_acc.astype(jnp.int32).reshape(1, -1, 1, 1)
    mn, mx = _int32_range(min_data, max_data, min_weight, max_weight)
    return acc, mn, mx


@register("_contrib_quantized_pooling", aliases=["quantized_pooling"],
          num_outputs=3, differentiable=False, ndarray_inputs=['data', 'min_data', 'max_data'])
def _quantized_pooling(data, min_data, max_data, kernel=(2, 2),
                       stride=None, pad=(0, 0), pool_type="max",
                       global_pool=False):
    from .nn import _pooling

    out = _pooling(data.astype(jnp.float32), kernel=kernel, stride=stride,
                   pad=pad, pool_type=pool_type, global_pool=global_pool)
    if pool_type == "max":
        out = out.astype(data.dtype)  # max pooling is exact on the int grid
    else:
        out = jnp.round(out).astype(data.dtype)
    return out, min_data, max_data


@register("_contrib_quantized_flatten", aliases=["quantized_flatten"],
          num_outputs=3, differentiable=False, ndarray_inputs=['data', 'min_data', 'max_data'])
def _quantized_flatten(data, min_data, max_data):
    return data.reshape(data.shape[0], -1), min_data, max_data
