"""Fused optimizer-update ops.

Reference: ``src/operator/optimizer_op.*`` (TBV — SURVEY.md §2.2): sgd_update,
sgd_mom_update, mp_* (fp16 with fp32 master weights), adam, lamb, ftrl, signum,
multi-tensor variants. Functional redesign: each op returns the updated
(weight, *states) instead of mutating in place; the optimizer frontend assigns
back, and inside a jit'd train step XLA fuses all of them into the step program
(the reference's whole reason for fusing these by hand).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _grad_prep(grad, wd, weight, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register("sgd_update", ndarray_inputs=['weight', 'grad'])
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    return weight - lr * g


@register("sgd_mom_update", num_outputs=2, ndarray_inputs=['weight', 'grad', 'mom'])
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, lazy_update=True):
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    mom = momentum * mom - lr * g
    return weight + mom, mom


@register("nag_mom_update", num_outputs=2, ndarray_inputs=['weight', 'grad', 'mom'])
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    mom = momentum * mom - lr * g
    return weight + momentum * mom - lr * g, mom


@register("mp_sgd_update", num_outputs=2, ndarray_inputs=['weight', 'grad', 'weight32'])
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    g = _grad_prep(grad.astype(jnp.float32), wd, weight32, rescale_grad, clip_gradient)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_outputs=3, ndarray_inputs=['weight', 'grad', 'mom', 'weight32'])
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _grad_prep(grad.astype(jnp.float32), wd, weight32, rescale_grad, clip_gradient)
    mom = momentum * mom - lr * g
    w32 = weight32 + mom
    return w32.astype(weight.dtype), mom, w32


@register("adam_update", num_outputs=3, ndarray_inputs=['weight', 'grad', 'mean', 'var'])
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    mean = beta1 * mean + (1 - beta1) * g
    var = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - lr * mean / (jnp.sqrt(var) + epsilon), mean, var


@register("adamw_update", aliases=["_adamw_update", "_contrib_adamw_update"], num_outputs=3, ndarray_inputs=['weight', 'grad', 'mean', 'var'])
def _adamw_update(weight, grad, mean, var, rescale_grad=None, lr=0.001, beta1=0.9,
                  beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0, clip_gradient=-1.0):
    rg = rescale_grad if not hasattr(rescale_grad, "shape") else rescale_grad.reshape(())
    if rg is None:
        rg = 1.0
    g = grad * rg
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mean = beta1 * mean + (1 - beta1) * g
    var = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * mean / (jnp.sqrt(var) + epsilon) + wd * weight)
    return w, mean, var


@register("rmsprop_update", num_outputs=2, ndarray_inputs=['weight', 'grad', 'n'])
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n


@register("rmspropalex_update", num_outputs=4, ndarray_inputs=['weight', 'grad', 'n', 'g_', 'delta'])
def _rmspropalex_update(weight, grad, n, g_, delta, lr=0.001, gamma1=0.95, gamma2=0.9,
                        epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                        clip_weights=-1.0):
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    g_ = gamma1 * g_ + (1 - gamma1) * g
    delta = gamma2 * delta - lr * g / jnp.sqrt(n - jnp.square(g_) + epsilon)
    w = weight + delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n, g_, delta


@register("ftrl_update", num_outputs=3, ndarray_inputs=['weight', 'grad', 'z', 'n'])
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    n_new = n + jnp.square(g)
    z = z + g - (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr * weight
    w = jnp.where(
        jnp.abs(z) > lamda1,
        -(z - jnp.sign(z) * lamda1) / ((beta + jnp.sqrt(n_new)) / lr + wd),
        0.0,
    )
    return w.astype(weight.dtype), z, n_new


@register("signsgd_update", ndarray_inputs=['weight', 'grad'])
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_outputs=2, ndarray_inputs=['weight', 'grad', 'mom'])
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mom = momentum * mom - (1 - momentum) * g
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom)
    return w, mom


def _lamb_states(grad, mean, var, beta1=0.9, beta2=0.999, rescale_grad=1.0,
                 clip_gradient=-1.0):
    """Shared lamb state advance (single and multi-tensor ops must agree)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return beta1 * mean + (1 - beta1) * g, beta2 * var + (1 - beta2) * jnp.square(g)


@register("lamb_update_phase1", ndarray_inputs=['weight', 'grad', 'mean', 'var'])
def _lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999, epsilon=1e-6,
                        t=1, bias_correction=True, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0):
    m, v = _lamb_states(grad, mean, var, beta1, beta2, rescale_grad,
                        clip_gradient)
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    return m / (jnp.sqrt(v) + epsilon) + wd * weight


@register("lamb_update_phase2", ndarray_inputs=['weight', 'g', 'r1', 'r2'])
def _lamb_update_phase2(weight, g, r1, r2, lr=0.01, lower_bound=-1.0, upper_bound=-1.0):
    r1v = r1.reshape(())
    r2v = r2.reshape(())
    if lower_bound is not None and lower_bound > 0:
        r1v = jnp.maximum(r1v, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1v = jnp.minimum(r1v, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1v > 0, r2v > 0), r1v / r2v, 1.0)
    return weight - lr * ratio * g


@register("adagrad_update", aliases=["_sparse_adagrad_update"], num_outputs=2, ndarray_inputs=['weight', 'grad', 'history'])
def _adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    history = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(history) + epsilon), history


@register("adadelta_update", aliases=["adaalpha_update"], num_outputs=3, ndarray_inputs=['weight', 'grad', 'acc_g', 'acc_delta'])
def _adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5, wd=0.0,
                     rescale_grad=1.0, clip_gradient=-1.0):
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(acc_g + epsilon) * g
    acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return weight - delta, acc_g, acc_delta


@register("ftml_update", num_outputs=4, ndarray_inputs=['weight', 'grad', 'd', 'v', 'z'])
def _ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999, epsilon=1e-8,
                 t=1, wd=0.0, rescale_grad=1.0, clip_grad=-1.0):
    g = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad > 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = (1 - beta1 ** t) / lr * (jnp.sqrt(v / (1 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * d
    z = beta1 * z + (1 - beta1) * g - sigma * weight
    return -z / d_new, d_new, v, z


# ---------------------------------------------------------------------------
# Multi-tensor updates (reference src/operator/optimizer_op.* multi_sgd_*,
# contrib multi_lamb/multi_adamw — TBV, SURVEY.md §2.2 optimizer row).
# The reference fuses N small parameter updates into one kernel launch; here
# each group update is the single-tensor op applied per group — inside a jit
# XLA fuses across groups into one program, which is the TPU-native analog of
# the multi-tensor apply. Inputs arrive flattened per the reference calling
# convention ([w0,g0, w1,g1, ...]); lrs/wds are per-group lists.
# ---------------------------------------------------------------------------

def _per_group(kwargs, name, i, default):
    v = kwargs.get(name, None)
    if v is None:
        return default
    if isinstance(v, (list, tuple)):
        return v[i]
    return v


def _multi(step, n_in, n_out_per, arrays, kwargs):
    num = int(kwargs.get("num_weights", len(arrays) // n_in))
    outs = []
    for i in range(num):
        group = arrays[i * n_in:(i + 1) * n_in]
        outs.append(step(i, *group))
    # flatten [(w,m), ...] -> (w0, w1, ..., m0, m1, ...): reference multi ops
    # emit all updated weights first (their aux states follow)
    flat = []
    for j in range(n_out_per):
        for o in outs:
            flat.append(o[j] if isinstance(o, tuple) else o)
    return tuple(flat) if len(flat) > 1 else flat[0]


def _multi_n_out(n_in, n_out_per):
    def n(kwargs):
        return int(kwargs["num_weights"]) * n_out_per if "num_weights" in kwargs else n_out_per
    return n


@register("multi_sgd_update", num_outputs=_multi_n_out(2, 1), ndarray_inputs="*")
def _multi_sgd_update(*arrays, **kwargs):
    def step(i, w, g):
        return _sgd_update(w, g, lr=_per_group(kwargs, "lrs", i, 0.01),
                           wd=_per_group(kwargs, "wds", i, 0.0),
                           rescale_grad=kwargs.get("rescale_grad", 1.0),
                           clip_gradient=kwargs.get("clip_gradient", -1.0))
    return _multi(step, 2, 1, arrays, kwargs)


@register("multi_sgd_mom_update", num_outputs=_multi_n_out(3, 2), ndarray_inputs="*")
def _multi_sgd_mom_update(*arrays, **kwargs):
    def step(i, w, g, m):
        return _sgd_mom_update(w, g, m, lr=_per_group(kwargs, "lrs", i, 0.01),
                               momentum=kwargs.get("momentum", 0.0),
                               wd=_per_group(kwargs, "wds", i, 0.0),
                               rescale_grad=kwargs.get("rescale_grad", 1.0),
                               clip_gradient=kwargs.get("clip_gradient", -1.0))
    return _multi(step, 3, 2, arrays, kwargs)


@register("multi_mp_sgd_update", num_outputs=_multi_n_out(3, 2), ndarray_inputs="*")
def _multi_mp_sgd_update(*arrays, **kwargs):
    def step(i, w, g, w32):
        return _mp_sgd_update(w, g, w32, lr=_per_group(kwargs, "lrs", i, 0.01),
                              wd=_per_group(kwargs, "wds", i, 0.0),
                              rescale_grad=kwargs.get("rescale_grad", 1.0),
                              clip_gradient=kwargs.get("clip_gradient", -1.0))
    return _multi(step, 3, 2, arrays, kwargs)


@register("multi_mp_sgd_mom_update", num_outputs=_multi_n_out(4, 3), ndarray_inputs="*")
def _multi_mp_sgd_mom_update(*arrays, **kwargs):
    def step(i, w, g, m, w32):
        return _mp_sgd_mom_update(w, g, m, w32,
                                  lr=_per_group(kwargs, "lrs", i, 0.01),
                                  momentum=kwargs.get("momentum", 0.0),
                                  wd=_per_group(kwargs, "wds", i, 0.0),
                                  rescale_grad=kwargs.get("rescale_grad", 1.0),
                                  clip_gradient=kwargs.get("clip_gradient", -1.0))
    return _multi(step, 4, 3, arrays, kwargs)


def _preloaded(base_fn, n_in, n_out_per):
    """preloaded_multi_*: lrs/wds arrive as device arrays (last two inputs)
    instead of python lists — the reference variant that keeps hyperparams
    on-device across steps."""
    def fn(*arrays, **kwargs):
        lrs, wds = arrays[-2], arrays[-1]
        body = arrays[:-2]
        num = int(kwargs.get("num_weights", len(body) // n_in))
        kw = dict(kwargs)
        kw["num_weights"] = num
        kw["lrs"] = [lrs.reshape(-1)[i] for i in range(num)]
        kw["wds"] = [wds.reshape(-1)[i] for i in range(num)]
        return base_fn(*body, **kw)
    return fn


register("preloaded_multi_sgd_update",
         num_outputs=_multi_n_out(2, 1), ndarray_inputs="*")(
    _preloaded(_multi_sgd_update, 2, 1))
register("preloaded_multi_sgd_mom_update",
         num_outputs=_multi_n_out(3, 2), ndarray_inputs="*")(
    _preloaded(_multi_sgd_mom_update, 3, 2))
register("preloaded_multi_mp_sgd_update",
         num_outputs=_multi_n_out(3, 2), ndarray_inputs="*")(
    _preloaded(_multi_mp_sgd_update, 3, 2))
register("preloaded_multi_mp_sgd_mom_update",
         num_outputs=_multi_n_out(4, 3), ndarray_inputs="*")(
    _preloaded(_multi_mp_sgd_mom_update, 4, 3))


@register("multi_lamb_update_phase1", aliases=["_multi_lamb_update_phase1"],
          num_outputs=_multi_n_out(4, 3), ndarray_inputs="*")
def _multi_lamb_phase1(*arrays, **kwargs):
    def step(i, w, g, mean, var):
        b1 = kwargs.get("beta1", 0.9)
        b2 = kwargs.get("beta2", 0.999)
        m, v = _lamb_states(
            g, mean, var, beta1=b1, beta2=b2,
            rescale_grad=kwargs.get("rescale_grad", 1.0),
            clip_gradient=kwargs.get("clip_gradient", -1.0))
        mb, vb = m, v
        if kwargs.get("bias_correction", True):
            t = _per_group(kwargs, "step_count",
                           i, _per_group(kwargs, "t", i, 1))
            mb = m / (1 - b1 ** t)
            vb = v / (1 - b2 ** t)
        upd = (mb / (jnp.sqrt(vb) + kwargs.get("epsilon", 1e-6))
               + _per_group(kwargs, "wds", i, 0.0) * w)
        return upd, m, v
    return _multi(step, 4, 3, arrays, kwargs)


@register("multi_lamb_update_phase2", aliases=["_multi_lamb_update_phase2"],
          num_outputs=_multi_n_out(4, 1), ndarray_inputs="*")
def _multi_lamb_phase2(*arrays, **kwargs):
    def step(i, w, g, r1, r2):
        return _lamb_update_phase2(
            w, g, r1, r2, lr=_per_group(kwargs, "lrs", i, 0.01),
            lower_bound=kwargs.get("lower_bound", -1.0),
            upper_bound=kwargs.get("upper_bound", -1.0))
    return _multi(step, 4, 1, arrays, kwargs)


@register("multi_adamw_update", aliases=["_multi_adamw_update"],
          num_outputs=_multi_n_out(4, 3), ndarray_inputs="*")
def _multi_adamw_update(*arrays, **kwargs):
    def step(i, w, g, mean, var):
        return _adamw_update(
            w, g, mean, var, lr=_per_group(kwargs, "lrs", i, 0.01),
            beta1=kwargs.get("beta1", 0.9), beta2=kwargs.get("beta2", 0.999),
            epsilon=kwargs.get("epsilon", 1e-8),
            wd=_per_group(kwargs, "wds", i, 0.0),
            eta=_per_group(kwargs, "etas", i, kwargs.get("eta", 1.0)),
            rescale_grad=kwargs.get("rescale_grad", 1.0),
            clip_gradient=kwargs.get("clip_gradient", -1.0))
    return _multi(step, 4, 3, arrays, kwargs)


@register("multi_mp_adamw_update", aliases=["_multi_mp_adamw_update"],
          num_outputs=_multi_n_out(5, 4), ndarray_inputs="*")
def _multi_mp_adamw_update(*arrays, **kwargs):
    def step(i, w, g, mean, var, w32):
        nw32, m, v = _adamw_update(
            w32, g.astype(jnp.float32), mean, var,
            lr=_per_group(kwargs, "lrs", i, 0.01),
            beta1=kwargs.get("beta1", 0.9), beta2=kwargs.get("beta2", 0.999),
            epsilon=kwargs.get("epsilon", 1e-8),
            wd=_per_group(kwargs, "wds", i, 0.0),
            eta=_per_group(kwargs, "etas", i, kwargs.get("eta", 1.0)),
            rescale_grad=kwargs.get("rescale_grad", 1.0),
            clip_gradient=kwargs.get("clip_gradient", -1.0))
        return nw32.astype(w.dtype), m, v, nw32
    return _multi(step, 5, 4, arrays, kwargs)


@register("adamax_update", num_outputs=3, ndarray_inputs=['weight', 'grad', 'mean', 'inf_norm'])
def _adamax_update(weight, grad, mean, inf_norm, lr=0.002, beta1=0.9,
                   beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, t=1):
    """AdaMax (reference optimizer_op.* adamax — infinity-norm Adam)."""
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    mean = beta1 * mean + (1 - beta1) * g
    inf_norm = jnp.maximum(beta2 * inf_norm, jnp.abs(g))
    lr_t = lr / (1 - beta1 ** t)
    return weight - lr_t * mean / (inf_norm + epsilon), mean, inf_norm


@register("nadam_update", num_outputs=3, ndarray_inputs=['weight', 'grad', 'mean', 'var'])
def _nadam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                  epsilon=1e-8, schedule_decay=0.004, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, t=1, m_schedule=1.0):
    """Nesterov Adam (reference optimizer.Nadam semantics)."""
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    momentum_t = beta1 * (1 - 0.5 * 0.96 ** (t * schedule_decay))
    momentum_t1 = beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * schedule_decay))
    m_sched = m_schedule * momentum_t
    m_sched_next = m_sched * momentum_t1
    g_prime = g / (1 - m_sched)
    mean = beta1 * mean + (1 - beta1) * g
    var = beta2 * var + (1 - beta2) * jnp.square(g)
    m_prime = mean / (1 - m_sched_next)
    v_prime = var / (1 - beta2 ** t)
    m_bar = (1 - momentum_t) * g_prime + momentum_t1 * m_prime
    return weight - lr * m_bar / (jnp.sqrt(v_prime) + epsilon), mean, var


@register("sgld_update", differentiable=False, ndarray_inputs=['weight', 'grad'])
def _sgld_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0):
    """Stochastic Gradient Langevin Dynamics: SGD step + N(0, lr) noise
    (reference optimizer.SGLD)."""
    import jax

    from ..random import next_key

    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    noise = jax.random.normal(next_key(), weight.shape, weight.dtype) \
        * jnp.sqrt(jnp.asarray(lr, weight.dtype))
    return weight - 0.5 * lr * g + noise


@register("lars_update", num_outputs=2, ndarray_inputs=['weight', 'grad', 'mom'])
def _lars_update(weight, grad, mom, lr=0.01, momentum=0.9, eta=0.001,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """LARS (reference optimizer.LARS): per-tensor trust ratio
    eta*||w|| / (||g|| + wd*||w|| + eps) scales the lr of a plain momentum
    step.  Norms are f32 in-graph — no host round-trip."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w_norm = jnp.sqrt(jnp.sum(jnp.square(weight.astype(jnp.float32))))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    trust = jnp.where((w_norm > 0) & (g_norm > 0),
                      eta * w_norm / (g_norm + wd * w_norm + epsilon),
                      jnp.float32(1.0))
    return _sgd_mom_update(weight, grad, mom, lr=(lr * trust).astype(weight.dtype),
                           momentum=momentum, wd=wd, rescale_grad=rescale_grad,
                           clip_gradient=clip_gradient)


@register("dcasgd_update", num_outputs=3, ndarray_inputs=['weight', 'grad', 'mom', 'prev_weight'])
def _dcasgd_update(weight, grad, mom, prev_weight, lr=0.01, momentum=0.0,
                   lamda=0.04, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Delay-compensated async SGD (reference optimizer.DCASGD): the delayed
    gradient is corrected with lamda * g² * (w - w_prev)."""
    g = _grad_prep(grad, wd, weight, rescale_grad, clip_gradient)
    comp = g + lamda * jnp.square(g) * (weight - prev_weight)
    mom = momentum * mom - lr * comp
    return weight + mom, mom, weight
