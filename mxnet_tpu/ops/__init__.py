"""Operator library: pure-JAX implementations behind the registry.

Importing this package registers every op family (the analog of the
reference's static NNVM_REGISTER_OP initializers in src/operator/**, TBV).
"""
from . import registry  # noqa: F401
from .registry import get_op, has_op, list_ops, register, alias, coerce_kwargs  # noqa: F401

# Register op families (order matters only for aliases).
from . import elemwise  # noqa: F401
from . import reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import indexing  # noqa: F401
from . import init_ops  # noqa: F401
from . import ordering  # noqa: F401
from . import nn  # noqa: F401
from . import sequence  # noqa: F401
from . import rnn  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import contrib  # noqa: F401
from . import control_flow  # noqa: F401
from . import image_ops  # noqa: F401
from . import random_ops  # noqa: F401
from . import loss_ops  # noqa: F401
from . import quantization  # noqa: F401
