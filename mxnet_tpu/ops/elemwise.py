"""Elementwise + broadcast operator family.

Reference: ``src/operator/tensor/elemwise_unary_op*``, ``elemwise_binary_op*``,
``elemwise_binary_broadcast_op*``, ``elemwise_scalar_op*`` (paths TBV —
SURVEY.md §2.2: "elemwise + broadcast are the long tail", ~400 tensor ops).

TPU design: every op is one jax.numpy expression. XLA fuses chains of these
into single HBM-bandwidth-bound kernels (and into adjacent matmuls), which is
exactly the job mshadow expression templates + mxnet_op::Kernel::Launch do by
hand in the reference — so there is nothing to schedule here.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# Unary ops. Name table mirrors the reference registry names.
# ---------------------------------------------------------------------------

_UNARY = {
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": lambda x: jnp.where(x >= 0, 1 / (1 + jnp.exp(-x)), jnp.exp(x) / (1 + jnp.exp(x))),
    "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "softsign": lambda x: x / (1 + jnp.abs(x)),
    "tanh": jnp.tanh,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1 / jnp.cbrt(x),
    "square": jnp.square,
    "reciprocal": lambda x: 1 / x,
    "abs": jnp.abs,
    "sign": jnp.sign,
    "round": jnp.round,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "negative": jnp.negative,
    "erf": lax.erf,
    "erfinv": lax.erf_inv,
    "gamma": lambda x: jnp.exp(lax.lgamma(x)),
    "gammaln": lax.lgamma,
    "digamma": lax.digamma,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh_": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
    "isnan": jnp.isnan,
    "isinf": jnp.isinf,
    "isfinite": jnp.isfinite,
}

# analyzer tags: exp/log feed the numerics lint rules (log-of-softmax,
# exp-on-raw-input); log1p is the stabilized form, deliberately untagged
_UNARY_TAGS = {"exp": ("exp",), "log": ("log",), "log10": ("log",),
               "log2": ("log",)}

for _name, _f in _UNARY.items():
    if _name == "tanh_":
        continue
    register(_name, ndarray_inputs=["x"],
             tags=_UNARY_TAGS.get(_name, ()))(_f)

alias("abs", "_abs")
alias("negative", "_np_negative")


@register("softrelu", ndarray_inputs=['x'])
def _softrelu(x):
    # log(1+exp(x)), numerically stable
    return jnp.logaddexp(x, 0.0)


@register("gelu", aliases=["_npx_gelu"], ndarray_inputs=['x'])
def _gelu(x, approximation="erf"):
    if approximation == "tanh":
        c = 0.7978845608028654  # sqrt(2/pi)
        return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))
    return 0.5 * x * (1.0 + lax.erf(x / 1.4142135623730951))


@register("silu", ndarray_inputs=['x'])
def _silu(x):
    return x * (1 / (1 + jnp.exp(-x)))


@register("log_sigmoid", ndarray_inputs=['x'])
def _log_sigmoid(x):
    return -jnp.logaddexp(0.0, -x)


@register("mish", ndarray_inputs=['x'])
def _mish(x):
    return x * jnp.tanh(jnp.logaddexp(x, 0.0))


@register("clip", ndarray_inputs=['data'])
def _clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


@register("smooth_l1", ndarray_inputs=['data'])
def _smooth_l1(data, scalar=1.0):
    # reference src/operator/tensor/elemwise_unary_op (smooth_l1, sigma=scalar)
    s2 = scalar * scalar
    a = jnp.abs(data)
    return jnp.where(a < 1.0 / s2, 0.5 * s2 * data * data, a - 0.5 / s2)


@register("Cast", aliases=["cast"], ndarray_inputs=['data'])
def _cast(data, dtype="float32"):
    from ..base import dtype_np

    return data.astype(dtype_np(dtype))


@register("amp_cast", ndarray_inputs=['data'])
def _amp_cast(data, dtype="float32"):
    from ..base import dtype_np

    return data.astype(dtype_np(dtype))


@register("amp_multicast", num_outputs=lambda kw: int(kw.get("num_outputs", 1)), ndarray_inputs="*")
def _amp_multicast(*data, num_outputs=None, cast_narrow=False):
    dts = [d.dtype for d in data]
    widest = jnp.result_type(*dts) if not cast_narrow else min(dts, key=lambda d: jnp.dtype(d).itemsize)
    out = tuple(d.astype(widest) for d in data)
    return out if len(out) > 1 else out[0]


@register("zeros_like", ndarray_inputs=['data'])
def _zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like", ndarray_inputs=['data'])
def _ones_like(data):
    return jnp.ones_like(data)


@register("shape_array", differentiable=False, ndarray_inputs=['data'])
def _shape_array(data):
    return jnp.asarray(data.shape, dtype=jnp.int64 if False else jnp.int32)


@register("size_array", differentiable=False, ndarray_inputs=['data'])
def _size_array(data):
    return jnp.asarray([data.size], dtype=jnp.int32)


@register("BlockGrad", aliases=["stop_gradient"], ndarray_inputs=['data'])
def _block_grad(data):
    return lax.stop_gradient(data)


@register("identity", aliases=["_copy"], ndarray_inputs=['data'])
def _identity(data):
    return data


@register("MakeLoss", aliases=["make_loss"], ndarray_inputs=['data'])
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    # Forward is identity; grad_scale is applied by autograd via custom vjp-free
    # scaling: we fold it into the forward with stop_gradient trickery.
    if grad_scale == 1.0:
        return data
    return data * grad_scale - lax.stop_gradient(data * grad_scale - data)


# ---------------------------------------------------------------------------
# Binary broadcast + elemwise ops
# ---------------------------------------------------------------------------

_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": lambda a, b: (a == b).astype(jnp.result_type(a, b)),
    "not_equal": lambda a, b: (a != b).astype(jnp.result_type(a, b)),
    "greater": lambda a, b: (a > b).astype(jnp.result_type(a, b)),
    "greater_equal": lambda a, b: (a >= b).astype(jnp.result_type(a, b)),
    "lesser": lambda a, b: (a < b).astype(jnp.result_type(a, b)),
    "lesser_equal": lambda a, b: (a <= b).astype(jnp.result_type(a, b)),
    "logical_and": lambda a, b: ((a != 0) & (b != 0)).astype(jnp.result_type(a, b)),
    "logical_or": lambda a, b: ((a != 0) | (b != 0)).astype(jnp.result_type(a, b)),
    "logical_xor": lambda a, b: ((a != 0) ^ (b != 0)).astype(jnp.result_type(a, b)),
}

for _name, _f in _BINARY.items():
    register("broadcast_" + _name, ndarray_inputs=["a", "b"])(_f)

# elemwise_* variants require same shape in the reference; broadcasting is a
# superset, so they share implementations.
alias("broadcast_add", "elemwise_add", "_plus", "_add")
alias("broadcast_sub", "elemwise_sub", "_minus", "_sub")
alias("broadcast_mul", "elemwise_mul", "_mul")
alias("broadcast_div", "elemwise_div", "_div")
alias("broadcast_power", "_power", "_pow")
alias("broadcast_mod", "_mod")
alias("broadcast_maximum", "_maximum")
alias("broadcast_minimum", "_minimum")
alias("broadcast_equal", "_equal")
alias("broadcast_not_equal", "_not_equal")
alias("broadcast_greater", "_greater")
alias("broadcast_greater_equal", "_greater_equal")
alias("broadcast_lesser", "_lesser")
alias("broadcast_lesser_equal", "_lesser_equal")
alias("broadcast_logical_and", "_logical_and")
alias("broadcast_logical_or", "_logical_or")
alias("broadcast_logical_xor", "_logical_xor")
alias("broadcast_hypot", "_hypot")


@register("_scatter_elemwise_div", ndarray_inputs=['lhs', 'rhs'])
def _scatter_div(lhs, rhs):
    return lhs / rhs


# ---------------------------------------------------------------------------
# Scalar ops (tensor ⊕ python scalar), reference elemwise_binary_scalar_op*
# ---------------------------------------------------------------------------

_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
    "_logical_and_scalar": lambda x, s: ((x != 0) & (s != 0)).astype(x.dtype),
    "_logical_or_scalar": lambda x, s: ((x != 0) | (s != 0)).astype(x.dtype),
    "_logical_xor_scalar": lambda x, s: ((x != 0) ^ (s != 0)).astype(x.dtype),
}


def _make_scalar_op(f):
    def op(data, scalar=0.0, is_int=None):
        return f(data, scalar)

    return op


for _name, _f in _SCALAR.items():
    register(_name, ndarray_inputs=["data"])(_make_scalar_op(_f))
