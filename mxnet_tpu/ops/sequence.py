"""Sequence ops: SequenceMask / SequenceLast / SequenceReverse.

Reference: ``src/operator/sequence_{mask,last,reverse}*`` (TBV — SURVEY.md
§5.7: these + bucketing are the reference's entire variable-length story).
Layout convention kept from the reference: time-major ``(seq_len, batch, ...)``
unless ``axis=1``.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _len_mask(seq_len, batch, length):
    # (seq_len, batch) bool: t < length[b]
    t = jnp.arange(seq_len)[:, None]
    return t < length.astype(jnp.int32)[None, :]


@register("SequenceMask", ndarray_inputs=['data', 'sequence_length'])
def _sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    ax = int(axis)
    x = jnp.swapaxes(data, 0, 1) if ax == 1 else data
    m = _len_mask(x.shape[0], x.shape[1], sequence_length)
    m = m.reshape(m.shape + (1,) * (x.ndim - 2))
    out = jnp.where(m, x, jnp.asarray(value, x.dtype))
    return jnp.swapaxes(out, 0, 1) if ax == 1 else out


@register("SequenceLast", ndarray_inputs=['data', 'sequence_length'])
def _sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    ax = int(axis)
    x = jnp.swapaxes(data, 0, 1) if ax == 1 else data
    if not use_sequence_length or sequence_length is None:
        return x[-1]
    idx = jnp.clip(sequence_length.astype(jnp.int32) - 1, 0, x.shape[0] - 1)  # (batch,)
    return jnp.take_along_axis(x, idx.reshape((1, -1) + (1,) * (x.ndim - 2)), axis=0)[0]


@register("SequenceReverse", ndarray_inputs=['data', 'sequence_length'])
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    x = data  # reference only supports axis=0 (time-major)
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(x, axis=0)
    T = x.shape[0]
    ln = sequence_length.astype(jnp.int32)[None, :]  # (1, batch)
    t = jnp.arange(T)[:, None]
    src = jnp.where(t < ln, ln - 1 - t, t)  # reverse first len steps, keep rest
    src = src.reshape((T, -1) + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, jnp.broadcast_to(src, x.shape), axis=0)
