"""Ordering ops: topk / sort / argsort.

Reference: ``src/operator/tensor/ordering_op*`` (TBV — SURVEY.md §2.2; §7 hard
part #4). TPU design: XLA sort is a fully-static bitonic/stable sort — no
data-dependent shapes — so topk/sort map directly; ``ret_typ='mask'`` uses a
scatter over the sorted indices.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _topk_n_out(kw):
    return 2 if kw.get("ret_typ", "indices") == "both" else 1


@register("topk", num_outputs=_topk_n_out, differentiable=False, ndarray_inputs=['data'])
def _topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    from ..base import dtype_np

    ax = data.ndim - 1 if axis is None else int(axis) % data.ndim
    k = int(k)
    if k <= 0:
        k = data.shape[ax]
    x = jnp.moveaxis(data, ax, -1)
    if is_ascend:
        vals, idx = lax.top_k(-x, k)
        vals = -vals
    else:
        vals, idx = lax.top_k(x, k)
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax).astype(dtype_np(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idx
    if ret_typ == "both":
        return vals, idx
    if ret_typ == "mask":
        xm = jnp.moveaxis(jnp.zeros_like(data), ax, -1)
        ii = jnp.moveaxis(idx, ax, -1).astype(jnp.int32)
        mask = jnp.take_along_axis(xm, ii, axis=-1)  # shape probe
        flatm = xm.reshape(-1, xm.shape[-1])
        flati = ii.reshape(-1, ii.shape[-1])
        out = flatm.at[jnp.arange(flatm.shape[0])[:, None], flati].set(1.0)
        return jnp.moveaxis(out.reshape(xm.shape), -1, ax)
    raise ValueError(f"unknown ret_typ {ret_typ!r}")


@register("sort", ndarray_inputs=['data'])
def _sort(data, axis=-1, is_ascend=True):
    ax = data.ndim - 1 if axis is None else int(axis)
    s = jnp.sort(data, axis=ax)
    return s if is_ascend else jnp.flip(s, axis=ax)


@register("argsort", differentiable=False, ndarray_inputs=['data'])
def _argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    from ..base import dtype_np

    ax = data.ndim - 1 if axis is None else int(axis)
    idx = jnp.argsort(data, axis=ax, stable=True)
    if not is_ascend:
        idx = jnp.flip(idx, axis=ax)
    return idx.astype(dtype_np(dtype))


@register("_unravel_index", aliases=["unravel_index"], differentiable=False, ndarray_inputs=['data'])
def _unravel(data, shape=()):
    idx = jnp.unravel_index(data.astype(jnp.int32), tuple(shape))
    return jnp.stack(idx, axis=0).astype(jnp.float32)


@register("_ravel_multi_index", aliases=["ravel_multi_index"], differentiable=False, ndarray_inputs=['data'])
def _ravel(data, shape=()):
    coords = tuple(data[i].astype(jnp.int32) for i in range(data.shape[0]))
    return jnp.asarray(jnp.ravel_multi_index(coords, tuple(shape), mode="clip")).astype(jnp.float32)
