"""Central operator registry — the NNVM-registry analog.

In the reference, every operator is registered once in C++
(``NNVM_REGISTER_OP(...)`` in ``src/operator/**``, path TBV — SURVEY.md §2.2)
with FCompute/FGradient/FInferShape attributes, and the Python ``mx.nd``/
``mx.sym`` wrappers are *generated at import time* from that registry.

TPU-native redesign: an op is a **single pure JAX function** over jax.Arrays.
That one definition serves every consumer:

- eager dispatch (``mx.nd.*``)            — call it on concrete arrays;
- autograd (``FGradient``)                — ``jax.vjp`` of the same function;
- hybridize / symbolic executor (jit)     — trace it;
- shape/type inference (``FInferShape``)  — ``jax.eval_shape``;
- sharding/multi-chip                     — it composes with shard_map/pjit.

There is no separate kernel per backend: XLA lowers the traced HLO onto the
MXU; Pallas kernels plug in as just another pure function.
"""
from __future__ import annotations

import ast
from typing import Any, Callable, Dict, List, Optional

__all__ = ["OpDef", "register", "get_op", "list_ops", "alias", "coerce_kwargs"]


class OpDef:
    """One registered operator.

    Attributes:
        name: canonical op name (reference op names kept, e.g. ``broadcast_add``).
        fn: pure function ``fn(*arrays, **kwargs) -> array | tuple(arrays)``.
        num_outputs: static int, or callable(kwargs)->int for ops like ``RNN``.
        ndarray_inputs: names of positional tensor inputs (for symbol
            binding), or the string ``"*"`` for variadic ops. Declared on
            every registration (enforced by tools/lint_repo.py).
        differentiable: False disables autograd recording (e.g. ``argmax``).
        tags: semantic labels consumed by the static analyzer
            (mxnet_tpu.analysis), e.g. ``"reduction"``/``"softmax"``/
            ``"exp"``/``"log"`` — they drive the zero-size-reduction and
            numerics lint rules without name matching.
    """

    __slots__ = ("name", "fn", "num_outputs", "ndarray_inputs", "differentiable", "param_types",
                 "tags")

    def __init__(self, name, fn, num_outputs=1, ndarray_inputs=None, differentiable=True,
                 param_types=None, tags=()):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.ndarray_inputs = ndarray_inputs
        self.differentiable = differentiable
        self.param_types = param_types or {}
        self.tags = tuple(tags)

    def n_out(self, kwargs) -> int:
        if callable(self.num_outputs):
            return self.num_outputs(kwargs)
        return self.num_outputs

    def __repr__(self):
        return f"<Op {self.name}>"


_REGISTRY: Dict[str, OpDef] = {}


def register(name: str, num_outputs=1, aliases: Optional[List[str]] = None,
             ndarray_inputs=None, differentiable=True, tags=()):
    """Decorator registering a pure-JAX op under a reference op name."""

    def deco(fn: Callable):
        op = OpDef(name, fn, num_outputs, ndarray_inputs, differentiable,
                   tags=tags)
        _REGISTRY[name] = op
        for a in aliases or ():
            _REGISTRY[a] = op
        return fn

    return deco


def alias(existing: str, *names: str) -> None:
    op = _REGISTRY[existing]
    for n in names:
        _REGISTRY[n] = op


def get_op(name: str) -> OpDef:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise NotImplementedError(
            f"operator {name!r} is not implemented in mxnet_tpu "
            f"({len(set(id(v) for v in _REGISTRY.values()))} ops registered)"
        ) from None


def has_op(name: str) -> bool:
    return name in _REGISTRY


def list_ops() -> List[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# Param coercion. The reference's dmlc::Parameter layer parses op kwargs from
# strings (symbol JSON stores all attrs as strings). coerce_kwargs gives the
# same tolerance: "(3, 3)" -> (3, 3), "True" -> True, "2" -> 2.
# ---------------------------------------------------------------------------

def coerce_value(v: Any) -> Any:
    if not isinstance(v, str):
        return v
    s = v.strip()
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return v


def coerce_kwargs(kwargs: Dict[str, Any]) -> Dict[str, Any]:
    return {k: coerce_value(v) for k, v in kwargs.items()}
