"""Creation ops: zeros/ones/full/arange/eye/linspace.

Reference: ``src/operator/tensor/init_op*`` (TBV — SURVEY.md §2.2). These take
no tensor inputs; the eager frontend supplies ctx/dtype kwargs.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import dtype_np
from .registry import register


@register("_zeros", aliases=["zeros"], ndarray_inputs=[])
def _zeros(shape=(), dtype="float32", ctx=None):
    return jnp.zeros(shape, dtype=dtype_np(dtype))


@register("_ones", aliases=["ones"], ndarray_inputs=[])
def _ones(shape=(), dtype="float32", ctx=None):
    return jnp.ones(shape, dtype=dtype_np(dtype))


@register("_full", aliases=["full"], ndarray_inputs=[])
def _full(shape=(), value=0.0, dtype="float32", ctx=None):
    return jnp.full(shape, value, dtype=dtype_np(dtype))


@register("_arange", aliases=["arange"], ndarray_inputs=[])
def _arange(start=0, stop=None, step=1.0, repeat=1, infer_range=False, dtype="float32", ctx=None):
    r = jnp.arange(start, stop, step, dtype=dtype_np(dtype))
    if int(repeat) > 1:
        r = jnp.repeat(r, int(repeat))
    return r


@register("_linspace", aliases=["linspace"], ndarray_inputs=[])
def _linspace(start=0, stop=1, num=50, endpoint=True, dtype="float32", ctx=None):
    return jnp.linspace(start, stop, int(num), endpoint=bool(endpoint), dtype=dtype_np(dtype))


@register("_eye", aliases=["eye"], ndarray_inputs=[])
def _eye(N=0, M=0, k=0, dtype="float32", ctx=None):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=dtype_np(dtype))
