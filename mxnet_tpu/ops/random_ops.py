"""Random sampling operators.

Reference: ``src/operator/random/sample_op.*`` (``_random_*``: scalar-param
draws), ``multisample_op.*`` (``_sample_*``: tensor-param draws, one
distribution per input element), ``pdf_op.*`` (``_random_pdf_*``: density
evaluation, differentiable) and ``shuffle_op.cc`` (TBV — SURVEY.md §2.2
Random row). Draws come from the framework RNG stream (random.next_key) —
per-context curand states become splittable threefry keys, trace-safe under
jit and seeded by MXNET_SEED.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _key():
    from ..random import next_key

    return next_key()


def _dt(dtype):
    from ..base import dtype_np

    if dtype in (None, "None"):
        return jnp.float32
    return dtype_np(dtype)


def _shp(shape):
    if shape is None or shape == "None":
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


# ---------------------------------------------------------------------------
# _random_*: scalar-parameter draws
# ---------------------------------------------------------------------------

@register("_random_uniform", aliases=["random_uniform"], differentiable=False, ndarray_inputs=[])
def _random_uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None):
    return jax.random.uniform(_key(), _shp(shape), _dt(dtype), low, high)


@register("_random_normal", aliases=["random_normal"], differentiable=False, ndarray_inputs=[])
def _random_normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None):
    return loc + scale * jax.random.normal(_key(), _shp(shape), _dt(dtype))


@register("_random_gamma", aliases=["random_gamma"], differentiable=False, ndarray_inputs=[])
def _random_gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None):
    return beta * jax.random.gamma(_key(), alpha, _shp(shape), _dt(dtype))


@register("_random_exponential", aliases=["random_exponential"],
          differentiable=False, ndarray_inputs=[])
def _random_exponential(lam=1.0, shape=None, dtype="float32", ctx=None):
    return jax.random.exponential(_key(), _shp(shape), _dt(dtype)) / lam


@register("_random_poisson", aliases=["random_poisson"], differentiable=False, ndarray_inputs=[])
def _random_poisson(lam=1.0, shape=None, dtype="float32", ctx=None):
    return jax.random.poisson(_key(), lam, _shp(shape)).astype(_dt(dtype))


@register("_random_randint", aliases=["random_randint"], differentiable=False, ndarray_inputs=[])
def _random_randint(low=0, high=1, shape=None, dtype="int32", ctx=None):
    return jax.random.randint(_key(), _shp(shape), int(low), int(high),
                              _dt(dtype))


@register("_random_negative_binomial", aliases=["random_negative_binomial"],
          differentiable=False, ndarray_inputs=[])
def _random_negative_binomial(k=1, p=1.0, shape=None, dtype="float32",
                              ctx=None):
    # NB(k, p) = Poisson(lam) with lam ~ Gamma(k, (1-p)/p)
    lam = jax.random.gamma(_key(), float(k), _shp(shape)) * ((1 - p) / p)
    return jax.random.poisson(_key(), lam, _shp(shape)).astype(_dt(dtype))


@register("_random_generalized_negative_binomial",
          aliases=["random_generalized_negative_binomial"],
          differentiable=False, ndarray_inputs=[])
def _random_gen_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                  dtype="float32", ctx=None):
    if alpha == 0.0:
        return jax.random.poisson(_key(), mu, _shp(shape)).astype(_dt(dtype))
    k = 1.0 / alpha
    p = k / (k + mu)
    lam = jax.random.gamma(_key(), k, _shp(shape)) * ((1 - p) / p)
    return jax.random.poisson(_key(), lam, _shp(shape)).astype(_dt(dtype))


# ---------------------------------------------------------------------------
# _sample_*: tensor-parameter draws. Output shape = param.shape + shape —
# each input element parameterizes an independent distribution.
# ---------------------------------------------------------------------------

def _tensor_draw(draw, params, shape, dtype):
    shape = _shp(shape)
    out_shape = params[0].shape + shape
    broadcast = [jnp.broadcast_to(
        p.reshape(p.shape + (1,) * len(shape)), out_shape) for p in params]
    return draw(out_shape, *broadcast).astype(_dt(dtype))


@register("_sample_uniform", aliases=["sample_uniform"], differentiable=False, ndarray_inputs=['low', 'high'])
def _sample_uniform(low, high, shape=None, dtype="float32"):
    return _tensor_draw(
        lambda s, lo, hi: lo + (hi - lo) * jax.random.uniform(_key(), s),
        [low, high], shape, dtype)


@register("_sample_normal", aliases=["sample_normal"], differentiable=False, ndarray_inputs=['mu', 'sigma'])
def _sample_normal(mu, sigma, shape=None, dtype="float32"):
    return _tensor_draw(
        lambda s, m, sd: m + sd * jax.random.normal(_key(), s),
        [mu, sigma], shape, dtype)


@register("_sample_gamma", aliases=["sample_gamma"], differentiable=False, ndarray_inputs=['alpha', 'beta'])
def _sample_gamma(alpha, beta, shape=None, dtype="float32"):
    return _tensor_draw(
        lambda s, a, b: b * jax.random.gamma(_key(), a, s),
        [alpha, beta], shape, dtype)


@register("_sample_exponential", aliases=["sample_exponential"],
          differentiable=False, ndarray_inputs=['lam'])
def _sample_exponential(lam, shape=None, dtype="float32"):
    return _tensor_draw(
        lambda s, l: jax.random.exponential(_key(), s) / l,
        [lam], shape, dtype)


@register("_sample_poisson", aliases=["sample_poisson"], differentiable=False, ndarray_inputs=['lam'])
def _sample_poisson(lam, shape=None, dtype="float32"):
    return _tensor_draw(
        lambda s, l: jax.random.poisson(_key(), l, s).astype(jnp.float32),
        [lam], shape, dtype)


@register("_sample_negative_binomial", aliases=["sample_negative_binomial"],
          differentiable=False, ndarray_inputs=['k', 'p'])
def _sample_negative_binomial(k, p, shape=None, dtype="float32"):
    def draw(s, kk, pp):
        lam = jax.random.gamma(_key(), kk, s) * ((1 - pp) / pp)
        return jax.random.poisson(_key(), lam, s).astype(jnp.float32)
    return _tensor_draw(draw, [k, p], shape, dtype)


@register("_sample_generalized_negative_binomial",
          aliases=["sample_generalized_negative_binomial"],
          differentiable=False, ndarray_inputs=['mu', 'alpha'])
def _sample_gen_negative_binomial(mu, alpha, shape=None, dtype="float32"):
    def draw(s, m, a):
        k = 1.0 / jnp.maximum(a, 1e-12)
        p = k / (k + m)
        lam = jax.random.gamma(_key(), k, s) * ((1 - p) / p)
        pois = jax.random.poisson(_key(), jnp.broadcast_to(m, s), s)
        nb = jax.random.poisson(_key(), lam, s)
        return jnp.where(a <= 0, pois, nb).astype(jnp.float32)
    return _tensor_draw(draw, [mu, alpha], shape, dtype)


@register("_sample_multinomial", aliases=["sample_multinomial"],
          differentiable=False, ndarray_inputs=['data'])
def _sample_multinomial(data, shape=None, get_prob=False, dtype="int32"):
    """data (..., K) probabilities → draws of shape data.shape[:-1] + shape."""
    shape = _shp(shape)
    batch = data.shape[:-1]
    k = data.shape[-1]
    logits = jnp.log(jnp.maximum(data, 1e-30))
    n = 1
    for s in shape:
        n *= s
    flat = logits.reshape(-1, k)
    draws = jax.vmap(lambda lg, key: jax.random.categorical(key, lg, shape=(max(n, 1),)))(
        flat, jax.random.split(_key(), flat.shape[0]))
    out = draws.reshape(batch + (shape if shape else ()))
    out = out.astype(_dt(dtype))
    if get_prob:
        logp = jnp.take_along_axis(
            jnp.log_softmax(logits.reshape(-1, k), axis=-1)
            if hasattr(jnp, "log_softmax") else jax.nn.log_softmax(
                logits.reshape(-1, k), axis=-1),
            draws.astype(jnp.int32), axis=-1)
        return out, logp.reshape(out.shape)
    return out


@register("_shuffle", aliases=["shuffle"], differentiable=False, ndarray_inputs=['data'])
def _shuffle_op(data):
    """Shuffle along the first axis (reference shuffle_op.cc)."""
    return jax.random.permutation(_key(), data, axis=0)


# ---------------------------------------------------------------------------
# _random_pdf_*: density evaluation (differentiable w.r.t. sample + params)
# ---------------------------------------------------------------------------

@register("_random_pdf_uniform", aliases=["random_pdf_uniform"], ndarray_inputs=['sample', 'low', 'high'])
def _pdf_uniform(sample, low, high, is_log=False):
    low = low[..., None]
    high = high[..., None]
    inside = (sample >= low) & (sample <= high)
    pdf = jnp.where(inside, 1.0 / (high - low), 0.0)
    return jnp.log(jnp.maximum(pdf, 1e-30)) if is_log else pdf


@register("_random_pdf_normal", aliases=["random_pdf_normal"], ndarray_inputs=['sample', 'mu', 'sigma'])
def _pdf_normal(sample, mu, sigma, is_log=False):
    mu = mu[..., None]
    sigma = sigma[..., None]
    logp = (-0.5 * jnp.square((sample - mu) / sigma)
            - jnp.log(sigma * jnp.sqrt(2 * jnp.pi)))
    return logp if is_log else jnp.exp(logp)


@register("_random_pdf_gamma", aliases=["random_pdf_gamma"], ndarray_inputs=['sample', 'alpha', 'beta'])
def _pdf_gamma(sample, alpha, beta, is_log=False):
    a = alpha[..., None]
    b = 1.0 / beta[..., None]  # reference: beta is a scale parameter
    logp = (a * jnp.log(b) + (a - 1) * jnp.log(sample) - b * sample
            - jax.scipy.special.gammaln(a))
    return logp if is_log else jnp.exp(logp)


@register("_random_pdf_exponential", aliases=["random_pdf_exponential"], ndarray_inputs=['sample', 'lam'])
def _pdf_exponential(sample, lam, is_log=False):
    lam = lam[..., None]
    logp = jnp.log(lam) - lam * sample
    return logp if is_log else jnp.exp(logp)


@register("_random_pdf_poisson", aliases=["random_pdf_poisson"], ndarray_inputs=['sample', 'lam'])
def _pdf_poisson(sample, lam, is_log=False):
    lam = lam[..., None]
    logp = (sample * jnp.log(jnp.maximum(lam, 1e-30)) - lam
            - jax.scipy.special.gammaln(sample + 1))
    return logp if is_log else jnp.exp(logp)


@register("_random_pdf_negative_binomial",
          aliases=["random_pdf_negative_binomial"], ndarray_inputs=['sample', 'k', 'p'])
def _pdf_negative_binomial(sample, k, p, is_log=False):
    k = k[..., None]
    p = p[..., None]
    binln = (jax.scipy.special.gammaln(sample + k)
             - jax.scipy.special.gammaln(sample + 1)
             - jax.scipy.special.gammaln(k))
    logp = binln + k * jnp.log(p) + sample * jnp.log1p(-p)
    return logp if is_log else jnp.exp(logp)


@register("_random_pdf_generalized_negative_binomial",
          aliases=["random_pdf_generalized_negative_binomial"], ndarray_inputs=['sample', 'mu', 'alpha'])
def _pdf_gen_negative_binomial(sample, mu, alpha, is_log=False):
    mu = mu[..., None]
    alpha = alpha[..., None]
    k = 1.0 / alpha
    p = k / (k + mu)
    binln = (jax.scipy.special.gammaln(sample + k)
             - jax.scipy.special.gammaln(sample + 1)
             - jax.scipy.special.gammaln(k))
    logp = binln + k * jnp.log(p) + sample * jnp.log1p(-p)
    return logp if is_log else jnp.exp(logp)


@register("_random_pdf_dirichlet", aliases=["random_pdf_dirichlet"], ndarray_inputs=['sample', 'alpha'])
def _pdf_dirichlet(sample, alpha, is_log=False):
    a = alpha[..., None, :] if alpha.ndim == sample.ndim - 1 else alpha
    logp = (jnp.sum((a - 1) * jnp.log(sample), axis=-1)
            + jax.scipy.special.gammaln(jnp.sum(a, axis=-1))
            - jnp.sum(jax.scipy.special.gammaln(a), axis=-1))
    return logp if is_log else jnp.exp(logp)
