"""Indexing operator family: take/Embedding/one_hot/pick/gather_nd/scatter_nd.

Reference: ``src/operator/tensor/indexing_op*`` (TBV — SURVEY.md §2.2).
TPU note: all of these lower to XLA gather/scatter; Embedding's backward is a
scatter-add, which XLA handles natively (the reference needs AddTakeGrad CUDA
kernels for this).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


@register("take", ndarray_inputs=['a', 'indices'])
def _take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    ax = int(axis)
    if mode == "clip":
        idx = jnp.clip(idx, 0, a.shape[ax] - 1)
    elif mode == "wrap":
        idx = jnp.mod(idx, a.shape[ax])
    return jnp.take(a, idx, axis=ax)


@register("Embedding", ndarray_inputs=['data', 'weight'])
def _embedding(data, weight, input_dim=None, output_dim=None, dtype="float32", sparse_grad=False):
    idx = jnp.clip(data.astype(jnp.int32), 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


@register("one_hot", differentiable=False, ndarray_inputs=['indices'])
def _one_hot(indices, depth=None, on_value=1.0, off_value=0.0, dtype="float32"):
    from ..base import dtype_np

    d = int(depth)
    idx = indices.astype(jnp.int32)
    oh = jnp.arange(d, dtype=jnp.int32) == idx[..., None]
    return jnp.where(oh, on_value, off_value).astype(dtype_np(dtype))


@register("pick", ndarray_inputs=['data', 'index'])
def _pick(data, index, axis=-1, keepdims=False, mode="clip"):
    ax = int(axis) % data.ndim
    idx = index.astype(jnp.int32)
    if mode == "clip":
        idx = jnp.clip(idx, 0, data.shape[ax] - 1)
    else:
        idx = jnp.mod(idx, data.shape[ax])
    picked = jnp.take_along_axis(data, jnp.expand_dims(idx, ax), axis=ax)
    return picked if keepdims else jnp.squeeze(picked, axis=ax)


@register("gather_nd", ndarray_inputs=['data', 'indices'])
def _gather_nd(data, indices):
    # indices: (M, ...) — first axis indexes the leading M dims of data
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd", ndarray_inputs=['data', 'indices'])
def _scatter_nd(data, indices, shape=()):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("_scatter_set_nd", ndarray_inputs=['lhs', 'rhs', 'indices'])
def _scatter_set_nd(lhs, rhs, indices, shape=()):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return lhs.at[tuple(idx[i] for i in range(m))].set(rhs)


@register("_backward_gather_nd", aliases=["gather_nd_grad"], ndarray_inputs=['data', 'indices'])
def _gather_nd_accumulate(data, indices, shape=()):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].add(data)


@register("take_along_axis", ndarray_inputs=['data', 'indices'])
def _take_along_axis(data, indices, axis=0):
    return jnp.take_along_axis(data, indices.astype(jnp.int32), axis=int(axis))


@register("_contrib_boolean_mask", aliases=["boolean_mask"], differentiable=False, ndarray_inputs=['data', 'index'])
def _boolean_mask(data, index, axis=0):
    # Data-dependent output shape: returns padded-to-count semantics is not
    # possible eagerly-traced; eager path computes concretely (host sync).
    import numpy as np

    mask = np.asarray(index) != 0  # lint: disable=host-call-in-op
    return jnp.compress(mask, data, axis=int(axis))


@register("_contrib_index_copy", ndarray_inputs=['old', 'index', 'new'])
def _index_copy(old, index, new):
    return old.at[index.astype(jnp.int32)].set(new)


@register("_contrib_index_array", differentiable=False, ndarray_inputs=['data'])
def _index_array(data, axes=None):
    shape = data.shape
    axes = tuple(axes) if axes is not None else tuple(range(len(shape)))
    grids = jnp.meshgrid(*[jnp.arange(shape[a]) for a in axes], indexing="ij")
    return jnp.stack(grids, axis=-1).astype(jnp.int64 if False else jnp.int32)


@register("_contrib_allclose", differentiable=False, ndarray_inputs=['a', 'b'])
def _allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=True):
    return jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=bool(equal_nan)).astype(jnp.float32).reshape(1)
