"""CTC loss (reference ``src/operator/nn/ctc_loss.*`` wrapping warp-ctc /
cuDNN CTC — TBV, SURVEY.md §2.2).

TPU redesign: the forward algorithm over the blank-interleaved label lattice
runs as one ``lax.scan`` over time in log space — static shapes, fully
differentiable by jax.grad (no hand-written backward), batched by vmap.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []

_NEG = -1e30


def _logsumexp2(a, b):
    m = jnp.maximum(a, b)
    m_safe = jnp.where(m <= _NEG / 2, 0.0, m)
    out = m_safe + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe))
    return jnp.where(m <= _NEG / 2, _NEG, out)


def _logsumexp3(a, b, c):
    return _logsumexp2(_logsumexp2(a, b), c)


def _ctc_single(logprobs, labels, t_len, l_len, blank):
    """logprobs (T, C) log-softmax; labels (L,) int32; returns -log p(l|x)."""
    T, C = logprobs.shape
    L = labels.shape[0]
    S = 2 * L + 1
    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((S,), blank, jnp.int32)
    ext = ext.at[1::2].set(labels)
    pos = jnp.arange(S)
    valid_s = pos < 2 * l_len + 1
    # skip-transition allowed when ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.concatenate([jnp.full((2,), -1, jnp.int32), ext[:-2]])
    can_skip = (ext != blank) & (ext != ext_m2)

    alpha0 = jnp.full((S,), _NEG)
    alpha0 = alpha0.at[0].set(logprobs[0, blank])
    alpha0 = alpha0.at[1].set(jnp.where(l_len > 0, logprobs[0, ext[1]], _NEG))

    def step(alpha, t):
        lp = logprobs[t]
        a_prev = jnp.concatenate([jnp.array([_NEG]), alpha[:-1]])
        a_prev2 = jnp.concatenate([jnp.full((2,), _NEG), alpha[:-2]])
        a = _logsumexp3(alpha, a_prev,
                        jnp.where(can_skip, a_prev2, _NEG))
        a = a + lp[ext]
        a = jnp.where(valid_s, a, _NEG)
        # frozen past t_len: keep alpha unchanged for padded frames
        a = jnp.where(t < t_len, a, alpha)
        return a, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    end1 = alpha[jnp.maximum(2 * l_len - 1, 0)]
    end2 = alpha[2 * l_len]
    ll = _logsumexp2(jnp.where(l_len > 0, end1, _NEG), end2)
    return -ll


def _ctc_n_out(kwargs):
    return 2


@register("ctc_loss", aliases=["CTCLoss", "_contrib_ctc_loss", "_contrib_CTCLoss"],
          num_outputs=_ctc_n_out, ndarray_inputs=['data', 'label'])
def _ctc_loss(data, label, data_lengths=None, label_lengths=None,
              use_data_lengths=False, use_label_lengths=False,
              blank_label="first", _pad_value=0):
    """data (T, B, C) unnormalized activations; label (B, L).

    Returns (loss (B,), log_softmax(data)) — the reference emits the
    (gradient-carrying) normalized activations as the second output.
    Labels: with blank_label="first", blank is class 0 and labels are
    1-based offsets; "last" puts blank at C-1 with 0-based labels.
    When use_label_lengths is False, padding value (0 for "first",
    -1 for "last") terminates each label row.
    """
    T, B, C = data.shape
    logprobs = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    lab = label.astype(jnp.int32)

    if blank_label == "first":
        blank = 0
        pad = jnp.int32(_pad_value)
        eff = jnp.where(lab == pad, -1, lab)  # padding → sentinel
    else:
        blank = C - 1
        pad = jnp.int32(-1)
        eff = lab

    if use_label_lengths and label_lengths is not None:
        l_lens = label_lengths.astype(jnp.int32)
    else:
        l_lens = jnp.sum((eff >= 0).astype(jnp.int32), axis=-1)
    if use_data_lengths and data_lengths is not None:
        t_lens = data_lengths.astype(jnp.int32)
    else:
        t_lens = jnp.full((B,), T, jnp.int32)

    eff = jnp.maximum(eff, 0)  # safe index; masked out by l_lens anyway

    losses = jax.vmap(_ctc_single, in_axes=(1, 0, 0, 0, None))(
        logprobs, eff, t_lens, l_lens, blank)
    return losses.astype(data.dtype), logprobs.astype(data.dtype)
