"""Control-flow operators: foreach / while_loop / cond.

Reference: ``src/operator/control_flow.cc`` (first-class ops taking
subgraphs — TBV, SURVEY.md §2.2). The natural TPU fit: ``lax.scan`` /
``lax.while_loop`` / ``lax.cond`` — these APIs take Python callables over
NDArrays (matching the reference's Python-facing contrib API
``mx.nd.contrib.foreach(body, data, init_states)``) and trace them into a
single fused XLA loop, eager or under jit alike.
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["foreach", "while_loop", "cond"]


def _wrap(v):
    from ..ndarray import NDArray

    return NDArray(v) if not isinstance(v, NDArray) else v


def _unwrap(v):
    from ..ndarray import NDArray

    if isinstance(v, NDArray):
        return v._data
    if isinstance(v, (list, tuple)):
        return [_unwrap(x) for x in v]
    return v


def foreach(body: Callable, data, init_states):
    """Scan ``body(item, states) -> (out, new_states)`` over axis 0 of data.

    Matches reference ``mx.nd.contrib.foreach`` semantics; compiles to one
    ``lax.scan`` (the fused-RNN building block).
    """
    from ..ndarray import NDArray
    from ..ndarray.ndarray import invoke_fn

    single_data = not isinstance(data, (list, tuple))
    single_state = not isinstance(init_states, (list, tuple))
    data_list = [data] if single_data else list(data)
    state_list = [init_states] if single_state else list(init_states)

    out_is_single = [None]  # discovered during trace

    def fn(*vals):
        xs = vals[:len(data_list)]
        st = list(vals[len(data_list):])

        def step(carry, x):
            x_nd = [NDArray(v) for v in (x if isinstance(x, tuple) else (x,))]
            c_nd = [NDArray(v) for v in carry]
            out, new_states = body(x_nd[0] if single_data else x_nd,
                                   c_nd[0] if single_state else c_nd)
            outs = [out] if not isinstance(out, (list, tuple)) else list(out)
            out_is_single[0] = not isinstance(out, (list, tuple))
            ns = [new_states] if not isinstance(new_states, (list, tuple)) \
                else list(new_states)
            return tuple(_unwrap(n) for n in ns), \
                tuple(_unwrap(o) for o in outs)

        carry, ys = lax.scan(step, tuple(st),
                             xs[0] if len(xs) == 1 else tuple(xs))
        return tuple(ys) + tuple(carry)

    n_data = len(data_list)
    results = invoke_fn(lambda *v: fn(*v), data_list + state_list)
    if not isinstance(results, tuple):
        results = (results,)
    n_states = len(state_list)
    n_out = len(results) - n_states
    outs = list(results[:n_out])
    states = list(results[n_out:])
    out = outs[0] if (out_is_single[0] or n_out == 1) else outs
    st = states[0] if single_state else states
    return out, st


def while_loop(cond_fn: Callable, func: Callable, loop_vars,
               max_iterations=None):
    """Reference ``mx.nd.contrib.while_loop(cond, func, loop_vars,
    max_iterations)``. Returns (stacked_outputs, final_loop_vars).

    XLA needs static shapes: outputs are collected into a ``max_iterations``
    buffer with an iteration-count mask (the reference pads identically).
    """
    from ..ndarray import NDArray
    from ..ndarray.ndarray import invoke_fn

    assert max_iterations is not None and max_iterations > 0, \
        "max_iterations is required (static shapes on TPU)"
    single = not isinstance(loop_vars, (list, tuple))
    lv = [loop_vars] if single else list(loop_vars)
    out_meta = {}

    def fn(*vals):
        # probe one step to learn the output structure
        probe_out, _ = func([NDArray(v) for v in vals] if not single
                            else NDArray(vals[0]))
        probe_outs = [probe_out] if not isinstance(probe_out, (list, tuple)) \
            else list(probe_out)
        out_meta["single"] = not isinstance(probe_out, (list, tuple))
        bufs = tuple(jnp.zeros((max_iterations,) + tuple(_unwrap(o).shape),
                               _unwrap(o).dtype) for o in probe_outs)

        def cond_wrap(state):
            i, vars_, bufs_ = state
            c = cond_fn([NDArray(v) for v in vars_] if not single
                        else NDArray(vars_[0]))
            return jnp.logical_and(i < max_iterations,
                                   _unwrap(c).reshape(()).astype(bool))

        def body_wrap(state):
            i, vars_, bufs_ = state
            nd_vars = [NDArray(v) for v in vars_] if not single \
                else NDArray(vars_[0])
            out, new_vars = func(nd_vars)
            outs = [out] if not isinstance(out, (list, tuple)) else list(out)
            nv = [new_vars] if not isinstance(new_vars, (list, tuple)) \
                else list(new_vars)
            new_bufs = tuple(b.at[i].set(_unwrap(o))
                             for b, o in zip(bufs_, outs))
            return (i + 1, tuple(_unwrap(v) for v in nv), new_bufs)

        i, final_vars, final_bufs = lax.while_loop(
            cond_wrap, body_wrap, (jnp.int32(0), tuple(vals), bufs))
        return final_bufs + final_vars + (i,)

    results = invoke_fn(lambda *v: fn(*v), lv)
    if not isinstance(results, tuple):
        results = (results,)
    n_vars = len(lv)
    n_out = len(results) - n_vars - 1
    outs = list(results[:n_out])
    final_vars = list(results[n_out:n_out + n_vars])
    out = outs[0] if (out_meta.get("single") or n_out == 1) else outs
    fv = final_vars[0] if single else final_vars
    return out, fv


def cond(pred_fn_or_val, then_func: Callable, else_func: Callable, inputs=None):
    """Reference ``mx.nd.contrib.cond(pred, then_func, else_func, inputs)``.

    pred may be a callable over inputs or a boolean NDArray/scalar.
    """
    from ..ndarray import NDArray
    from ..ndarray.ndarray import invoke_fn

    single = not isinstance(inputs, (list, tuple)) and inputs is not None
    ins = [] if inputs is None else ([inputs] if single else list(inputs))

    def fn(*vals):
        nd_ins = [NDArray(v) for v in vals]
        arg = (nd_ins[0] if single else nd_ins) if ins else None

        if callable(pred_fn_or_val):
            p = _unwrap(pred_fn_or_val(arg)).reshape(()).astype(bool)
        else:
            p = _unwrap(pred_fn_or_val)
            p = jnp.asarray(p).reshape(()).astype(bool)

        def then_branch(vs):
            r = then_func(arg)
            rs = [r] if not isinstance(r, (list, tuple)) else list(r)
            return tuple(_unwrap(x) for x in rs)

        def else_branch(vs):
            r = else_func(arg)
            rs = [r] if not isinstance(r, (list, tuple)) else list(r)
            return tuple(_unwrap(x) for x in rs)

        return lax.cond(p, then_branch, else_branch, tuple(vals))

    result = invoke_fn(lambda *v: fn(*v), ins)
    if isinstance(result, tuple) and len(result) == 1:
        return result[0]
    return result
